"""Dump the bench model's optimized train-step HLO + cost summary.

Run from the repo root: ``python -m tools.dump_hlo``.  Writes the HLO
text to /tmp/hlo_opt.txt and prints the backend cost rows.

The HLO comes through the ONE extraction path
(``tools/graftaudit/extract.py``): fit() populates the trace cache, and
the recorded train-step call is re-lowered via ``audit_lower`` — the
program production actually ran, with its declared donation, not a
hand-reconstructed ``.lower()`` with a fresh RNG key.
"""
import json

import jax.numpy as jnp

from deeplearning4j_tpu.models import available_bench_model
from tools.graftaudit.extract import iter_trace_cache_hlo

model, (x, y) = available_bench_model(batch=256, image=224)
x, y = jnp.asarray(x), jnp.asarray(y)
model.fit(x, y)                       # records the real train-step call
exs = list(iter_trace_cache_hlo(kinds=("train_step",)))
assert exs, "no train_step in the trace cache after fit()"
ex = exs[-1]
with open("/tmp/hlo_opt.txt", "w") as f:
    f.write(ex.hlo_text)
ca = ex.cost_analysis()
flops = ca.get("flops", 0)
print(json.dumps({k: v for k, v in ca.items()
                  if k in ("flops", "bytes accessed", "optimal_seconds",
                           "bytes accessed0{}", "bytes accessedout{}")},
                 indent=0))
print("flops/step TFLOP:", flops / 1e12)
