import jax, jax.numpy as jnp
from deeplearning4j_tpu.models import available_bench_model

model, (x, y) = available_bench_model(batch=256, image=224)
x, y = jnp.asarray(x), jnp.asarray(y)
model.fit(x, y)
step = model._get_jitted("train_step")
model._rng, key = jax.random.split(model._rng)
lowered = step.lower(model.params, model.state, model.opt_state, key,
                     [x], [y], None, None)
compiled = lowered.compile()
with open("/tmp/hlo_opt.txt", "w") as f:
    f.write(compiled.as_text())
ca = compiled.cost_analysis()
if isinstance(ca, list): ca = ca[0]
import json
flops = ca.get("flops", 0)
print(json.dumps({k: v for k, v in ca.items()
                  if k in ("flops", "bytes accessed", "optimal_seconds",
                           "bytes accessed0{}", "bytes accessedout{}")},
                 indent=0))
print("flops/step TFLOP:", flops / 1e12)
