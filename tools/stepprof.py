"""stepprof CLI: capture a step-profiler window and commit the artifact.

Run from the repo root: ``python -m tools.stepprof``.  Drives a short fit
of the canonical dense MLP (the exact net behind the ``train_step[dense]``
graftaudit card, ``tools/graftaudit/canonical.py``) with the
:class:`~deeplearning4j_tpu.observability.profiler.StepProfiler` armed,
then emits:

1. a checksummed Chrome-trace artifact (``stepprof-<pid>-<ts>.json``,
   loadable in chrome://tracing / Perfetto) via the atomic-commit path —
   the same artifact ``GET /debug/profile?dump=1`` serves from a live
   trainer; and
2. a text phase table — mean seconds + share of step wall per phase over
   steady steps, with the sampled-fence coverage check, MFU (card flops
   over the fenced device slice), and the live-bytes watermark vs the
   AX008 budget.

Replaces the round-2 ``profile_capture.py`` Xprof-glob script: Xprof
answers "which op is slow on the device"; this answers the prior
question — "is the time even ON the device" — without chip tooling.

Options::

  --steps N     minibatches per epoch          (default 48)
  --epochs E    epochs                         (default 2)
  --sample N    fence cadence (1 = every step) (default 8)
  --program P   program label for card/budget  (default train_step[dense])
  --out DIR     artifact directory             (default .)
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def _fmt_s(v) -> str:
    return "      —" if v is None else f"{v * 1e3:7.3f}"


def phase_table(summary: dict) -> str:
    """Render a phase_summary() dict as the text table the runbook shows."""
    from deeplearning4j_tpu.observability.profiler import PHASES
    lines = [f"{'phase':<12} {'mean ms':>8} {'share':>7}",
             "-" * 29]
    mean = summary.get("mean_phase_s") or {}
    share = summary.get("phase_share") or {}
    for name in PHASES:
        lines.append(f"{name:<12} {_fmt_s(mean.get(name)):>8} "
                     f"{share.get(name, 0.0):>6.1%}")
    lines.append("-" * 29)
    lines.append(f"{'step wall':<12} {_fmt_s(summary.get('mean_wall_s')):>8} "
                 f"{'over':>4} {summary['steps']} steps")
    cov = summary.get("sampled_coverage")
    if cov is not None:
        lines.append(f"sampled coverage {cov:.1%} of wall attributed "
                     f"({summary.get('sampled_steps', 0)} fenced steps)")
    if summary.get("mean_mfu") is not None:
        lines.append(f"MFU {summary['mean_mfu']:.2%} (card flops / fenced "
                     "device slice / peak)")
    if summary.get("max_budget_ratio") is not None:
        lines.append(f"live-bytes watermark {summary['max_budget_ratio']:.1%} "
                     "of AX008 peak_live_bytes budget")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.stepprof",
        description="short canonical fit -> Chrome trace + phase table")
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--sample", type=int, default=8)
    ap.add_argument("--program", default="train_step[dense]")
    ap.add_argument("--out", default=".")
    args = ap.parse_args(argv)

    # env, not API: the capture must exercise the exact default-on wiring
    # a production fit runs (fit() -> step_profiler_for -> env knobs)
    os.environ["DL4J_TPU_STEPPROF"] = "1"
    os.environ["DL4J_TPU_STEPPROF_SAMPLE"] = str(max(1, args.sample))
    os.environ["DL4J_TPU_STEPPROF_PROGRAM"] = args.program

    from deeplearning4j_tpu.observability.profiler import (CHANNEL,
                                                           chrome_trace,
                                                           dump_chrome_trace,
                                                           phase_summary)
    from deeplearning4j_tpu.observability.recorder import (FlightRecorder,
                                                           set_flight_recorder)
    from tools.graftaudit.canonical import _batch, _mlp

    # a dedicated recorder: the window holds exactly this capture's steps
    rec = FlightRecorder(capacity=max(256, args.steps * args.epochs + 16))
    prev = set_flight_recorder(rec)
    try:
        net = _mlp()
        x, y = _batch()
        net.fit([(x, y)] * args.steps, epochs=args.epochs)
    finally:
        set_flight_recorder(prev)

    records = rec.channel(CHANNEL).items()
    if not records:
        print("no profile records captured (is DL4J_TPU_STEPPROF forced "
              "off?)", file=sys.stderr)
        return 1
    summary = phase_summary(records)
    path = dump_chrome_trace(directory=args.out, records=records)
    doc = chrome_trace(records)
    print(phase_table(summary))
    print(f"\ntrace: {path} ({len(doc['traceEvents'])} events — load in "
          "chrome://tracing or ui.perfetto.dev)")
    print(json.dumps({"program": args.program,
                      "steps": summary.get("steps"),
                      "sampled_steps": summary.get("sampled_steps"),
                      "mean_wall_ms": round(
                          (summary.get("mean_wall_s") or 0) * 1e3, 3),
                      "trace": path}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
