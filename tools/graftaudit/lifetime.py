"""Buffer-lifetime / donation solver: the exact answer AX005 estimates.

AX005's size-threshold heuristic asks "is this dead-after-call argument
big enough to care about?".  This module computes what is *actually*
safe and useful to donate, per compiled program, from three independent
sources of truth:

1. **jaxpr def-use** (reusing ``ir.py``'s walkers): per-argument
   last-use over the top-level equation order — an argument consumed
   only by equation 3 of 40 is garbage for the remaining 37, whether or
   not anyone declared it donatable.
2. **Output aliasing compatibility**: donation only pays when XLA can
   alias the donated input buffer to an output of identical
   shape/dtype (the train step's fresh params reuse the old params'
   buffers leaf for leaf).  The solver injectively matches each
   candidate argument's array leaves against the program's unclaimed
   output leaves; an argument with no full match (serve's padded batch:
   no output shares its shape) is dead but not *usefully* donatable.
3. **Observed caller liveness** (``InstrumentedJit.audit_liveness``):
   weakref probes recorded at call time show whether the caller's
   bindings were still alive at audit time.  ``"dead"`` upgrades an
   argument into the candidate set even without a kind contract;
   ``"live"`` vetoes donation even when the contract says dead (a
   device-resident dataset re-fed every epoch must never be donated);
   ``"unknown"`` falls back to the ``DEAD_AFTER_CALL`` kind contract.

The intersection — caller-dead AND fully alias-matched — is the
*maximal safe donation set*, AX007's exact yardstick against
``donate_argnums``.  The same def-use pass yields a peak-live-bytes
estimate (live-range interval sweep over the eqn order, sub-jaxpr
scopes contributing their internal peaks at the enclosing equation —
scan carries included), AX008's subject.

Sharding note: jaxpr avals carry no sharding, so leaf matching is on
(shape, dtype).  For the programs this runs on, params/opt-state in-
and outputs share their shardings by construction (the same
NamedSharding tree threads through), so (shape, dtype) equality is the
honest portable criterion; a sharding-mismatched alias would surface as
a compile-time donation warning long before this analysis.
"""
from __future__ import annotations

from collections import Counter
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import ir as IR

__all__ = ["ArgLifetime", "LifetimeInfo", "solve_lifetime",
           "peak_live_bytes", "spec_variant_group"]


@dataclass(frozen=True)
class ArgLifetime:
    """Lifetime facts for ONE positional argument of one program."""
    argnum: int
    bytes: int                  # total array-leaf bytes of the binding
    leaves: int                 # array leaf count (0 = pure scalar arg)
    last_use: int               # top-level eqn index of the last read;
                                # -1 = never read, len(eqns) = returned
    returned: bool              # some leaf IS a program output (alias)
    matched: bool               # every array leaf found a compatible
                                # unclaimed output leaf (donation pays)
    caller: str                 # "dead" | "live" | "unknown" (observed)
    contract_dead: bool         # the kind contract says dead-after-call
    donatable: bool             # in the maximal safe donation set


@dataclass(frozen=True)
class LifetimeInfo:
    args: Tuple[ArgLifetime, ...]
    maximal_donation: Tuple[int, ...]
    peak_live_bytes: int


def _arg_leaf_avals(jaxpr, spec) -> List[List[Any]]:
    """Invar avals grouped per positional argument.

    ``make_jaxpr`` flattens ``(args, kwargs)`` in order, so the first
    ``len(tree_leaves(args[i]))`` invars belong to arg 0, and so on;
    kwargs leaves (if any) trail and are not donation candidates
    (jax only donates positional argnums)."""
    import jax

    args, _kwargs = spec
    groups: List[List[Any]] = []
    pos = 0
    invars = list(jaxpr.invars)
    for a in args:
        n = len(jax.tree_util.tree_leaves(a))
        groups.append([v.aval for v in invars[pos:pos + n]])
        pos += n
    return groups


def _aval_key(aval) -> Optional[Tuple]:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return None
    return (tuple(shape), str(dtype))


def _last_uses(jaxpr) -> Dict[Any, int]:
    """Top-level last-use position per var: eqn index, or ``len(eqns)``
    for vars read by the program's outputs.  Sub-jaxpr reads count at
    their enclosing equation (the operand list of the scan/pjit eqn)."""
    last: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for iv in eqn.invars:
            if hasattr(iv, "val"):
                continue                      # Literal
            last[iv] = i
    n = len(jaxpr.eqns)
    for ov in jaxpr.outvars:
        if hasattr(ov, "val"):
            continue
        last[ov] = n
    return last


def peak_live_bytes(jaxpr) -> int:
    """Estimated peak of simultaneously-live buffer bytes over the
    top-level equation order: each var's bytes are live from its
    defining equation through its last use; a sub-jaxpr (scan body,
    pjit call, cond branch) contributes its own internal peak at the
    enclosing equation's position, so scan carries and loop-internal
    temporaries count where they actually coexist with the outer live
    set.  An estimate — XLA's fusion/rematerialization moves the real
    number both ways — but a *monotone* one: a change that doubles the
    live params or forgets a donation moves it the same direction at
    both fidelities."""
    return _scope_peak(jaxpr, count_invars=True)


def _scope_peak(jaxpr, count_invars: bool) -> int:
    eqns = list(jaxpr.eqns)
    last = _last_uses(jaxpr)
    add: Dict[int, int] = {}
    remove: Dict[int, int] = {}

    def _alloc(v, def_pos: int) -> None:
        b = IR.aval_bytes(v)
        if b <= 0:
            return
        add[def_pos] = add.get(def_pos, 0) + b
        # never-read vars die where they were defined
        remove[last.get(v, def_pos)] = \
            remove.get(last.get(v, def_pos), 0) + b

    if count_invars:
        for v in jaxpr.invars:
            _alloc(v, -1)
    for v in getattr(jaxpr, "constvars", ()):
        _alloc(v, -1)
    for i, eqn in enumerate(eqns):
        for ov in eqn.outvars:
            _alloc(ov, i)

    live = add.get(-1, 0)
    peak = live
    for i, eqn in enumerate(eqns):
        live += add.get(i, 0)
        sub_extra = 0
        subs: List = []
        for v in eqn.params.values():
            IR._sub_jaxprs(v, subs)
        for sub in subs:
            # sub invars map to outer operands already counted here
            sub_extra = max(sub_extra,
                            _scope_peak(sub, count_invars=False))
        peak = max(peak, live + sub_extra)
        live -= remove.get(i, 0)
    return peak


def solve_lifetime(jaxpr, spec, donate: Sequence[int] = (),
                   entry: Any = None,
                   contract_dead: Sequence[int] = ()) -> LifetimeInfo:
    """Solve per-argument lifetimes and the maximal safe donation set
    for one program (see module docstring for the three fact sources).

    ``entry`` is the program's ``InstrumentedJit`` (or anything with an
    ``audit_liveness(spec)``); ``contract_dead`` the kind contract
    (``rules.DEAD_AFTER_CALL``) used when no liveness was observed."""
    groups = _arg_leaf_avals(jaxpr, spec)
    last = _last_uses(jaxpr)
    out_ids = {id(v) for v in jaxpr.outvars if not hasattr(v, "val")}

    liveness: Tuple[str, ...] = ()
    if entry is not None:
        try:
            liveness = tuple(entry.audit_liveness(spec))
        except Exception:
            liveness = ()

    # output leaf pool for aliasing compatibility (multiset of
    # shape/dtype keys; each output leaf claimable once)
    pool: Counter = Counter()
    for ov in jaxpr.outvars:
        if hasattr(ov, "val"):
            continue
        key = _aval_key(getattr(ov, "aval", None))
        if key is not None:
            pool[key] += 1

    # provisional per-arg facts, then greedy matching biggest-first so
    # the params tree claims its outputs before a same-shaped small arg
    facts: List[Dict[str, Any]] = []
    invar_pos = 0
    invars = list(jaxpr.invars)
    for argnum, avals in enumerate(groups):
        my_invars = invars[invar_pos:invar_pos + len(avals)]
        invar_pos += len(avals)
        arr_keys = [k for k in (_aval_key(a) for a in avals)
                    if k is not None]
        size = sum(IR.aval_bytes(a) for a in avals)
        uses = [last.get(v, -1) for v in my_invars]
        status = liveness[argnum] if argnum < len(liveness) else "unknown"
        in_contract = argnum in tuple(contract_dead)
        facts.append({
            "argnum": argnum, "bytes": size, "leaves": len(arr_keys),
            "last_use": max(uses) if uses else -1,
            "returned": any(id(v) in out_ids for v in my_invars),
            "need": Counter(arr_keys),
            "caller": status, "contract_dead": in_contract,
            "dead": status == "dead"
            or (status == "unknown" and in_contract),
        })

    for f in sorted(facts, key=lambda f: -f["bytes"]):
        need = f["need"]
        f["matched"] = bool(need) and f["dead"] and \
            all(pool[k] >= c for k, c in need.items())
        if f["matched"]:
            pool -= need

    args = tuple(ArgLifetime(
        argnum=f["argnum"], bytes=f["bytes"], leaves=f["leaves"],
        last_use=f["last_use"], returned=f["returned"],
        matched=bool(f.get("matched")), caller=f["caller"],
        contract_dead=f["contract_dead"],
        donatable=f["dead"] and bool(f.get("matched")) and f["bytes"] > 0,
    ) for f in facts)
    return LifetimeInfo(
        args=args,
        maximal_donation=tuple(a.argnum for a in args if a.donatable),
        peak_live_bytes=peak_live_bytes(jaxpr))


# ------------------------------------------------------- variant grouping
def _variant_key(spec) -> Tuple:
    """Spec identity with Python-scalar values and weak-typed 0-d leaves
    erased: two captured specs with equal variant keys but distinct
    capture keys compile (or at least re-dispatch) the SAME program
    modulo a scalar's value/weak-type — the avoidable variant explosion
    AX009 exists to flag.  0-d ShapeDtypeStructs collapse into the same
    bucket as raw Python scalars so ``1.0`` vs ``np.float32(1.0)``
    (a genuine retrace: weak vs committed dtype) is caught too."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(spec)
    norm: List[Tuple] = []
    for leaf in leaves:
        if isinstance(leaf, jax.ShapeDtypeStruct) and \
                tuple(leaf.shape) != ():
            sh = getattr(leaf, "sharding", None)
            if sh is not None:
                norm.append(("sds", tuple(leaf.shape), str(leaf.dtype),
                             str(sh.spec),
                             tuple(sh.mesh.shape.items())))
            else:
                norm.append(("sds", tuple(leaf.shape), str(leaf.dtype),
                             None, None))
        else:
            norm.append(("scalar",))
    return (treedef, tuple(norm))


def spec_variant_group(entry, spec) -> Tuple[int, List[str]]:
    """How many of ``entry``'s captured specs differ from ``spec`` only
    by Python-scalar value / weak-typed 0-d leaves, and the repr of the
    churning leaves (for the finding message).  ``(1, [])`` = no churn."""
    import jax

    try:
        mine = _variant_key(spec)
        variants = [s for s in entry.audit_specs()
                    if _variant_key(s) == mine]
    except Exception:
        return (1, [])
    if len(variants) <= 1:
        return (1, [])
    churn: List[str] = []
    rows = [jax.tree_util.tree_flatten(s)[0] for s in variants]
    for pos in range(min(len(r) for r in rows)):
        vals = {repr(r[pos]) for r in rows}
        if len(vals) > 1:
            churn.append(f"arg leaf {pos}: {sorted(vals)[:4]}")
    return (len(variants), churn)
