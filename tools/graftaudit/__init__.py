"""graftaudit — IR-level static analysis of the compiled program set.

graftlint's AST rules see Python source; every performance and
correctness contract this framework actually ships — the GSPMD-derived
reduce-scatter/all-gather layout of the ZeRO-3 step (arxiv 2004.13336),
bf16 compute against f32 masters, donated serve/decode buffers, zero
steady-state host syncs — lives in the *compiled program*, which no AST
rule can see (the whole-program-IR argument of arxiv 1810.09868).
graftaudit closes that gap with two IR phases over the REAL production
programs, reached through the process-global trace cache
(``nn/compile_cache``: every ``InstrumentedJit`` records the abstract
spec of the calls that defined its compiled variants):

* **jaxpr phase** (``ir.py``): the exact functional trace — dtype
  promotion origins (AX001), precision-policy leaks and cast churn
  (AX002), host callbacks (AX004), donation misses (AX005), oversized
  broadcasts (AX006).
* **partitioned-HLO phase** (``hlo.py``): collectives only exist after
  GSPMD runs, so the census + layout guard (AX003) parses the compiled
  executable's HLO.

Conventions are graftlint's: text/json/sarif output, justified
suppressions (the manifest's inline pragmas), a ratcheted empty
baseline, and a canonical-program-set CI gate (``tests/test_audit.py``)
plus committed per-program cards (``cards/``) for PR-over-PR IR diffs.

Usage:
    python -m tools.graftaudit                      # audit canonical set
    python -m tools.graftaudit --format json|sarif
    python -m tools.graftaudit --write-cards        # refresh cards/
    python -m tools.graftaudit --programs zero3     # subset

Library API:
    from tools.graftaudit import (AuditProgram, AuditConfig, Suppression,
                                  audit_programs, build_canonical)
"""
from __future__ import annotations

from .audit import (AuditConfig, AuditProgram, AuditResult, ProgramIR,
                    Suppression, analyze_program, audit_programs,
                    programs_from_trace_cache)
from .cards import build_card, card_filename, load_card, write_cards
from .extract import ExtractedHLO, extract_hlo, iter_trace_cache_hlo
from .rules import AUDIT_RULES, AUDIT_RULE_DOCS, DEAD_AFTER_CALL

__all__ = [
    "AuditConfig", "AuditProgram", "AuditResult", "ProgramIR",
    "Suppression", "analyze_program", "audit_programs",
    "programs_from_trace_cache", "build_card", "card_filename",
    "load_card", "write_cards", "ExtractedHLO", "extract_hlo",
    "iter_trace_cache_hlo", "AUDIT_RULES", "AUDIT_RULE_DOCS",
    "DEAD_AFTER_CALL",
]
