"""The differential gate: fresh audit vs committed budgets.

``python -m tools.graftaudit --diff-cards`` rebuilds the canonical set,
audits it under ``CANONICAL_CONFIG`` (which arms AX010 card-drift
against ``cards/`` and AX008 peak-live ceilings), and then runs THIS
module's budget checks: per-program ceilings from ``budgets.json`` on
collective bytes/counts, XLA temp bytes, dtype-histogram hazard
entries, host-callback count, and the minimum donation map.  Every
breach is a finding (AX008 for numeric ceilings, AX007 for a dropped
budgeted donation) so the four classic silent IR regressions — an f64
escape, a lost donation, a grown all-reduce, a new ``pure_callback`` —
each fail the gate with the rule that names the bug.

Ratchet semantics mirror the graftlint baseline: ceilings may only be
raised in a PR that justifies the raise (budgets.json carries the
comment), and a budget entry for a program that no longer exists (and
is not an explicit host skip) is STALE — exit 2, delete it — so an
allowance never lies in wait to absorb a future regression.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..graftlint.core import Finding
from . import ir as IR
from .audit import ProgramIR
from .rules import _CALLBACK_PRIMS

__all__ = ["load_budgets", "check_budgets", "budget_entry"]


def load_budgets(path: str) -> Dict:
    """Parse budgets.json; raises (never returns empty) on a missing or
    malformed file — the gate must fail loudly, not run budget-less."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data.get("programs"), dict) or not data["programs"]:
        raise ValueError(f"{path}: no 'programs' budget map")
    return data


def _finding(name: str, code: str, msg: str) -> Finding:
    return Finding(path=name, line=0, col=0, rule=code, message=msg)


def _census_totals(ir_prog: ProgramIR) -> Tuple[int, int]:
    by = sum(int(row.get("bytes", 0)) for row in ir_prog.census.values())
    ct = sum(int(row.get("count", 0)) for row in ir_prog.census.values())
    return by, ct


def _callback_count(ir_prog: ProgramIR) -> int:
    return sum(1 for e in IR.iter_eqns(ir_prog.jaxpr)
               if e.primitive.name in _CALLBACK_PRIMS)


def _check_one(ir_prog: ProgramIR, row: Dict) -> List[Finding]:
    out: List[Finding] = []
    name = ir_prog.name

    def over(metric: str, value, ceiling) -> None:
        out.append(_finding(
            name, "AX008",
            f"budget breach: {metric} {value} exceeds the ceiling "
            f"{ceiling} in budgets.json — fix the regression or raise "
            "the ceiling with a justifying comment (ratchet: raises "
            "need review, never silence)"))

    cbytes, ccount = _census_totals(ir_prog)
    if row.get("collective_bytes") is not None and \
            cbytes > int(row["collective_bytes"]):
        over("collective bytes", cbytes, int(row["collective_bytes"]))
    if row.get("collective_count") is not None and \
            ccount > int(row["collective_count"]):
        over("collective count", ccount, int(row["collective_count"]))
    if row.get("temp_bytes") is not None and \
            ir_prog.temp_bytes is not None and \
            ir_prog.temp_bytes > int(row["temp_bytes"]):
        over("XLA temp bytes", ir_prog.temp_bytes, int(row["temp_bytes"]))
    if row.get("callbacks") is not None:
        n = _callback_count(ir_prog)
        if n > int(row["callbacks"]):
            over("host callback eqns", n, int(row["callbacks"]))
    dtype_ceilings = row.get("dtypes") or {}
    if dtype_ceilings:
        hist = IR.dtype_histogram(ir_prog.jaxpr)
        for dt, ceiling in sorted(dtype_ceilings.items()):
            n = int(hist.get(dt, 0))
            if n > int(ceiling):
                over(f"'{dt}' eqn outputs", n, int(ceiling))
    for argnum in row.get("donation_min", ()):
        if int(argnum) not in ir_prog.donate:
            out.append(_finding(
                name, "AX007",
                f"budgeted donation dropped: arg {argnum} is in "
                "budgets.json donation_min but no longer in "
                f"donate_argnums{tuple(ir_prog.donate)} — the input/"
                "output aliasing this program was reviewed with is gone"))
    return out


def check_budgets(irs: Sequence[ProgramIR], budgets: Dict,
                  skipped: Optional[Dict[str, str]] = None
                  ) -> Tuple[List[Finding], List[str]]:
    """Budget checks over a fresh audit.  Returns ``(findings,
    stale_budget_names)``; a budgeted program absent from ``irs`` is
    stale UNLESS it is in ``skipped`` (the host explicitly could not
    build it — reduced coverage, recorded, not a dead entry)."""
    skipped = skipped or {}
    by_name = {ir_prog.name: ir_prog for ir_prog in irs}
    findings: List[Finding] = []
    stale: List[str] = []
    for name, row in sorted(budgets.get("programs", {}).items()):
        ir_prog = by_name.get(name)
        if ir_prog is None:
            if name not in skipped:
                stale.append(name)
            continue
        findings.extend(_check_one(ir_prog, row))
    findings.sort(key=lambda f: (f.path, f.rule, f.message))
    return findings, stale


def budget_entry(ir_prog: ProgramIR) -> Dict:
    """A fresh ratchet-tight budget row for one program — what
    ``--write-budgets`` records: current values as ceilings (collective
    exactness comes free — the census is deterministic per version),
    with headroom only where the metric legitimately jitters across
    hosts (peak-live scalars under x64, XLA temp allocation)."""
    cbytes, ccount = _census_totals(ir_prog)
    peak = ir_prog.peak_live_bytes
    hist = IR.dtype_histogram(ir_prog.jaxpr)
    return {
        "collective_bytes": cbytes,
        "collective_count": ccount,
        "temp_bytes": None if ir_prog.temp_bytes is None
        else int(ir_prog.temp_bytes * 2),
        "callbacks": _callback_count(ir_prog),
        "dtypes": {dt: int(hist.get(dt, 0))
                   for dt in ("float64", "complex128")},
        "donation_min": sorted(ir_prog.donate),
        "peak_live_bytes": None if peak is None else int(peak * 1.25),
    }
