"""Partitioned-HLO phase: the collective layout of a compiled program.

The jaxpr of a ``jit``-compiled program over ``NamedSharding`` arguments
contains NO collectives — GSPMD derives all-gathers / reduce-scatters /
all-reduces from the argument shardings during XLA compilation (arxiv
2004.13336; the whole point of the ZeRO-3 layout in
``parallel/sharded.py`` is that the rewrite is *derived*, not written).
So the only honest place to count them is the post-optimization HLO of
the compiled executable.  This module lowers a recorded audit spec
through ``InstrumentedJit.audit_lower`` (fresh jit, no counter ticks),
compiles it, and parses the HLO text into a collective census:
``{op: {count, bytes}}`` plus per-instruction operand identities for the
duplicate-gather check.

Parsing HLO text instead of walking a C++ module keeps the auditor
dependency-free and version-tolerant: the instruction grammar
(``%name = TYPE op(operands), attrs``) has been stable across every XLA
the repo has met, and an unrecognized line simply doesn't count — the
census can under-report on an exotic XLA, never crash the gate.
"""
from __future__ import annotations

import re
import warnings
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["compile_lowered", "parse_collectives", "census_from_ops",
           "compiled_flops", "compiled_temp_bytes", "CollectiveOp"]

# `%ag.1 = f32[64,32]{1,0} all-gather(f32[16,32]{1,0} %param.3), ...`
_OP_RE = re.compile(
    r"\b(all-gather|all-reduce|reduce-scatter|all-to-all|"
    r"collective-permute)(-start|-done)?\(")
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_OPERAND_RE = re.compile(r"%[\w.-]+")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
}


class CollectiveOp:
    """One collective instruction from the optimized HLO."""

    __slots__ = ("op", "result_bytes", "shapes", "operands", "line")

    def __init__(self, op: str, result_bytes: int,
                 shapes: List[str], operands: Tuple[str, ...], line: str):
        self.op = op
        self.result_bytes = result_bytes
        self.shapes = shapes
        self.operands = operands
        self.line = line

    def __repr__(self) -> str:  # debugging aid
        return (f"CollectiveOp({self.op}, {self.result_bytes}B, "
                f"{self.shapes})")


def compile_lowered(lowered):
    """Compile a ``Lowered``, silencing the CPU donation warnings the
    audit deliberately re-triggers (production skipped donation there;
    the audit lowers the DECLARED donation, which is the contract under
    test, not the platform workaround)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return lowered.compile()


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def parse_collectives(hlo_text: str) -> List[CollectiveOp]:
    """Collective instructions from optimized-HLO text.

    ``-done`` halves of async pairs are skipped (their ``-start`` twin
    already counted the transfer); result bytes come from the LHS shape
    tokens (variadic collectives sum their tuple elements).  A
    ``-start`` LHS is a state TUPLE that aliases the operand shapes
    (``(f32[16,32], f32[64,32]) all-gather-start(f32[16,32] %p)`` — and
    collective-permute adds u32[] context slots): counting the whole
    tuple would double-bill, so the operand shapes (and bare context
    scalars) are multiset-subtracted and only the true results remain.
    """
    out: List[CollectiveOp] = []
    for raw in hlo_text.splitlines():
        m = _OP_RE.search(raw)
        if m is None or m.group(2) == "-done":
            continue
        op = m.group(1)
        eq = raw.find("=")
        lhs = raw[(eq + 1) if eq >= 0 else 0:m.start()]
        lhs_shapes = _SHAPE_RE.findall(lhs)
        paren = raw[m.end():]
        depth, end = 1, 0
        for i, ch in enumerate(paren):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        operands = tuple(_OPERAND_RE.findall(paren[:end]))
        result_shapes = list(lhs_shapes)
        if m.group(2) == "-start" and len(lhs_shapes) > 1:
            remaining = list(_SHAPE_RE.findall(paren[:end]))
            kept = []
            for tok in lhs_shapes:
                if tok in remaining:              # aliased operand slot
                    remaining.remove(tok)
                elif tok[0].startswith("u") and tok[1] == "":
                    continue                      # u32[] context scalar
                else:
                    kept.append(tok)
            if kept:
                result_shapes = kept
        shapes = [f"{dt}[{dims}]" for dt, dims in result_shapes]
        result_bytes = sum(_shape_bytes(dt, dims)
                           for dt, dims in result_shapes)
        out.append(CollectiveOp(op, result_bytes, shapes, operands,
                                raw.strip()))
    return out


def census_from_ops(ops: List[CollectiveOp]) -> Dict[str, Dict[str, int]]:
    census: Dict[str, Dict[str, int]] = {}
    for c in ops:
        row = census.setdefault(c.op, {"count": 0, "bytes": 0})
        row["count"] += 1
        row["bytes"] += c.result_bytes
    return dict(sorted(census.items()))


def compiled_flops(compiled) -> Optional[float]:
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = ca.get("flops")
        return None if f is None else float(f)
    except Exception:
        return None


def compiled_temp_bytes(compiled) -> Optional[int]:
    """XLA's temp (intermediate) allocation for the executable — the real
    peak-intermediate number when the backend reports it."""
    try:
        ma = compiled.memory_analysis()
        return int(ma.temp_size_in_bytes)
    except Exception:
        return None
