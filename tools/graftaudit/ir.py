"""jaxpr traversal utilities for graftaudit.

The auditor's first IR phase works on the *jaxpr* of a compiled entry
point — the functional trace JAX produces before XLA lowering.  Every
rule that is about what the PROGRAM COMPUTES (dtypes, casts, callbacks,
broadcasts) runs here: the jaxpr is cheap to produce (no XLA compile),
exact (it is the very trace the production call executed), and stable
across backends.  Collective layout (AX003) is the one question the
jaxpr cannot answer — GSPMD inserts collectives from the argument
shardings at compile time — so that phase lives in ``hlo.py``.

Everything here is recursive over sub-jaxprs: ``pjit``/``closed_call``
bodies, ``scan``/``while``/``cond`` branches, ``remat`` and custom-vjp
call jaxprs all contribute equations (a cast hidden inside a
scan-over-layers body is still churn).
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "iter_eqns", "iter_jaxprs", "aval_bytes", "aval_dtype",
    "primitive_histogram", "dtype_histogram", "max_eqn_out_bytes",
    "invar_dtypes", "promotion_origins", "escaping_promotion_origins",
    "convert_churn_chains",
    "JAXPR_COLLECTIVES", "jaxpr_collective_census",
]

#: collective primitives that can appear at jaxpr level (shard_map/pmap
#: programs; jit-of-sharded-args programs get theirs from GSPMD instead)
JAXPR_COLLECTIVES = ("psum", "all_gather", "reduce_scatter", "all_to_all",
                     "ppermute", "psum_scatter", "pmax", "pmin")


def _sub_jaxprs(value: Any, out: List) -> None:
    """Collect open jaxprs reachable from one eqn-params value."""
    if value is None:
        return
    jx = getattr(value, "jaxpr", None)
    if jx is not None and hasattr(jx, "eqns"):      # ClosedJaxpr
        out.append(jx)
        return
    if hasattr(value, "eqns") and hasattr(value, "invars"):  # open Jaxpr
        out.append(value)
        return
    if isinstance(value, (list, tuple)):
        for v in value:
            _sub_jaxprs(v, out)


def iter_jaxprs(jaxpr) -> Iterator:
    """The jaxpr plus every sub-jaxpr reachable through eqn params,
    depth-first (each scope yielded exactly once)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        subs: List = []
        for v in eqn.params.values():
            _sub_jaxprs(v, subs)
        for sub in subs:
            yield from iter_jaxprs(sub)


def iter_eqns(jaxpr) -> Iterator:
    """Every equation in the program, recursively."""
    for j in iter_jaxprs(jaxpr):
        yield from j.eqns


def aval_dtype(v) -> Optional[Any]:
    aval = getattr(v, "aval", None)
    return getattr(aval, "dtype", None)


def aval_bytes(v) -> int:
    """Byte size of a var/aval (0 for abstract tokens and opaque types)."""
    aval = getattr(v, "aval", v)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except (TypeError, ValueError):    # symbolic dim
            return 0
    try:
        import numpy as np
        return n * int(np.dtype(dtype).itemsize)
    except Exception:
        return 0


def primitive_histogram(jaxpr) -> Dict[str, int]:
    hist: Dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        hist[name] = hist.get(name, 0) + 1
    return dict(sorted(hist.items()))


def dtype_histogram(jaxpr) -> Dict[str, int]:
    """Eqn-OUTPUT dtype histogram: what the program actually computes in."""
    hist: Dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        for ov in eqn.outvars:
            dt = aval_dtype(ov)
            if dt is not None:
                key = str(dt)
                hist[key] = hist.get(key, 0) + 1
    return dict(sorted(hist.items()))


def max_eqn_out_bytes(jaxpr) -> int:
    """Largest single equation output — a cheap jaxpr-level proxy for the
    peak intermediate (XLA's real temp allocation is reported separately
    when the program was compiled)."""
    best = 0
    for eqn in iter_eqns(jaxpr):
        for ov in eqn.outvars:
            best = max(best, aval_bytes(ov))
    return best


def invar_dtypes(jaxpr) -> List[str]:
    return [str(aval_dtype(v)) for v in jaxpr.invars
            if aval_dtype(v) is not None]


# --------------------------------------------------------------- promotion
_WIDE = ("float64", "complex128")


def _is_wide(dt) -> bool:
    return dt is not None and str(dt) in _WIDE


def promotion_origins(jaxpr) -> List[Tuple[Any, str]]:
    """Equations that INTRODUCE a 64-bit float/complex value: output is
    f64/c128 while no input is.  These are the true promotion points
    (dtype-defaulted constants like ``jnp.zeros(())`` under x64, weak
    Python-scalar promotion, an explicit astype) — everything downstream
    of one is just contamination, so reporting origins keeps one finding
    per bug instead of one per contaminated eqn."""
    out: List[Tuple[Any, str]] = []
    for eqn in iter_eqns(jaxpr):
        if not any(_is_wide(aval_dtype(ov)) for ov in eqn.outvars):
            continue
        if any(_is_wide(aval_dtype(iv)) for iv in eqn.invars):
            continue
        wide = next(str(aval_dtype(ov)) for ov in eqn.outvars
                    if _is_wide(aval_dtype(ov)))
        out.append((eqn, wide))
    return out


def escaping_promotion_origins(jaxpr) -> List[Tuple[Any, str]]:
    """Promotion origins whose wide value actually ESCAPES: reaches a
    program output or a non-scalar wide value, through wide-valued
    dataflow.  Contained scalar f64 (optax's weak-typed ``1 -
    b1**count`` bias correction, consumed straight back into an f32
    division) is byte-free noise and is NOT returned, even when a real
    escape exists elsewhere in the same program — each origin is judged
    by what ITS value reaches.

    Reachability is per jaxpr scope (backward walk from the escape
    seeds — top-level wide outvars plus any wide array — over
    wide-dtype def-use edges).  A wide value that escapes only by
    crossing a scan/pjit boundary is attributed to the enclosing
    equation in the parent scope (whose wide output makes it an origin
    there), so the finding still fires, one level up."""
    results: List[Tuple[Any, str]] = []
    scopes = list(iter_jaxprs(jaxpr))
    top = scopes[0] if scopes else None
    for scope in scopes:
        producers: Dict[Any, Any] = {}
        seeds = set()
        for eqn in scope.eqns:
            for ov in eqn.outvars:
                producers[ov] = eqn
                aval = getattr(ov, "aval", None)
                if _is_wide(getattr(aval, "dtype", None)) and \
                        len(getattr(aval, "shape", ())) >= 1:
                    seeds.add(ov)
        if scope is top:
            for v in scope.outvars:
                if _is_wide(aval_dtype(v)):
                    seeds.add(v)
        reached = set()
        stack = list(seeds)
        while stack:
            v = stack.pop()
            if v in reached:
                continue
            reached.add(v)
            eqn = producers.get(v)
            if eqn is None:
                continue
            for iv in eqn.invars:
                if hasattr(iv, "val"):
                    continue      # Literal: unhashable, and no producer
                if _is_wide(aval_dtype(iv)) and iv not in reached:
                    stack.append(iv)
        for eqn in scope.eqns:
            wide_outs = [ov for ov in eqn.outvars
                         if _is_wide(aval_dtype(ov))]
            if not wide_outs:
                continue
            if any(_is_wide(aval_dtype(iv)) for iv in eqn.invars):
                continue                       # contamination, not origin
            if any(ov in reached for ov in wide_outs):
                results.append((eqn, str(aval_dtype(wide_outs[0]))))
    return results


# ------------------------------------------------------------------- churn
def convert_churn_chains(jaxpr) -> List[Tuple[str, str, int]]:
    """Cast–uncast ping-pong: ``x:A -> convert -> y:B -> convert -> z:A``
    with ``A != B``.  Each round trip costs two element-wise passes over
    the value and (for f32->bf16->f32) quietly truncates mantissa bits —
    either the value should STAY in B (drop the second cast) or never
    have left A (drop both).  Detected per jaxpr scope (a chain that
    crosses a scan/pjit boundary is two different values to XLA anyway).
    Returns ``(src_dtype, mid_dtype, count)`` aggregates."""
    chains: Dict[Tuple[str, str], int] = {}
    for j in iter_jaxprs(jaxpr):
        producers: Dict[Any, Any] = {}
        for eqn in j.eqns:
            for ov in eqn.outvars:
                producers[ov] = eqn
        for eqn in j.eqns:
            if eqn.primitive.name != "convert_element_type":
                continue
            mid_var = eqn.invars[0]
            prev = producers.get(mid_var)
            if prev is None or prev.primitive.name != "convert_element_type":
                continue
            src_dt = aval_dtype(prev.invars[0])
            mid_dt = aval_dtype(mid_var)
            out_dt = aval_dtype(eqn.outvars[0])
            if src_dt is None or mid_dt is None or out_dt is None:
                continue
            # a true round trip: back where it started through a DIFFERENT
            # dtype (same-dtype converts are weak-type canonicalization)
            if str(src_dt) == str(out_dt) and str(mid_dt) != str(out_dt):
                key = (str(src_dt), str(mid_dt))
                chains[key] = chains.get(key, 0) + 1
    return [(s, m, c) for (s, m), c in sorted(chains.items())]


def jaxpr_collective_census(jaxpr) -> Dict[str, Dict[str, int]]:
    """Fallback collective census for programs with no multi-device
    sharding (shard_map/pmap bodies carry their collectives at jaxpr
    level; plain jit programs report through the partitioned HLO in
    ``hlo.py`` instead)."""
    census: Dict[str, Dict[str, int]] = {}
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in JAXPR_COLLECTIVES:
            continue
        row = census.setdefault(name, {"count": 0, "bytes": 0})
        row["count"] += 1
        row["bytes"] += sum(aval_bytes(ov) for ov in eqn.outvars)
    return dict(sorted(census.items()))
