"""graftaudit rule pack AX001–AX010.

Each rule is ``rule(ir: ProgramIR) -> list[Finding]`` over the analyzed
IR of ONE compiled program (``audit.analyze_program``), registered in
``AUDIT_RULES``.  Findings use the program NAME as their path — the
stable key the baseline and suppression machinery ratchets on — and the
catalog with rationale lives in ``tools/README.md``.

These are the contracts graftlint's AST rules structurally cannot see:
they live in the traced jaxpr / partitioned HLO, not the Python source.
A PR that turns the ZeRO-3 reduce-scatter into a dense all-reduce, leaks
an f32 matmul into a bf16 step, or drops donation on the decode cache
changes NO line any AST rule looks at — only the compiled program set.
"""
from __future__ import annotations

from typing import Callable, Dict, List

from ..graftlint.core import Finding
from . import ir as IR

__all__ = ["AUDIT_RULES", "AUDIT_RULE_DOCS", "DEAD_AFTER_CALL"]

AUDIT_RULES: Dict[str, Callable] = {}
AUDIT_RULE_DOCS: Dict[str, str] = {}

#: which positional args each jit KIND leaves dead after the call —
#: the caller-side contract the builders in ``nn/_common`` /
#: ``nn/multilayer`` / ``generation/programs`` encode in their
#: ``donate_argnums``.  train-family steps return fresh
#: params/state/opt (the old pytrees are garbage the moment the call
#: returns); serve's padded batch is built per dispatch and never
#: reread; the generation cache is threaded through both programs.
DEAD_AFTER_CALL: Dict[str, tuple] = {
    # arg 3 is the RNG key: the fused-RNG step splits it in-program and
    # returns the successor, so the caller's key is dead after the call
    # (the fit loops thread `new_rng` straight back in)
    "train_step": (0, 1, 2, 3),
    "train_step_carry": (0, 1, 2, 3, 8),
    "epoch_scan": (0, 1, 2, 3),
    "epochs_scan": (0, 1, 2, 3),
    "serve": (2,),
    # the paged pair threads the BLOCK POOL (tables/pos ride along as
    # host-mirrored data args and are rebuilt per call, never donated)
    "paged_prefill": (4,),
    "paged_decode": (3,),
}

_LOW_PRECISION = ("bfloat16", "float16")
_DOT_PRIMS = ("dot_general", "conv_general_dilated")
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback")


def rule(code: str, doc: str):
    def deco(fn):
        AUDIT_RULES[code] = fn
        AUDIT_RULE_DOCS[code] = doc
        return fn
    return deco


def _finding(ir_prog, code: str, msg: str) -> Finding:
    return Finding(path=ir_prog.name, line=0, col=0, rule=code, message=msg)


# --------------------------------------------------------------------- AX001
@rule("AX001", "f64/weak-type promotion introduced inside a steady-state "
               "program whose inputs are all <=32-bit")
def ax001(ir_prog) -> List[Finding]:
    """Under x64 a dtype-defaulted constant (``jnp.zeros(())``) or a weak
    Python scalar silently promotes everything downstream of it to f64 —
    double the bytes through every fused loop of the hottest program,
    with no Python line changed.  Flagged at the ORIGIN equations (output
    f64/c128, no f64/c128 input), one finding per primitive, and only
    when no program INPUT is 64-bit (a gradient-check feeding f64 data
    wants f64 math).  Contained scalar f64 that never reaches an output
    or an array (optax's weak-typed bias-correction arithmetic) is
    byte-free and stays silent — each origin is judged by what ITS value
    reaches (``escaping_promotion_origins``), so a real escape elsewhere
    never drags the benign scalar math into the report."""
    out: List[Finding] = []
    if not ir_prog.steady:
        return out
    if any(dt in ("float64", "complex128") for dt in ir_prog.input_dtypes):
        return out
    by_prim: Dict[str, int] = {}
    wide_by_prim: Dict[str, str] = {}
    for eqn, wide in IR.escaping_promotion_origins(ir_prog.jaxpr):
        name = eqn.primitive.name
        by_prim[name] = by_prim.get(name, 0) + 1
        wide_by_prim.setdefault(name, wide)
    for name in sorted(by_prim):
        out.append(_finding(
            ir_prog, "AX001",
            f"{by_prim[name]} `{name}` eqn(s) introduce "
            f"{wide_by_prim[name]} into a steady-state program whose "
            "inputs are all <=32-bit: a dtype-defaulted constant or weak "
            "Python scalar is promoting the math under x64 — give the "
            "constant the dtype of the value it joins"))
    return out


# --------------------------------------------------------------------- AX002
@rule("AX002", "precision-policy violation: f32 contraction inside a "
               "low-precision program, or convert_element_type churn")
def ax002(ir_prog) -> List[Finding]:
    """Two arms.  (a) In a program whose manifest DECLARES a bf16/f16
    policy, any ``dot_general``/conv with all-f32 floating operands
    bypassed the policy: the MXU runs it at 1/2 (or worse) throughput
    and the activation memory doubles.  The default keep_f32 classes
    and loss reductions are elementwise/reduce ops (no contractions),
    but a per-name ``overrides={'layer': 'float32'}`` pinning a dense
    layer IS a supported deliberate f32 contraction — so this arm only
    runs on explicitly declared policies, where the declarer also knows
    the overrides: declare ``policy=None`` for such a program, or
    suppress with the override as the justification.  (b) Cast–uncast
    ping-pong (``f32 -> bf16 -> f32`` on one value), any program: two
    wasted element-wise passes and a silent mantissa truncation; either
    stay in the narrow dtype or never leave the wide one."""
    out: List[Finding] = []
    dots = [e for e in IR.iter_eqns(ir_prog.jaxpr)
            if e.primitive.name in _DOT_PRIMS]

    def op_dtypes(eqn):
        return [str(IR.aval_dtype(v)) for v in eqn.invars[:2]
                if IR.aval_dtype(v) is not None]

    if ir_prog.policy in _LOW_PRECISION:
        f32_dots: Dict[str, int] = {}
        for e in dots:
            dts = op_dtypes(e)
            if dts and all(dt == "float32" for dt in dts):
                f32_dots[e.primitive.name] = \
                    f32_dots.get(e.primitive.name, 0) + 1
        for name in sorted(f32_dots):
            out.append(_finding(
                ir_prog, "AX002",
                f"{f32_dots[name]} f32 `{name}` eqn(s) inside a "
                f"declared-{ir_prog.policy} program: the contraction "
                "bypassed the precision policy — cast its operands to "
                "the compute dtype (default keep_f32 classes and loss "
                "reductions have no contractions; a deliberate per-name "
                "f32 override is the suppression justification)"))
    for src, mid, count in IR.convert_churn_chains(ir_prog.jaxpr):
        out.append(_finding(
            ir_prog, "AX002",
            f"convert_element_type churn: {count} value(s) round-trip "
            f"{src} -> {mid} -> {src} — two wasted element-wise passes "
            f"(and mantissa truncation when {mid} is narrower); keep the "
            "value in one dtype across the chain"))
    return out


# --------------------------------------------------------------------- AX003
@rule("AX003", "collective layout guard: dense all-reduce where the "
               "ZeRO-3 layout implies reduce-scatter, or duplicate "
               "per-operand all-gathers")
def ax003(ir_prog) -> List[Finding]:
    """The census itself (count + byte estimate per collective op) lands
    in the program card; this rule guards the two layout regressions
    that cost real HBM/interconnect.  (a) A ZeRO-3 program (sharded
    param args) containing an ``all-reduce`` of (near-)full-model
    gradient bytes: GSPMD was supposed to derive reduce-scatter + shard
    -local update from the shardings (arxiv 2004.13336); a dense
    all-reduce there means some op defeated the derivation and every
    step now ships dp x the gradient bytes.  (b) The same operand
    all-gathered twice with the same result shape — a missed CSE that
    doubles the gather traffic for one leaf."""
    out: List[Finding] = []
    if ir_prog.zero3 and ir_prog.param_bytes > 0:
        for c in ir_prog.collective_ops:
            if c.op != "all-reduce":
                continue
            if c.result_bytes >= 0.5 * ir_prog.param_bytes:
                out.append(_finding(
                    ir_prog, "AX003",
                    f"dense all-reduce of {c.result_bytes} bytes "
                    f"(>= 50% of the {ir_prog.param_bytes}-byte param "
                    "set) in a ZeRO-3 sharded program: the layout "
                    "implies reduce-scatter grads + shard-local update; "
                    "something (an unsharded constraint, a host-shaped "
                    "op) defeated the GSPMD derivation"))
    seen: Dict[tuple, int] = {}
    for c in ir_prog.collective_ops:
        if c.op != "all-gather" or not c.operands:
            continue
        if c.result_bytes < ir_prog.config.dup_gather_bytes:
            # tiny re-gathered index blocks (XLA skips cross-fusion CSE
            # on them) are not the duplicated-param-gather regression
            continue
        key = (c.operands, tuple(c.shapes))
        seen[key] = seen.get(key, 0) + 1
    for (operands, shapes), n in sorted(seen.items()):
        if n > 1:
            out.append(_finding(
                ir_prog, "AX003",
                f"operand {operands[0]} is all-gathered {n}x with "
                f"identical result {shapes}: duplicate per-leaf forward "
                "gather — reuse the gathered value"))
    return out


# --------------------------------------------------------------------- AX004
@rule("AX004", "host callback (pure_callback/io_callback/debug.print) "
               "inside a steady-state program")
def ax004(ir_prog) -> List[Finding]:
    """A callback primitive stalls the device at every execution of the
    program: the runtime must round-trip the host (on TPU, through the
    dispatch tunnel) before the next fused region can run — the
    zero-steady-state-host-sync contract is void while one of these is
    in a train/serve/decode program.  ``jax.debug.print`` lowers to
    ``debug_callback``, so a leftover debug line is caught here even
    though the AST-side complement (JX026) already flags the source."""
    out: List[Finding] = []
    if not ir_prog.steady:
        return out
    counts: Dict[str, int] = {}
    for eqn in IR.iter_eqns(ir_prog.jaxpr):
        if eqn.primitive.name in _CALLBACK_PRIMS:
            counts[eqn.primitive.name] = \
                counts.get(eqn.primitive.name, 0) + 1
    for name in sorted(counts):
        out.append(_finding(
            ir_prog, "AX004",
            f"{counts[name]} `{name}` eqn(s) in a steady-state program: "
            "every execution stalls the device on a host round-trip — "
            "move the callback out of the hot program (or pragma a "
            "deliberate one with its justification)"))
    return out


# --------------------------------------------------------------------- AX005
@rule("AX005", "donation miss: a large dead-after-call argument is not "
               "in donate_argnums")
def ax005(ir_prog) -> List[Finding]:
    """For the arg positions this program's KIND leaves dead after the
    call (``DEAD_AFTER_CALL``: train steps return fresh
    params/state/opt, serve never rereads its padded batch, the decode
    cache is threaded), a leaf tree above the size threshold that is NOT
    donated forces XLA to keep input and output alive simultaneously —
    on the train step that is 2x params + 2x optimizer state of
    avoidable HBM, exactly the headroom large-model configs run out of
    first."""
    out: List[Finding] = []
    dead = DEAD_AFTER_CALL.get(ir_prog.kind)
    if dead is None and ir_prog.kind.startswith("pretrain"):
        dead = (0, 1, 2)    # layer params, opt state, RNG key (fused split)
    if not dead:
        return out
    for argnum in dead:
        if argnum >= len(ir_prog.arg_bytes):
            continue
        size = ir_prog.arg_bytes[argnum]
        if size < ir_prog.config.min_donate_bytes:
            continue
        if argnum not in ir_prog.donate:
            out.append(_finding(
                ir_prog, "AX005",
                f"arg {argnum} ({size} bytes) is dead after the call in "
                f"kind '{ir_prog.kind}' but not in donate_argnums"
                f"{tuple(ir_prog.donate)}: XLA must hold input and "
                "output alive together — donate it (or pragma the "
                "platform that cannot, with justification)"))
    return out


# --------------------------------------------------------------------- AX006
@rule("AX006", "oversized broadcast intermediate materialized inside the "
               "program")
def ax006(ir_prog) -> List[Finding]:
    """A ``broadcast_in_dim`` whose result is both large in absolute
    bytes and a big multiple of its operand usually means a reduction
    was written as materialize-then-reduce (or a mask/one-hot blew up to
    batch x vocab x seq): XLA often fuses these away, but one that
    survives into the jaxpr at this size is peak-memory risk worth a
    look.  Thresholds ride the audit config so toy canonical programs
    don't cry wolf."""
    out: List[Finding] = []
    cfg = ir_prog.config
    hits = 0
    worst = 0
    for eqn in IR.iter_eqns(ir_prog.jaxpr):
        if eqn.primitive.name != "broadcast_in_dim":
            continue
        ob = sum(IR.aval_bytes(ov) for ov in eqn.outvars)
        ib = max([IR.aval_bytes(iv) for iv in eqn.invars] or [0])
        if ob >= cfg.broadcast_bytes and ob >= cfg.broadcast_ratio * \
                max(ib, 1):
            hits += 1
            worst = max(worst, ob)
    if hits:
        out.append(_finding(
            ir_prog, "AX006",
            f"{hits} broadcast_in_dim eqn(s) materialize >= "
            f"{cfg.broadcast_bytes} bytes (largest {worst}) from a "
            f">= {cfg.broadcast_ratio}x smaller operand: likely a "
            "materialize-then-reduce — restructure to reduce without "
            "the full intermediate"))
    return out


# --------------------------------------------------------------------- AX007
@rule("AX007", "declared-donation incompleteness: the lifetime solver's "
               "maximal safe donation set exceeds donate_argnums")
def ax007(ir_prog) -> List[Finding]:
    """The exact form of AX005's threshold heuristic (which stays as the
    cheap pre-filter): the lifetime solver proved these arguments are
    (a) dead after the call — the caller's bindings were observed
    collected/donated, or the kind contract says so and no observation
    contradicts it — and (b) *usefully* donatable: every array leaf has
    a shape/dtype-compatible unclaimed output leaf for XLA to alias
    into.  Each one not in ``donate_argnums`` keeps input AND output
    alive across the execution for no reason — on a train step that is
    a whole extra params+opt-state of HBM.  Unlike AX005 this cannot
    cry wolf on an argument donation would not help (no aliasable
    output) or one the caller actually re-reads (observed live)."""
    out: List[Finding] = []
    lt = ir_prog.lifetime
    if lt is None:
        return out
    for a in lt.args:
        if not a.donatable or a.argnum in ir_prog.donate:
            continue
        if a.bytes < ir_prog.config.min_donate_bytes:
            continue
        out.append(_finding(
            ir_prog, "AX007",
            f"arg {a.argnum} ({a.bytes} bytes, caller {a.caller}"
            f"{', contract-dead' if a.contract_dead else ''}) is in the "
            f"maximal safe donation set but not donate_argnums"
            f"{tuple(ir_prog.donate)}: every leaf has an aliasable "
            "output — donate it (or suppress for the platform that "
            "cannot, with justification)"))
    return out


# --------------------------------------------------------------------- AX008
@rule("AX008", "per-program IR budget exceeded: peak-live-bytes (this "
               "rule) or a collective/temp/dtype/callback ceiling (the "
               "--diff-cards gate, same code)")
def ax008(ir_prog) -> List[Finding]:
    """The lifetime solver's peak-live-bytes estimate (live-range
    intervals over the eqn order, scan carries included) checked
    against a per-program ceiling — the ``peak_live_bytes`` entries of
    ``budgets.json``, threaded through ``AuditConfig``.  An unbudgeted
    program is silent (budgets are opt-in); a budgeted one that grew
    past its ceiling fails, because a silent 2x in live bytes is
    exactly how an OOM ships: no Python line changed, only the compiled
    program's live set."""
    out: List[Finding] = []
    budgets = ir_prog.config.peak_live_budgets
    if not budgets or ir_prog.peak_live_bytes is None:
        return out
    ceiling = budgets.get(ir_prog.name)
    if ceiling is None or ir_prog.peak_live_bytes <= int(ceiling):
        return out
    out.append(_finding(
        ir_prog, "AX008",
        f"peak-live-bytes estimate {ir_prog.peak_live_bytes} exceeds "
        f"the budget ceiling {int(ceiling)}: the program's live set "
        "grew — find the new/longer-lived buffer (lost donation, new "
        "mirror, wider dtype) or raise the ceiling in budgets.json "
        "with a justifying comment"))
    return out


# --------------------------------------------------------------------- AX009
@rule("AX009", "recompile-hazard call variants: captured specs differing "
               "only by Python-scalar value / weak-typed 0-d leaf")
def ax009(ir_prog) -> List[Finding]:
    """Multiple captured call specs of this entry collapse onto ONE
    program once Python-scalar values and weak-typed 0-d leaves are
    erased: the call sites are feeding raw Python scalars (or mixing
    ``1.0`` with ``np.float32(1.0)``) where a committed dtype belongs.
    Each variant is at best a redundant dispatch-cache entry crowding
    the audit spec ring, at worst a full retrace (weak-type flips, int
    vs float) — the classic \"temperature knob retraces the decode
    step\" bug.  Commit the scalar at the call boundary
    (``np.float32(x)``) so every value rides one compiled program."""
    out: List[Finding] = []
    if ir_prog.variant_count <= 1:
        return out
    detail = "; ".join(ir_prog.variant_churn[:3]) or "0-d leaves"
    out.append(_finding(
        ir_prog, "AX009",
        f"{ir_prog.variant_count} captured call specs differ only by "
        f"Python-scalar value / weak-typed 0-d leaves ({detail}): "
        "commit the scalar to a fixed np dtype at the call boundary so "
        "one compiled variant serves every value"))
    return out


# --------------------------------------------------------------------- AX010
@rule("AX010", "committed-card drift: fresh audit disagrees with the "
               "checked-in program card on a stable field")
def ax010(ir_prog) -> List[Finding]:
    """The committed cards under ``tools/graftaudit/cards/`` are the
    reviewed IR record of each canonical program; this rule is the
    enforcement arm: any stable-field disagreement between the FRESH
    audit and the committed card (collective census, donation map,
    kind/policy flags) — or a missing card — is a finding, so an IR
    regression must either be fixed or land as a reviewable card diff
    (``--write-cards``), never as silent drift.  Only runs when
    ``AuditConfig.cards_dir`` is set (the canonical/gate path)."""
    out: List[Finding] = []
    cards_dir = ir_prog.config.cards_dir
    if not cards_dir:
        return out
    import os

    from .cards import STABLE_FIELDS, build_card, card_filename, load_card

    path = os.path.join(cards_dir, card_filename(ir_prog.name))
    if not os.path.exists(path):
        out.append(_finding(
            ir_prog, "AX010",
            f"no committed card at {path}: run --write-cards and commit "
            "the new program's card"))
        return out
    committed = load_card(path)
    fresh = build_card(ir_prog)
    for fld in STABLE_FIELDS:
        if fresh.get(fld) != committed.get(fld):
            out.append(_finding(
                ir_prog, "AX010",
                f"stable field '{fld}' drifted from the committed card: "
                f"card has {committed.get(fld)!r}, fresh audit has "
                f"{fresh.get(fld)!r} — fix the regression or commit the "
                "reviewed card diff (--write-cards)"))
    return out
