"""graftaudit command-line interface.

Audits the canonical program set (builds the tiny representative
programs through their production entry points, then rule-checks their
jaxpr / partitioned HLO), mirroring graftlint's CLI conventions:
text/json/sarif output, a ratcheted (empty) baseline, exit 1 on
findings, exit 2 on stale allowances.  ``--write-cards`` commits the
per-program IR cards that make compiled-program diffs reviewable PR
over PR; ``--diff-cards`` is the differential gate — rebuild, audit
(AX010 card drift armed), check the budgets.json ceilings — that turns
every silent IR regression into a CI failure.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")
DEFAULT_CARDS_DIR = os.path.join(os.path.dirname(__file__), "cards")
DEFAULT_BUDGETS = os.path.join(os.path.dirname(__file__), "budgets.json")


def _setup_jax_env() -> None:
    """Before jax import: virtual devices for the sharded programs; and
    honor JAX_PLATFORMS via config (the environment's sitecustomize
    snapshots it at interpreter start, so the env var alone is too
    late — same workaround as tests/conftest.py)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.graftaudit",
        description="IR-level static analyzer of the compiled program "
                    "set: rules AX001-AX010 over the jaxpr + partitioned "
                    "HLO of the canonical programs (see tools/README.md)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON of accepted findings "
                        "(default: tools/graftaudit/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file "
                        "and exit")
    p.add_argument("--write-cards", action="store_true",
                   help="write per-program IR cards (committed artifact; "
                        "canonical when run under the tier-1 rig: CPU, 8 "
                        "virtual devices, x64)")
    p.add_argument("--cards-dir", default=DEFAULT_CARDS_DIR,
                   help="directory for --write-cards "
                        "(default: tools/graftaudit/cards)")
    p.add_argument("--diff-cards", action="store_true",
                   help="differential gate: rebuild the canonical set, "
                        "diff the fresh audit against the committed "
                        "cards (AX010) and the budgets.json ceilings "
                        "(AX007/AX008); exit 1 on any breach, exit 2 on "
                        "stale budget entries")
    p.add_argument("--budgets", default=DEFAULT_BUDGETS,
                   help="per-program IR budgets JSON for --diff-cards "
                        "(default: tools/graftaudit/budgets.json)")
    p.add_argument("--write-budgets", action="store_true",
                   help="write ratchet-tight budget rows for the "
                        "current audit to --budgets and exit (edit the "
                        "file to keep a raise justified)")
    p.add_argument("--programs", default=None,
                   help="comma-separated name substrings: audit only "
                        "matching canonical programs")
    p.add_argument("--no-compile", action="store_true",
                   help="jaxpr phase only (no XLA compiles: faster, but "
                        "no collective census / flops)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from .rules import AUDIT_RULE_DOCS

    if args.list_rules:
        for code in sorted(AUDIT_RULE_DOCS):
            print(f"{code}  {AUDIT_RULE_DOCS[code]}")
        return 0

    _setup_jax_env()
    import dataclasses

    from ..graftlint import to_sarif
    from ..graftlint.core import Baseline
    from .audit import audit_programs
    from .canonical import CANONICAL_CONFIG, build_canonical
    from .cards import write_cards

    include = ([s.strip() for s in args.programs.split(",") if s.strip()]
               if args.programs else None)
    config = CANONICAL_CONFIG
    if args.no_compile:
        config = dataclasses.replace(config, compile="never")
    if args.diff_cards:
        # the gate always diffs against the cards dir it was pointed at
        config = dataclasses.replace(config, cards_dir=args.cards_dir)
    budgets = None
    if args.diff_cards or args.write_budgets:
        from .diff import check_budgets, load_budgets
        if args.diff_cards:
            try:
                budgets = load_budgets(args.budgets)
            except (OSError, ValueError) as e:
                # a gate without budgets is not a clean gate
                print(f"graftaudit: cannot load budgets "
                      f"({type(e).__name__}: {e}) — the diff gate "
                      "refuses to run budget-less", file=sys.stderr)
                return 2
    cs = build_canonical(include=include)
    if not cs.programs:
        build_parser().error("no canonical programs matched --programs")
    result = audit_programs(cs.programs, cs.suppressions, config)
    for name, why in sorted(cs.skipped.items()):
        print(f"graftaudit: skipped {name}: {why}", file=sys.stderr)

    stale_budgets: List[str] = []
    if budgets is not None:
        # a --programs subset run leaves the NON-matching budgeted
        # programs un-audited, not dead — but a row that matches the
        # filter and still produced no program is as stale as ever
        skipped_for_diff = dict(cs.skipped)
        if include is not None:
            audited = {ir_prog.name for ir_prog in result.irs}
            for name in budgets.get("programs", {}):
                if name not in audited and \
                        not any(s in name for s in include):
                    skipped_for_diff.setdefault(name, "--programs subset")
        diff_findings, stale_budgets = check_budgets(
            result.irs, budgets, skipped_for_diff)
        result.findings = sorted(
            result.findings + diff_findings,
            key=lambda f: (f.path, f.rule, f.message))

    if args.write_budgets:
        from .diff import budget_entry
        rows = {}
        if os.path.exists(args.budgets):
            try:
                with open(args.budgets, "r", encoding="utf-8") as fh:
                    rows = json.load(fh).get("programs", {})
            except (OSError, ValueError):
                rows = {}
        # subset/skipped-host runs keep the other programs' rows (same
        # rule as card pruning: reduced coverage is not deletion)
        kept = {n: r for n, r in rows.items()
                if include is not None or n in cs.skipped}
        for ir_prog in result.irs:
            kept[ir_prog.name] = budget_entry(ir_prog)
        payload = {
            "comment": "graftaudit per-program IR budgets "
                       "(--diff-cards). Ceilings only RATCHET down "
                       "automatically (--write-budgets records current "
                       "values); raising one is a reviewed edit with a "
                       "justifying comment, like a suppression. Stale "
                       "entries (program gone) fail the gate with "
                       "exit 2 — delete them.",
            "programs": dict(sorted(kept.items())),
        }
        with open(args.budgets, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=1, sort_keys=False)
            fh.write("\n")
        print(f"wrote {len(result.irs)} budget row(s) to {args.budgets}")
        return 0

    if args.write_cards:
        # a full-set run owns the cards dir and prunes orphans (renamed/
        # removed programs) — but a program this HOST merely couldn't
        # build (cs.skipped) still exists, so its committed card is
        # live, not an orphan; a --programs subset never prunes
        from .cards import card_filename
        paths = write_cards(
            result.irs, args.cards_dir, prune=include is None,
            keep={card_filename(n) for n in cs.skipped})
        print(f"wrote {len(paths)} program card(s) to {args.cards_dir}")

    if args.write_baseline:
        Baseline.from_findings(result.findings).save(args.baseline)
        print(f"wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    findings = result.findings
    stale_bl: List[str] = []
    if not args.no_baseline:
        findings, stale_bl = Baseline.load(args.baseline).apply(findings)

    if args.format == "json":
        print(json.dumps([f.to_json() for f in findings], indent=2))
    elif args.format == "sarif":
        print(json.dumps(to_sarif(findings, AUDIT_RULE_DOCS), indent=2))
    else:
        for f in findings:
            print(f.format())
        n, np_, ns = len(findings), len(result.irs), \
            sum(result.suppressed.values())
        print(f"graftaudit: {n} finding(s) over {np_} program(s)"
              + (f", {ns} suppressed" if ns else "")
              if n else
              f"graftaudit: clean ({np_} program(s)"
              + (f", {ns} suppressed" if ns else "") + ")")

    # the ratchet, both layers: an allowance (inline suppression OR
    # baseline entry) matching nothing must be deleted, not left armed
    rc = 1 if findings else 0
    if result.stale_suppressions:
        print("graftaudit: stale suppression(s) — remove from the "
              "manifest:", file=sys.stderr)
        for key in result.stale_suppressions:
            print(f"  {key}", file=sys.stderr)
        rc = 2
    if stale_bl:
        print(f"graftaudit: stale baseline entr"
              f"{'y' if len(stale_bl) == 1 else 'ies'} (no matching "
              f"finding — remove from {args.baseline}):", file=sys.stderr)
        for key in stale_bl:
            print(f"  {key}", file=sys.stderr)
        rc = 2
    if stale_budgets:
        print(f"graftaudit: stale budget entr"
              f"{'y' if len(stale_budgets) == 1 else 'ies'} (program no "
              f"longer exists — remove from {args.budgets}):",
              file=sys.stderr)
        for name in stale_budgets:
            print(f"  {name}", file=sys.stderr)
        rc = 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
