"""graftaudit orchestration: program model, analysis, rule driving.

``AuditProgram`` names one compiled variant of one jitted entry point:
the live ``InstrumentedJit`` from the process-global trace cache
(``nn/compile_cache.iter_trace_cache``) plus ONE recorded abstract call
spec (``InstrumentedJit.audit_specs``).  ``analyze_program`` derives its
IR views — jaxpr (always) and, per the compile policy, the
partitioned-HLO collective census / flops / temp bytes of a fresh
compile (``compile="auto"`` compiles every program, degrading
gracefully to jaxpr-only when XLA refuses; ``"never"`` skips the
compile phase for fast unit tests) — into a ``ProgramIR`` that the AX
rules consume.

Suppressions are graftaudit's inline pragmas: declared in code right
next to the program set they apply to (``canonical.py`` for the
canonical manifest), each carrying a MANDATORY justification.  An unused
suppression is reported stale exactly like a stale baseline entry — an
allowance must never lie in wait to absorb a future regression.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..graftlint.core import Finding
from . import extract as EX
from . import hlo as HLO
from . import ir as IR
from . import lifetime as LT
from .rules import AUDIT_RULES, DEAD_AFTER_CALL

__all__ = ["AuditConfig", "AuditProgram", "ProgramIR", "Suppression",
           "AuditResult", "analyze_program", "audit_programs",
           "programs_from_trace_cache"]


@dataclass(frozen=True)
class AuditConfig:
    """Thresholds + compile policy for one audit run."""
    #: AX005: dead-after-call args below this size are not worth donating
    min_donate_bytes: int = 1 << 20
    #: AX006: a broadcast result below this absolute size never fires
    broadcast_bytes: int = 64 << 20
    #: AX006: ... and must also be this multiple of its operand
    broadcast_ratio: int = 8
    #: AX003(b): duplicate all-gathers below this result size are noise
    #: (XLA re-gathers tiny index blocks inside separate fusions rather
    #: than CSE'ing across them — e.g. the sparse-embedding id blocks);
    #: the arm targets duplicated PARAM-leaf gathers, which dwarf this
    dup_gather_bytes: int = 1024
    #: "auto" compiles every program (census + flops + temp bytes,
    #: degrading to jaxpr-only when XLA refuses); "never" stays at the
    #: jaxpr phase (fast unit tests)
    compile: str = "auto"
    #: AX008: per-program peak-live-bytes ceilings (program name -> int,
    #: usually the "peak_live_bytes" entries of budgets.json); None
    #: disables the rule entirely, and a program absent from the map is
    #: unbudgeted (silent) — budgets are opt-in per program
    peak_live_budgets: Optional[Any] = None
    #: AX010: directory of committed program cards to diff the fresh
    #: audit against (stable fields only); None disables the rule
    cards_dir: Optional[str] = None


@dataclass(frozen=True)
class Suppression:
    """One justified allowance: suppress ``rule`` on ``program``.

    ``reason`` is mandatory and non-empty — the justification IS the
    point (graftlint pragma convention); an unexplained suppression is
    indistinguishable from a hidden regression.
    """
    program: str
    rule: str
    reason: str

    def __post_init__(self):
        if not self.reason or not self.reason.strip():
            raise ValueError(
                f"Suppression({self.program!r}, {self.rule!r}) needs a "
                "non-empty justification")

    @property
    def key(self) -> str:
        return f"{self.program}::{self.rule}"


@dataclass
class AuditProgram:
    """One compiled program variant to audit."""
    name: str                 # unique within the audited set
    entry: Any                # InstrumentedJit
    spec: Any                 # one recorded (args, kwargs) abstract spec
    steady: bool = True       # steady-state program (AX001/AX004 scope)
    policy: Optional[str] = None   # declared compute dtype, e.g. "bfloat16"
    zero3: Optional[bool] = None   # None = auto-detect from arg shardings

    @property
    def kind(self) -> str:
        return self.entry.name


@dataclass
class ProgramIR:
    """Analyzed IR views of one program, as the rules consume them."""
    name: str
    kind: str
    steady: bool
    policy: Optional[str]
    zero3: bool
    config: AuditConfig
    jaxpr: Any                          # open jaxpr (ClosedJaxpr.jaxpr)
    spec: Any
    donate: Tuple[int, ...]
    arg_bytes: List[int]
    param_bytes: int
    input_dtypes: List[str]
    census: Dict[str, Dict[str, int]] = field(default_factory=dict)
    census_source: str = "jaxpr"        # "hlo" | "jaxpr"
    collective_ops: List[Any] = field(default_factory=list)
    flops: Optional[float] = None
    temp_bytes: Optional[int] = None
    #: lifetime/donation solver output (lifetime.LifetimeInfo) — None
    #: only when the solver itself failed (recorded in the name-keyed
    #: warning, never silently)
    lifetime: Optional[Any] = None
    peak_live_bytes: Optional[int] = None
    #: captured-spec variant churn (lifetime.spec_variant_group): how
    #: many of the entry's recorded specs collapse onto this spec once
    #: Python-scalar values / weak-typed 0-d leaves are erased
    variant_count: int = 1
    variant_churn: List[str] = field(default_factory=list)


def _tree_bytes(tree: Any) -> int:
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += IR.aval_bytes(leaf)
    return total


def _leaf_sharded(leaf: Any) -> bool:
    sh = getattr(leaf, "sharding", None)
    mesh = getattr(sh, "mesh", None)
    if mesh is None or getattr(mesh, "size", 1) <= 1:
        return False
    spec = getattr(sh, "spec", None)
    return spec is not None and any(ax is not None for ax in tuple(spec))


def analyze_program(p: AuditProgram,
                    config: Optional[AuditConfig] = None) -> ProgramIR:
    """Derive the IR views of one program: jaxpr (exact re-trace of the
    recorded spec) plus, per the compile policy, the partitioned-HLO
    collective census / flops / temp bytes of a fresh compile."""
    import jax

    config = config or AuditConfig()
    closed = p.entry.audit_jaxpr(p.spec)
    jaxpr = closed.jaxpr
    args, _kwargs = p.spec
    arg_bytes = [_tree_bytes(a) for a in args]
    zero3 = p.zero3
    if zero3 is None:
        zero3 = bool(args) and any(
            _leaf_sharded(l) for l in jax.tree_util.tree_leaves(args[0]))
    ir_prog = ProgramIR(
        name=p.name, kind=p.kind, steady=p.steady, policy=p.policy,
        zero3=zero3, config=config, jaxpr=jaxpr, spec=p.spec,
        donate=tuple(p.entry.donate_argnums), arg_bytes=arg_bytes,
        param_bytes=arg_bytes[0] if arg_bytes else 0,
        input_dtypes=IR.invar_dtypes(jaxpr),
        census=IR.jaxpr_collective_census(jaxpr))
    contract = DEAD_AFTER_CALL.get(p.kind)
    if contract is None and p.kind.startswith("pretrain"):
        contract = (0, 1)
    try:
        ir_prog.lifetime = LT.solve_lifetime(
            jaxpr, p.spec, donate=ir_prog.donate, entry=p.entry,
            contract_dead=contract or ())
        ir_prog.peak_live_bytes = ir_prog.lifetime.peak_live_bytes
    except Exception as e:           # solver failure must be loud
        import warnings

        warnings.warn(
            f"graftaudit: lifetime solve of '{p.name}' failed — "
            f"{type(e).__name__}: {e}", RuntimeWarning, stacklevel=2)
    count, churn = LT.spec_variant_group(p.entry, p.spec)
    ir_prog.variant_count, ir_prog.variant_churn = count, churn
    if config.compile == "never":
        return ir_prog
    try:
        ex = EX.extract_hlo(p.entry, p.spec, name=p.name)
        ops = HLO.parse_collectives(ex.hlo_text)
        ir_prog.collective_ops = ops
        ir_prog.census = HLO.census_from_ops(ops)
        ir_prog.census_source = "hlo"
        ir_prog.flops = ex.flops
        ir_prog.temp_bytes = ex.temp_bytes
    except Exception as e:
        # jaxpr-phase results stand, but NEVER silently: a failed
        # compile of a sharded program would otherwise "audit clean"
        # with an empty census — AX003's entire subject matter.  The
        # degradation is recorded where the gate tests and committed
        # cards look (census_source), so a zero3 program whose compile
        # broke fails the census_source=="hlo" pins instead of passing.
        import warnings

        ir_prog.census_source = \
            f"jaxpr (compile failed: {type(e).__name__})"
        warnings.warn(
            f"graftaudit: HLO phase of '{p.name}' degraded to jaxpr "
            f"census — {type(e).__name__}: {e}", RuntimeWarning,
            stacklevel=2)
    return ir_prog


@dataclass
class AuditResult:
    findings: List[Finding]             # post-suppression, pre-baseline
    irs: List[ProgramIR]
    suppressed: Dict[str, int]          # suppression key -> absorbed count
    stale_suppressions: List[str]       # declared but matched nothing


def audit_programs(programs: Sequence[AuditProgram],
                   suppressions: Sequence[Suppression] = (),
                   config: Optional[AuditConfig] = None,
                   rules: Optional[Sequence[str]] = None) -> AuditResult:
    """Analyze + rule-check a program set.

    Duplicate program names are an error (they are the baseline /
    suppression keys).  Returns findings AFTER suppression filtering —
    baseline application is the caller's (CLI / gate test) concern, same
    split as graftlint.
    """
    names = [p.name for p in programs]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise ValueError(f"duplicate program name(s): {', '.join(dupes)}")
    codes = sorted(AUDIT_RULES) if rules is None else list(rules)
    irs = [analyze_program(p, config) for p in programs]
    findings: List[Finding] = []
    for ir_prog in irs:
        for code in codes:
            findings.extend(AUDIT_RULES[code](ir_prog))
    findings.sort(key=lambda f: (f.path, f.rule, f.message))
    by_key = {s.key: s for s in suppressions}
    if len(by_key) != len(list(suppressions)):
        raise ValueError("duplicate suppression keys")
    suppressed: Dict[str, int] = {}
    kept: List[Finding] = []
    for f in findings:
        key = f"{f.path}::{f.rule}"
        if key in by_key:
            suppressed[key] = suppressed.get(key, 0) + 1
        else:
            kept.append(f)
    stale = sorted(k for k in by_key if k not in suppressed)
    return AuditResult(findings=kept, irs=irs, suppressed=suppressed,
                       stale_suppressions=stale)


def programs_from_trace_cache(steady_kinds: Optional[Sequence[str]] = None
                              ) -> List[AuditProgram]:
    """Audit programs for EVERY live trace-cache entry's recorded specs —
    the in-process audit path (a long-lived trainer/server can audit
    itself).  Names are ``<kind>#<i>`` per recorded spec; steady-state
    marking defaults to the kinds graftaudit knows are per-step/request
    programs."""
    from deeplearning4j_tpu.nn.compile_cache import iter_trace_cache

    if steady_kinds is None:
        steady_kinds = ("train_step", "train_step_carry", "epoch_scan",
                        "epochs_scan", "serve", "paged_prefill",
                        "paged_decode")
    out: List[AuditProgram] = []
    seen: Dict[str, int] = {}
    for _key, entry in iter_trace_cache():
        for spec in entry.audit_specs():
            i = seen.get(entry.name, 0)
            seen[entry.name] = i + 1
            out.append(AuditProgram(
                name=f"{entry.name}#{i}", entry=entry, spec=spec,
                steady=entry.name in steady_kinds
                or entry.name.startswith("pretrain")))
    return out
