"""Program cards: the committed, reviewable IR summary of one program.

A card is a small deterministic JSON artifact per canonical program —
collective census, flops, peak intermediate bytes, donation map, eqn /
dtype histograms — committed under ``tools/graftaudit/cards/`` so an
IR-level change shows up as a reviewable diff in the PR that caused it
(the same way a lockfile diff shows a dependency change).  A rewritten
collective layout, a dropped donation, or a dtype drift is one `git
diff` away instead of one profile review away.

Fields that depend on the host environment's dtype defaults (the
``dtypes``/``primitives`` histograms shift with ``jax_enable_x64``) are
still recorded — cards are canonically (re)generated on the tier-1 rig
(``--write-cards`` under the test environment: CPU, 8 virtual devices,
x64) — but the gate test pins only the environment-stable fields
(collectives, donation, kind/policy flags).
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict, List

from . import ir as IR
from .audit import ProgramIR
from .rules import DEAD_AFTER_CALL

__all__ = ["build_card", "card_filename", "write_cards", "load_card",
           "STABLE_FIELDS"]

#: card fields the gate test compares against a fresh audit — stable
#: across x64/backends once the program set is AX001-clean
STABLE_FIELDS = ("program", "kind", "steady", "policy", "zero3",
                 "collectives", "census_source", "donation")


def build_card(ir_prog: ProgramIR) -> Dict:
    dead = DEAD_AFTER_CALL.get(ir_prog.kind, ())
    donation = {
        "declared": sorted(ir_prog.donate),
        "args": [{"argnum": i, "bytes": b,
                  "donated": i in ir_prog.donate,
                  "dead_after_call": i in dead}
                 for i, b in enumerate(ir_prog.arg_bytes)],
    }
    # the lifetime solver's verdict (ISSUE 16): recorded like flops —
    # reviewable PR over PR, but NOT in STABLE_FIELDS (the caller
    # observation rides process GC timing; the pinned proof lives in
    # tests/test_audit_diff.py and budgets.json instead)
    lt = ir_prog.lifetime
    lifetime = None if lt is None else {
        "maximal_donation": sorted(lt.maximal_donation),
        "undeclared_donatable": sorted(
            set(lt.maximal_donation) - set(ir_prog.donate)),
        "peak_live_bytes": ir_prog.peak_live_bytes,
    }
    jaxpr = ir_prog.jaxpr
    return {
        "program": ir_prog.name,
        "kind": ir_prog.kind,
        "steady": ir_prog.steady,
        "policy": ir_prog.policy,
        "zero3": ir_prog.zero3,
        "collectives": ir_prog.census,
        "census_source": ir_prog.census_source,
        "donation": donation,
        "lifetime": lifetime,
        "flops": ir_prog.flops,
        "temp_bytes": ir_prog.temp_bytes,
        "max_eqn_out_bytes": IR.max_eqn_out_bytes(jaxpr),
        "eqns": sum(1 for _ in IR.iter_eqns(jaxpr)),
        "primitives": IR.primitive_histogram(jaxpr),
        "dtypes": IR.dtype_histogram(jaxpr),
        "input_dtypes": ir_prog.input_dtypes,
    }


def card_filename(program_name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", program_name) + ".json"


def write_cards(irs: List[ProgramIR], directory: str,
                prune: bool = False, keep: "set" = ()) -> List[str]:
    """Write one card per program.  ``prune=True`` (the full-set CLI
    path) also DELETES ``*.json`` cards for programs not in ``irs`` —
    an orphan card for a renamed/removed program would keep
    "documenting" a dead program forever, the exact stale-allowance
    smell the suppression/baseline ratchets exist to reject.  Subset
    runs (``--programs``) must not prune, and ``keep`` names card files
    of programs that still EXIST but this host couldn't build (a
    backend-skipped sharded dp) — live, never orphans."""
    os.makedirs(directory, exist_ok=True)
    paths = []
    kept = set(keep)
    for ir_prog in irs:
        fname = card_filename(ir_prog.name)
        kept.add(fname)
        path = os.path.join(directory, fname)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(build_card(ir_prog), fh, indent=1, sort_keys=True)
            fh.write("\n")
        paths.append(path)
    if prune:
        for fname in sorted(os.listdir(directory)):
            if fname.endswith(".json") and fname not in kept:
                os.remove(os.path.join(directory, fname))
    return paths


def load_card(path: str) -> Dict:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)
