"""The canonical program set: the manifest the CI gate audits.

One tiny representative per steady-state program class the framework
ships — dense / ZeRO-3-sharded (dp=2, dp=4) / bf16 train steps, the
serving forward, and the generation programs (the paged-KV
prefill/decode pair) — driven through the
REAL production entry points (``fit``, ``ShardedTrainer.fit``, the
``serve`` jit, ``GenerationEngine.warmup``), so the audited jaxprs are
the very traces production executes, not hand-built fixtures.  The
dense and sharded runs deliberately share one topology: they exercise
the PR-12 contract that sharding lives in the ARGUMENTS (one trace,
three recorded specs at mesh sizes 1/2/4).

Suppressions declared here are the manifest's inline pragmas — each
with its mandatory justification, right next to the programs they
cover.  They are added CONDITIONALLY (the CPU-only donation skips exist
only on the CPU backend), so on a backend where the finding cannot
fire, the allowance is never declared and can never go stale-but-armed.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .audit import AuditConfig, AuditProgram, Suppression

__all__ = ["CANONICAL_CONFIG", "CanonicalSet", "build_canonical",
           "CANONICAL_PROGRAM_NAMES", "BUDGETS_PATH", "CARDS_DIR"]

#: the checked-in per-program IR budgets (AX008 + the --diff-cards
#: gate) and the committed card directory (AX010)
BUDGETS_PATH = os.path.join(os.path.dirname(__file__), "budgets.json")
CARDS_DIR = os.path.join(os.path.dirname(__file__), "cards")


def _peak_budgets() -> Optional[Dict[str, int]]:
    """``peak_live_bytes`` ceilings from budgets.json (AX008's input);
    None (rule disabled) when the file is absent/unreadable — the
    --diff-cards gate separately refuses to run without budgets, so a
    deleted budgets file cannot silently green the gate."""
    try:
        with open(BUDGETS_PATH, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        return {name: int(row["peak_live_bytes"])
                for name, row in data.get("programs", {}).items()
                if row.get("peak_live_bytes") is not None}
    except (OSError, ValueError, KeyError, TypeError):
        return None


#: the canonical set audits TOY programs, so the donation-threshold
#: teeth come from a low floor (the serve batch is ~512 bytes; at the
#: default 1 MiB nothing toy-sized would ever exercise AX005/AX007);
#: the committed budgets/cards arm AX008/AX010 on every canonical audit
CANONICAL_CONFIG = AuditConfig(min_donate_bytes=256,
                               peak_live_budgets=_peak_budgets(),
                               cards_dir=CARDS_DIR)

CANONICAL_PROGRAM_NAMES = (
    "train_step[dense]", "train_step[zero3,dp=2]", "train_step[zero3,dp=4]",
    "train_step[bf16]", "train_step[f16]", "serve",
    "paged_prefill", "paged_decode", "train_step[embedding_zero3]",
)

_FEATURES, _CLASSES, _HIDDEN, _BATCH = 16, 8, 32, 8
#: the sparse-embedding canonical program's table: big enough that a
#: dense [vocab, dim] collective would dwarf every legitimate
#: touched-rows block (the no-dense-exchange pin in tests/test_audit.py)
EMBED_VOCAB, EMBED_DIM = 256, 8


def _mlp(precision: Optional[str] = None, seed: int = 19):
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.updaters import Adam
    from deeplearning4j_tpu.nn.layers.feedforward import (DenseLayer,
                                                          OutputLayer)

    b = (NeuralNetConfiguration.builder().seed(seed)
         .updater(Adam(learning_rate=0.02)))
    if precision is not None:
        b = b.precision(precision)
    lb = b.list()
    lb.layer(DenseLayer(n_out=_HIDDEN, activation="tanh"))
    lb.layer(OutputLayer(n_out=_CLASSES, activation="softmax",
                         loss="mcxent"))
    conf = lb.set_input_type(InputType.feed_forward(_FEATURES)).build()
    return MultiLayerNetwork(conf).init()


def _batch(n: int = _BATCH, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, _FEATURES)).astype(np.float32)
    y = np.eye(_CLASSES, dtype=np.float32)[
        rng.integers(0, _CLASSES, n)]
    return x, y


def _spec_mesh_size(spec) -> int:
    import jax

    size = 1
    for leaf in jax.tree_util.tree_leaves(spec):
        mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        if mesh is not None:
            size = max(size, int(mesh.size))
    return size


def _pick_spec(entry, mesh_size: int):
    """Newest recorded spec whose largest mesh is ``mesh_size``."""
    for spec in reversed(entry.audit_specs()):
        if _spec_mesh_size(spec) == mesh_size:
            return spec
    raise LookupError(
        f"no recorded spec of {entry.name} at mesh size {mesh_size} "
        f"(have {[_spec_mesh_size(s) for s in entry.audit_specs()]})")


def _pick_largest_prefill(entry):
    """The top-bucket prefill variant (tokens arg has the widest T)."""
    best, best_t = None, -1
    for spec in entry.audit_specs():
        args, _ = spec
        tokens = args[2]
        t = int(getattr(tokens, "shape", (0, 0))[1])
        if t > best_t:
            best, best_t = spec, t
    if best is None:
        raise LookupError("no prefill spec recorded")
    return best


@dataclass
class CanonicalSet:
    """The built canonical set, with its coverage made EXPLICIT: a
    wanted program this host could not build lands in ``skipped`` with
    the reason — consumers (CLI card pruning, the ``audit_time_ms``
    bench row) must never mistake reduced coverage for the full set."""
    programs: List[AuditProgram]
    suppressions: List[Suppression]
    skipped: Dict[str, str] = field(default_factory=dict)


def build_canonical(include: Optional[Sequence[str]] = None,
                    dps: Tuple[int, ...] = (2, 4)) -> CanonicalSet:
    """Build (driving real fits/serves/generates) the canonical program
    set plus its manifest suppressions.

    ``include``: optional substrings — only programs whose name contains
    one are built (the golden-census test builds just the zero3 pair).
    Sharded programs are skipped (not errored) when the backend exposes
    fewer devices than ``dp``; generation programs when the model /
    generation extras are unavailable — each skip is recorded in
    ``CanonicalSet.skipped`` with its reason.
    """
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn import compile_cache as cc

    def want(name: str) -> bool:
        return include is None or any(s in name for s in include)

    want_dense = want("train_step[dense]") or want("serve")
    want_sharded = [dp for dp in dps if want(f"train_step[zero3,dp={dp}]")]
    programs: List[AuditProgram] = []
    sups: List[Suppression] = []
    skipped: Dict[str, str] = {}
    cpu = jax.default_backend() == "cpu"
    prev_mode = cc.audit_capture_mode()
    cc.set_audit_capture("all")
    try:
        x, y = _batch()
        if want_dense or want_sharded:
            # ONE topology for dense + every dp: the sharded specs are
            # extra recorded layouts of the same single trace
            net = _mlp()
            entry = None
            if want_dense:
                net.fit(x, y)
                entry = net._get_jitted("train_step")
            if want("train_step[dense]"):
                programs.append(AuditProgram(
                    "train_step[dense]", entry, _pick_spec(entry, 1)))
            for dp in want_sharded:
                if len(jax.devices()) < dp:
                    skipped[f"train_step[zero3,dp={dp}]"] = \
                        f"needs >= {dp} devices, have {len(jax.devices())}"
                    continue
                from deeplearning4j_tpu.parallel import (ShardedTrainer,
                                                         make_mesh)
                net_s = _mlp()
                st = ShardedTrainer(net_s, make_mesh(dp=dp),
                                    min_shard_size=0)
                st.fit(x, y)
                entry = net_s._get_jitted("train_step")
                programs.append(AuditProgram(
                    f"train_step[zero3,dp={dp}]", entry,
                    _pick_spec(entry, dp), zero3=True))
            if want("serve"):
                serve = net._get_jitted("serve")
                serve(net.params, net.state, jnp.asarray(x))
                programs.append(AuditProgram(
                    "serve", serve, _pick_spec(serve, 1)))
                if cpu:
                    sups.append(Suppression(
                        "serve", "AX005",
                        "CPU implements no buffer donation; the serve "
                        "builder deliberately skips donate_argnums there "
                        "(nn/multilayer._build_stack_fn 'serve' branch) — "
                        "on TPU the padded batch IS donated"))
        if want("train_step[embedding_zero3]"):
            # the first structurally-sparse parameter: a sparse_grad
            # embedding table row-sharded over dp=2 — the program whose
            # card pins that NO collective carries O(vocab·dim) bytes
            # (the densified touched-rows exchange, arxiv 1905.04035,
            # derived by GSPMD from the zero3 argument shardings)
            if len(jax.devices()) < 2:
                skipped["train_step[embedding_zero3]"] = \
                    f"needs >= 2 devices, have {len(jax.devices())}"
            else:
                import numpy as np

                from deeplearning4j_tpu import (InputType,
                                                MultiLayerNetwork,
                                                NeuralNetConfiguration)
                from deeplearning4j_tpu.nn.conf.updaters import Adam
                from deeplearning4j_tpu.nn.layers.feedforward import (
                    EmbeddingLayer, OutputLayer)
                from deeplearning4j_tpu.parallel import (ShardedTrainer,
                                                         make_mesh)

                lb = (NeuralNetConfiguration.builder().seed(23)
                      .updater(Adam(learning_rate=0.02)).list())
                lb.layer(EmbeddingLayer(n_in=EMBED_VOCAB, n_out=EMBED_DIM,
                                        sparse_grad=True))
                lb.layer(OutputLayer(n_out=_CLASSES,
                                     activation="softmax", loss="mcxent"))
                net_e = MultiLayerNetwork(lb.build()).init()
                rng = np.random.default_rng(7)
                ids = rng.integers(0, EMBED_VOCAB,
                                   (_BATCH, 1)).astype(np.int32)
                ye = np.eye(_CLASSES, dtype=np.float32)[
                    rng.integers(0, _CLASSES, _BATCH)]
                st_e = ShardedTrainer(net_e, make_mesh(dp=2),
                                      min_shard_size=0)
                st_e.fit(ids, ye)
                entry_e = net_e._get_jitted("train_step")
                programs.append(AuditProgram(
                    "train_step[embedding_zero3]", entry_e,
                    _pick_spec(entry_e, 2), zero3=True))
        # the two low-precision variants: bf16 (no scaling) and f16
        # (dynamic loss scaling — its traced unscale/overflow-skip path
        # is where cast churn would live)
        for prec in ("bfloat16", "float16"):
            name = f"train_step[{'bf16' if prec == 'bfloat16' else 'f16'}]"
            if not want(name):
                continue
            net_p = _mlp(precision=prec)
            net_p.fit(x, y)
            entry_p = net_p._get_jitted("train_step")
            programs.append(AuditProgram(
                name, entry_p, _pick_spec(entry_p, 1), policy=prec))
        gen_names = ("paged_prefill", "paged_decode")
        if any(want(n) for n in gen_names):
            try:
                from deeplearning4j_tpu.generation import (
                    GenerationConfig, GenerationEngine)
                from deeplearning4j_tpu.models import TransformerLM
            except ImportError as e:
                for name in gen_names:
                    if want(name):
                        skipped[name] = \
                            f"generation/model extras unavailable: {e}"
                return CanonicalSet(programs, sups, skipped)

            lm = TransformerLM(vocab_size=17, seq_len=16, embed=16,
                               n_layers=2, n_heads=2).init()
            if want("paged_prefill") or want("paged_decode"):
                eng_p = GenerationEngine.for_model(
                    lm, GenerationConfig(max_slots=2, max_seq=16,
                                         block_size=4))
                try:
                    eng_p.warmup()
                    eng_p.generate([3, 1, 4], max_new_tokens=2)
                finally:
                    eng_p.shutdown()
            if want("paged_prefill"):
                ppf = lm._get_jitted("paged_prefill")
                programs.append(AuditProgram(
                    "paged_prefill", ppf, _pick_largest_prefill(ppf)))
                if cpu:
                    sups.append(Suppression(
                        "paged_prefill", "AX005",
                        "CPU implements no buffer donation; "
                        "generation/programs.build_generation_fn skips "
                        "donating the block pool there — on TPU both "
                        "paged programs donate it"))
                    sups.append(Suppression(
                        "paged_prefill", "AX007",
                        "same CPU no-donation skip, exact-solver form: "
                        "the lifetime solver proves the threaded block "
                        "pool (arg 4) donatable, and on TPU it IS "
                        "donated — CPU cannot alias buffers"))
            if want("paged_decode"):
                pdec = lm._get_jitted("paged_decode")
                programs.append(AuditProgram(
                    "paged_decode", pdec, pdec.audit_specs()[-1]))
                if cpu:
                    sups.append(Suppression(
                        "paged_decode", "AX005",
                        "CPU implements no buffer donation; "
                        "generation/programs.build_generation_fn skips "
                        "donating the block pool there — on TPU both "
                        "paged programs donate it"))
                    sups.append(Suppression(
                        "paged_decode", "AX007",
                        "same CPU no-donation skip, exact-solver form: "
                        "the lifetime solver proves the threaded block "
                        "pool (arg 3) donatable, and on TPU it IS "
                        "donated — CPU cannot alias buffers"))
    finally:
        cc.set_audit_capture(prev_mode)
    return CanonicalSet(programs, sups, skipped)
