"""The ONE optimized-HLO-text extraction path.

Every consumer of "the HLO of what the process actually compiled" goes
through this module: the audit's HLO phase (collective census / flops /
temp bytes in ``audit.analyze_program``), ``tools/dump_hlo.py`` (the
bench train-step dump), and ``tools/trace_top_ops.py`` (profile-trace
fusion attribution).  All of them used to re-spell the same pair —
``iter_trace_cache()`` to find the entry, ``entry.audit_lower(spec)``
to re-lower the recorded call — or worse, hand-rolled a ``.lower()``
with a fresh RNG key that compiled a program subtly different from the
one production ran.  One spelling means one set of invariants: the
audit lowering never ticks the compile counters, always lowers the
DECLARED donation (the contract under test, even where the platform
skipped it), and always describes a call that actually happened.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterator, Optional, Sequence

from . import hlo as HLO

__all__ = ["ExtractedHLO", "extract_hlo", "iter_trace_cache_hlo"]


@dataclass
class ExtractedHLO:
    """Optimized HLO text + the executable summaries every tool reads."""

    name: str
    entry: Any                       # the InstrumentedJit that owns it
    spec: Any                        # the recorded (args, kwargs) spec
    compiled: Any                    # jax.stages.Compiled
    hlo_text: str
    flops: Optional[float]
    temp_bytes: Optional[int]

    def cost_analysis(self) -> Dict[str, Any]:
        """The backend cost model's row for the executable ({} when the
        backend doesn't report one — callers print, never branch)."""
        try:
            ca = self.compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return dict(ca)
        except Exception:
            return {}


def extract_hlo(entry: Any, spec: Any,
                name: Optional[str] = None) -> ExtractedHLO:
    """Re-lower one recorded audit spec through ``entry.audit_lower``
    (fresh jit, declared donation, no counter ticks), compile it, and
    return the optimized HLO text with flops / temp-bytes attached."""
    lowered = entry.audit_lower(spec)
    compiled = HLO.compile_lowered(lowered)
    return ExtractedHLO(
        name=name or getattr(entry, "name", "<entry>"),
        entry=entry, spec=spec, compiled=compiled,
        hlo_text=compiled.as_text(),
        flops=HLO.compiled_flops(compiled),
        temp_bytes=HLO.compiled_temp_bytes(compiled))


def iter_trace_cache_hlo(kinds: Optional[Sequence[str]] = None
                         ) -> Iterator[ExtractedHLO]:
    """Extracted HLO for every recorded spec of every live trace-cache
    entry (optionally filtered to entry ``kinds``) — the in-process
    spelling the profiling tools use: whatever program the process
    really ran, re-lowered from its recorded call, never a
    hand-reconstructed approximation."""
    from deeplearning4j_tpu.nn.compile_cache import iter_trace_cache

    seen: Dict[str, int] = {}
    for _key, entry in iter_trace_cache():
        if kinds is not None and entry.name not in kinds:
            continue
        for spec in entry.audit_specs():
            i = seen.get(entry.name, 0)
            seen[entry.name] = i + 1
            yield extract_hlo(entry, spec, name=f"{entry.name}#{i}")
