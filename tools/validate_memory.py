import json
import numpy as np
import jax.numpy as jnp
from deeplearning4j_tpu.models import ResNet50, LeNet
from deeplearning4j_tpu.nn.conf.memory import (memory_report,
                                               memory_report_graph,
                                               xla_memory_report)

rng = np.random.default_rng(0)

net = ResNet50(num_classes=1000, compute_dtype="bfloat16",
               input_shape=(224, 224, 3)).init()
rep = memory_report_graph(net.conf)
batch = 128
x = rng.standard_normal((batch, 224, 224, 3), dtype=np.float32)
y = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)]
exact = xla_memory_report(net, [x], [y])
pred = rep.total_memory_bytes(batch)
print(json.dumps({"model": "resnet50_bf16_b128",
                  "analytic_upper_MiB": round(pred / 2**20, 1),
                  "xla_total_MiB": round(exact["total_bytes"] / 2**20, 1),
                  "params": rep.total_params,
                  "ratio": round(pred / exact["total_bytes"], 3)}))
# param+updater accounting vs XLA argument bytes (minus the data args)
data_bytes = x.nbytes + y.nbytes + 8
pred_args = (rep.total_params * 4 + rep.total_updater_elems * 4)
print(json.dumps({"check": "resnet50 params+updater vs XLA args",
                  "pred_MiB": round(pred_args / 2**20, 1),
                  "xla_MiB": round((exact["argument_bytes"] - data_bytes) / 2**20, 1),
                  "rel_err": round(abs(pred_args - (exact["argument_bytes"] - data_bytes))
                                   / (exact["argument_bytes"] - data_bytes), 4)}))
del net

net = LeNet().init()
rep2 = memory_report(net.conf)
x = rng.standard_normal((128, 28, 28, 1), dtype=np.float32)
y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 128)]
exact2 = xla_memory_report(net, x, y)
pred2 = rep2.total_memory_bytes(128)
data2 = x.nbytes + y.nbytes + 8
pred_args2 = rep2.total_params * 4 + rep2.total_updater_elems * 4
print(json.dumps({"model": "lenet_f32_b128",
                  "analytic_upper_MiB": round(pred2 / 2**20, 1),
                  "xla_total_MiB": round(exact2["total_bytes"] / 2**20, 1),
                  "args_rel_err": round(abs(pred_args2 - (exact2["argument_bytes"] - data2))
                                        / (exact2["argument_bytes"] - data2), 4)}))
