# Makes `tools` importable so `python -m tools.graftlint` and
# `from tools.graftlint import lint_source` work from the repo root.
