"""Attribute the busiest device track's fusions to HLO computations.

Usage: ``python -m tools.trace_top_ops TRACE.json.gz [HLO_PATH|bench]``

``bench`` (the default when HLO_PATH is omitted) re-extracts the bench
train-step HLO through the one extraction path
(``tools/graftaudit/extract.py`` — the same ``iter_trace_cache`` +
``audit_lower`` pair dump_hlo and the graftaudit HLO phase use), so the
computation names match the program the profiled process compiled.
"""
import gzip
import json
import re
import sys
from collections import defaultdict


def _load_hlo(arg: str) -> str:
    if arg != "bench":
        return open(arg).read()
    import jax.numpy as jnp
    from deeplearning4j_tpu.models import available_bench_model
    from tools.graftaudit.extract import iter_trace_cache_hlo

    model, (x, y) = available_bench_model(batch=256, image=224)
    model.fit(jnp.asarray(x), jnp.asarray(y))
    exs = list(iter_trace_cache_hlo(kinds=("train_step",)))
    assert exs, "no train_step in the trace cache after fit()"
    return exs[-1].hlo_text


trace_path = sys.argv[1]
hlo = _load_hlo(sys.argv[2] if len(sys.argv) > 2 else "bench")
with gzip.open(trace_path, "rt") as f:
    events = json.load(f)["traceEvents"]
comps = {}
for m in re.finditer(r"^(?:ENTRY )?%?([\w.\-]+)(?: \([^)]*\))? -> ([^\n{]+)\{\n(.*?)^\}", hlo, re.M | re.S):
    comps[m.group(1)] = (m.group(2), m.group(3))
fusion_calls = dict(re.findall(r"%?([\w.\-]+) = [^\n]*fusion\([^\n]*calls=%?([\w.\-]+)", hlo))

def conv_shapes(cname):
    body = comps.get(cname, ("", ""))[1]
    out = []
    for m in re.finditer(r"= (\S+) convolution\(([^)]*)\)[^\n]*window={([^}]*)}", body):
        out.append(f"{m.group(1)} win[{m.group(3)[:40]}]")
    for sub in re.findall(r"calls=%?([\w.\-]+)", body):
        out.extend(conv_shapes(sub))
    return out

# pick the busiest device track (tids vary across traces — same approach
# as trace_categorize.py)
dev_pids = {e["pid"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
            and "TPU" in e["args"].get("name", "")}
track_tot = defaultdict(float)
for e in events:
    if e.get("ph") == "X" and e.get("pid") in dev_pids:
        track_tot[(e["pid"], e["tid"])] += e.get("dur", 0)
busiest = max(track_tot, key=track_tot.get)
agg = defaultdict(float)
for e in events:
    if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) == busiest:
        agg[e["name"]] += e.get("dur", 0)

def pick(pred, n=18):
    rows = []
    for name, dur in sorted(agg.items(), key=lambda kv: -kv[1]):
        base = name.split("(")[0]
        comp = fusion_calls.get(base)
        if comp is None: continue
        body = comps.get(comp, ("", ""))[1]
        kinds = set(re.findall(r"= (?:\([^)]*\)|\S+?) ([a-z][\w\-]*)[\(.]", body))
        for sub in re.findall(r"calls=%?([\w.\-]+)", body):
            kinds |= set(re.findall(r"= (?:\([^)]*\)|\S+?) ([a-z][\w\-]*)[\(.]", comps.get(sub, ("",""))[1]))
        if not pred(kinds): continue
        cs = conv_shapes(comp)
        rows.append((dur/3e3, name, cs[:2]))
        if len(rows) >= n: break
    return rows

print("== top conv fusions ==")
for d, n, cs in pick(lambda k: "convolution" in k):
    print(f"  {d:6.2f} ms  {n[:28]:30s} {cs}")
print("== top elementwise (no conv/reduce) ==")
for d, n, cs in pick(lambda k: "convolution" not in k and "reduce" not in k, 12):
    print(f"  {d:6.2f} ms  {n[:40]}")
