"""One-shot fit-loop throughput probe for the r18 pipelining sweep.

Run in a FRESH interpreter per measurement (heap/cache isolation —
same rationale as ``obs_overhead_ms(isolate=True)``):

    python tools/bench_sweep_r18.py <dispatch|compute> [fits]

Prints one JSON line: median steady examples/sec over ``fits`` fit()
calls after a 2-batch warm.  The dispatch-bound arm is the tiny-MLP
geometry where the step is microseconds and the loop pays host work;
the compute-bound arm is the MLP-256 geometry where the device math
dominates.  The depth knob under test rides the normal
``DL4J_TPU_DISPATCH_DEPTH`` env var, read by the fit loop itself.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# resolve the package from the CURRENT working tree, not this file's
# location — the r18 sweep runs a /tmp copy of this script against
# stashed (pre-PR) and unstashed (post-PR) checkouts of the same repo
sys.path.insert(0, os.getcwd())

import numpy as np  # noqa: E402

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.updaters import Adam  # noqa: E402
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer  # noqa: E402


def main():
    arm = sys.argv[1] if len(sys.argv) > 1 else "dispatch"
    fits = int(sys.argv[2]) if len(sys.argv) > 2 else 9
    if arm == "dispatch":
        hidden, features, classes, batch, nb = 16, 16, 4, 16, 200
    else:
        hidden, features, classes, batch, nb = 256, 128, 10, 128, 60
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=0.01)).list()
            .layer(DenseLayer(n_out=hidden, activation="tanh"))
            .layer(DenseLayer(n_out=hidden, activation="tanh"))
            .layer(OutputLayer(n_out=classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(features)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(13)
    batches = [(rng.standard_normal((batch, features)).astype(np.float32),
                np.eye(classes, dtype=np.float32)[
                    rng.integers(0, classes, batch)])
               for _ in range(nb)]
    net.fit(iter(batches[:2]), epochs=1)          # compile + warm
    rates = []
    for _ in range(fits):
        t0 = time.perf_counter()
        net.fit(iter(batches), epochs=1)
        rates.append(nb * batch / (time.perf_counter() - t0))
    print(json.dumps({
        "arm": arm,
        "depth_env": os.environ.get("DL4J_TPU_DISPATCH_DEPTH"),
        "examples_per_sec": round(float(np.median(rates)), 1),
        "spread": round((max(rates) - min(rates)) / float(np.median(rates)),
                        3),
        "fits": fits, "batches_per_fit": nb, "batch": batch,
    }))


if __name__ == "__main__":
    main()
