import gzip, json, re, sys
from collections import defaultdict

trace_path, hlo_path = sys.argv[1], sys.argv[2]
with gzip.open(trace_path, "rt") as f:
    events = json.load(f)["traceEvents"]

# tid metadata to understand tracks
tids = {}
for e in events:
    if e.get("ph") == "M" and e.get("name") == "thread_name":
        tids[(e["pid"], e["tid"])] = e["args"].get("name", "")
dev_pids = {e["pid"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
            and "TPU" in e["args"].get("name", "")}
print("device tracks:", {k: v for k, v in tids.items() if k[0] in dev_pids})

hlo = open(hlo_path).read()
comps = {}
for m in re.finditer(r"^(?:ENTRY )?%?([\w.\-]+)(?: \([^)]*\))? -> [^\n{]+\{\n(.*?)^\}", hlo, re.M | re.S):
    comps[m.group(1)] = m.group(2)
fusion_calls = dict(re.findall(r"%?([\w.\-]+) = [^\n]*fusion\([^\n]*calls=%?([\w.\-]+)", hlo))

def comp_kinds(cname, depth=0):
    body = comps.get(cname, "")
    kinds = set(re.findall(r"= (?:\([^)]*\)|\S+?) ([a-z][\w\-]*)[\(.]", body))
    if depth < 2:
        for sub in re.findall(r"calls=%?([\w.\-]+)", body):
            kinds |= comp_kinds(sub, depth + 1)
    return kinds

def categorize(name):
    base = name.split("(")[0]
    comp = fusion_calls.get(base)
    if comp:
        kinds = comp_kinds(comp)
        if "convolution" in kinds: return "conv"
        if "dot" in kinds: return "dot"
        if "reduce" in kinds: return "bn_reduce"
        if "reduce-window" in kinds or "select-and-scatter" in kinds: return "pool"
        return "elementwise"
    if "convolution" in base: return "conv"
    if "select-and-scatter" in base or "reduce-window" in base: return "pool"
    if "copy" in base: return "copy"
    if "all-reduce" in base or "all-gather" in base: return "collective"
    if base in ("jit_step",) or base.isdigit(): return "SKIP"
    if "reduce" in base: return "bn_reduce"
    return "misc:" + base[:18]

# use only one track per pid=3: pick the track with max total to avoid dup lanes
track_tot = defaultdict(float)
for e in events:
    if e.get("ph") == "X" and e.get("pid") in dev_pids:
        track_tot[e["tid"]] += e.get("dur", 0)
print("track totals (ms):", {t: round(v/1e3,1) for t, v in sorted(track_tot.items())})

for chosen in sorted(track_tot, key=lambda t: -track_tot[t]):
    agg = defaultdict(float); cnt = defaultdict(int)
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in dev_pids and e["tid"] == chosen:
            c = categorize(e["name"])
            agg[c] += e.get("dur", 0); cnt[c] += 1
    tot = sum(v for k, v in agg.items() if k != "SKIP")
    print(f"\ntrack {chosen} ({tids.get((3,chosen),'')}): {tot/3e3:.1f} ms/step attributed")
    for c, v in sorted(agg.items(), key=lambda kv: -kv[1]):
        print(f"  {v/3e3:8.2f} ms/step x{cnt[c]//3:4d} {c}")
