"""Generate golden serialization fixtures (reference ``regressiontest/`` +
dl4j-test-resources role).

Run ONCE per new fixture version under the same environment the test suite
uses (CPU backend, x64 enabled — tests/conftest.py), then COMMIT the
outputs; later rounds must load them unchanged.  Never regenerate an
existing fixture to make a failing test pass — that inverts the contract.

    env PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python tools/make_golden_fixtures.py cnn transformer
"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)    # match tests/conftest.py

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from deeplearning4j_tpu import InputType  # noqa: E402
from deeplearning4j_tpu.nn.conf.multi_layer import \
    NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.conf.updaters import Adam  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.utils.model_serializer import \
    write_model  # noqa: E402

RES = "tests/resources"


def make_cnn():
    """Conv + BatchNormalization + pooling golden model — the layer family
    most exposed to perf work (ResNet50 campaign) and previously absent
    from the serde-stability net."""
    from deeplearning4j_tpu.nn.layers import (BatchNormalization,
                                              ConvolutionLayer, DenseLayer,
                                              OutputLayer, SubsamplingLayer)
    conf = (NeuralNetConfiguration.builder()
            .seed(20260731).activation("relu").weight_init("xavier")
            .updater(Adam(learning_rate=0.01))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3)))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=10))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((16, 8, 8, 1)), jnp.float32)
    y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)])
    for _ in range(5):
        net.fit_batch((x, y))          # Adam moments + BN running stats
    write_model(net, f"{RES}/golden_cnn_v1.zip")
    probe = jnp.asarray(rng.standard_normal((4, 8, 8, 1)), jnp.float32)
    np.savez(f"{RES}/golden_cnn_v1_io.npz", probe=np.asarray(probe),
             output=np.asarray(net.output(probe)))
    print("wrote golden_cnn_v1")


def make_transformer():
    """Transformer golden model with an explicit KV-cache capacity
    (max_cache_len) in the config — covers the attention-layer serde
    surface (attn_impl/flash_min_seq fields) and incremental-decode
    configuration."""
    from deeplearning4j_tpu.nn.layers.attention import (
        PositionalEncodingLayer, TransformerBlock)
    from deeplearning4j_tpu.nn.layers.feedforward import \
        EmbeddingSequenceLayer
    from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
    V, T = 32, 12
    conf = (NeuralNetConfiguration.builder()
            .seed(20260731).weight_init("xavier")
            .updater(Adam(learning_rate=1e-3))
            .list()
            .layer(EmbeddingSequenceLayer(n_out=16))
            .layer(PositionalEncodingLayer())
            .layer(TransformerBlock(n_heads=2, causal=True,
                                    attn_impl="reference",
                                    max_cache_len=24))
            .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(V, T))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(11)
    ids = rng.integers(0, V, (8, T + 1))
    x = jnp.asarray(ids[:, :-1])
    y = jnp.asarray(np.eye(V, dtype=np.float32)[ids[:, 1:]])
    for _ in range(5):
        net.fit_batch((x, y))
    write_model(net, f"{RES}/golden_transformer_v1.zip")
    probe = jnp.asarray(rng.integers(0, V, (3, T)))
    np.savez(f"{RES}/golden_transformer_v1_io.npz", probe=np.asarray(probe),
             output=np.asarray(net.output(probe)))
    print("wrote golden_transformer_v1")


if __name__ == "__main__":
    targets = sys.argv[1:] or ["cnn", "transformer"]
    for t in targets:
        {"cnn": make_cnn, "transformer": make_transformer}[t]()
