"""Word2Vec throughput bench — thin CLI over
deeplearning4j_tpu.utils.benchmarks.word2vec_words_per_sec (the BASELINE.md
words/sec target; parity bar SkipGram.java:271-283)."""
import json, os, sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from deeplearning4j_tpu.utils.benchmarks import word2vec_words_per_sec

print(json.dumps(word2vec_words_per_sec(
    vocab=int(os.environ.get("W2V_VOCAB", "5000")),
    n_sent=int(os.environ.get("W2V_SENT", "20000")),
    sent_len=int(os.environ.get("W2V_SLEN", "20")),
    epochs=int(os.environ.get("W2V_EPOCHS", "1")))))
