"""Word2Vec throughput bench (BASELINE.md words/sec target).

Synthetic zipf corpus, 5k vocab / layer 128 / window 5 / negative 5 —
the BENCH_NOTES round-1 configuration.  Reports steady-state words/sec
(post-compile: the first fit compiles the scan kernel, then weights are
reset and a second identical fit is timed) plus the cold number.
Parity bar: the reference's native batched AggregateSkipGram hot loop
(``SkipGram.java:271-283``).
"""
import os, sys, time
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

V = int(os.environ.get("W2V_VOCAB", "5000"))
NSENT = int(os.environ.get("W2V_SENT", "20000"))
SLEN = int(os.environ.get("W2V_SLEN", "20"))
EPOCHS = int(os.environ.get("W2V_EPOCHS", "1"))

rng = np.random.default_rng(0)
ids = np.clip(rng.zipf(1.3, size=NSENT * SLEN), 1, V) - 1
sents = ["w%d" % i for i in ids]
sentences = [" ".join(sents[i * SLEN:(i + 1) * SLEN]) for i in range(NSENT)]
total_words = NSENT * SLEN * EPOCHS

w2v = Word2Vec(sentences=sentences, layer_size=128, window=5, negative=5,
               epochs=EPOCHS, seed=1, min_word_frequency=1)
w2v.build_vocab()

# cold fit (includes the one-time scan-kernel compile)
t0 = time.perf_counter()
w2v.fit()
cold = time.perf_counter() - t0

# steady state: same jitted shapes (vocab unchanged), fresh weights
w2v.lookup_table.reset_weights()
t0 = time.perf_counter()
w2v.fit()
dt = time.perf_counter() - t0
print(f"steady: {total_words/dt:.0f} words/sec ({total_words} words in "
      f"{dt:.2f}s); cold: {total_words/cold:.0f} words/sec (compile included)")
