import glob, gzip, json, os, time
import jax, jax.numpy as jnp
from deeplearning4j_tpu.models import available_bench_model

model, (x, y) = available_bench_model(batch=256, image=224)
x, y = jnp.asarray(x), jnp.asarray(y)
model.fit(x, y)
step = model._get_jitted("train_step")

def run():
    model._rng, key = jax.random.split(model._rng)
    model.params, model.state, model.opt_state, loss, _ = step(
        model.params, model.state, model.opt_state, key, [x], [y], None, None)
    return loss

for _ in range(3):
    loss = run()
float(jnp.asarray(loss))

jax.profiler.start_trace("/tmp/xprof")
for _ in range(3):
    loss = run()
float(jnp.asarray(loss))
jax.profiler.stop_trace()
print("trace files:", glob.glob("/tmp/xprof/**/*", recursive=True))
