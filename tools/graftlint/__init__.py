"""graftlint — AST-based JAX/TPU correctness linter for deeplearning4j_tpu.

Two phases over one shared parse:

* **Module rules** (JX001–JX017): per-file failure modes a JAX
  reproduction actually hits — tracer leaks across the host/device
  boundary, Python control flow on tracers, hidden host syncs in hot
  loops, silent recompilation, jit impurity, benchmark lies from async
  dispatch, per-iteration host↔device transfers, non-atomic checkpoint
  writes, unbounded retries and queues.
* **Whole-program concurrency rules** (JX018–JX021): package-scope
  analysis (``program.py``) that infers thread-entry functions,
  lock-guarded attributes, and the global lock-order graph, then checks
  lock discipline — inconsistent guarding of shared attributes, leaked
  non-daemon threads, lock-order cycles, and check-then-act races.

Each file is parsed and walked ONCE; every module rule runs off the
shared ``ModuleInfo`` index and the program rules run off the same
parses.

Usage:
    python -m tools.graftlint deeplearning4j_tpu/            # text output
    python -m tools.graftlint --format json|sarif path/to/file.py
    python -m tools.graftlint --changed-only HEAD~1 deeplearning4j_tpu/
    python -m tools.graftlint --write-baseline deeplearning4j_tpu/

Library API:
    from tools.graftlint import lint_source, lint_paths, Finding
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .analysis import ModuleInfo, analyze_module
from .core import (Baseline, Finding, iter_python_files, parse_pragmas,
                   to_sarif)
from .program import build_program
from .rules import PROGRAM_RULES, RULES, RULE_DOCS

__all__ = ["Finding", "Baseline", "RULES", "PROGRAM_RULES", "RULE_DOCS",
           "lint_source", "lint_file", "lint_paths", "iter_python_files",
           "to_sarif"]


def lint_source(source: str, path: str = "<string>",
                select: Optional[Sequence[str]] = None,
                ignore: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one source string (module rules + a one-module program pass);
    returns findings after pragma filtering."""
    findings, parsed = _parse_and_run_module_rules(
        source, path, _active_rules(select, ignore))
    if parsed is not None:
        info, pragmas = parsed
        findings.extend(_run_program_rules(
            [info], {path: pragmas}, _active_program_rules(select, ignore)))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str, select: Optional[Sequence[str]] = None,
              ignore: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    return lint_source(source, path, select=select, ignore=ignore)


def lint_paths(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               ignore: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint files/directories: ONE parse per file shared by every module
    rule and the whole-program concurrency pass."""
    module_rules = _active_rules(select, ignore)
    program_rules = _active_program_rules(select, ignore)
    findings: List[Finding] = []
    infos: List[ModuleInfo] = []
    pragma_index: Dict[str, object] = {}
    for p in iter_python_files(paths):
        with open(p, "r", encoding="utf-8") as fh:
            source = fh.read()
        file_findings, parsed = _parse_and_run_module_rules(
            source, p, module_rules)
        findings.extend(file_findings)
        if parsed is not None:
            info, pragmas = parsed
            infos.append(info)
            pragma_index[p] = pragmas
    findings.extend(_run_program_rules(infos, pragma_index, program_rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _parse_and_run_module_rules(
        source: str, path: str, codes: Sequence[str]
) -> Tuple[List[Finding], Optional[Tuple[ModuleInfo, object]]]:
    try:
        info = analyze_module(source, path)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 1, col=e.offset or 0,
                        rule="JX000", message=f"syntax error: {e.msg}")], None
    pragmas = parse_pragmas(source)
    findings: List[Finding] = []
    for code in codes:
        findings.extend(RULES[code](info))
    findings = [f for f in findings if not pragmas.suppressed(f)]
    return findings, (info, pragmas)


def _run_program_rules(infos: Sequence[ModuleInfo], pragma_index: Dict,
                       codes: Sequence[str]) -> List[Finding]:
    if not codes or not infos:
        return []
    program = build_program(infos)
    findings: List[Finding] = []
    for code in codes:
        findings.extend(PROGRAM_RULES[code](program))
    kept = []
    for f in findings:
        pragmas = pragma_index.get(f.path)
        if pragmas is not None and pragmas.suppressed(f):
            continue
        kept.append(f)
    return kept


def _active_rules(select: Optional[Sequence[str]],
                  ignore: Optional[Sequence[str]]) -> List[str]:
    return _filter_codes(sorted(RULES), select, ignore)


def _active_program_rules(select: Optional[Sequence[str]],
                          ignore: Optional[Sequence[str]]) -> List[str]:
    return _filter_codes(sorted(PROGRAM_RULES), select, ignore)


def _filter_codes(codes: List[str], select: Optional[Sequence[str]],
                  ignore: Optional[Sequence[str]]) -> List[str]:
    if select:
        wanted = {c.strip().upper() for c in select}
        _check_known(wanted, "--select")
        codes = [c for c in codes if c in wanted]
    if ignore:
        dropped = {c.strip().upper() for c in ignore}
        _check_known(dropped, "--ignore")
        codes = [c for c in codes if c not in dropped]
    return codes


def _check_known(codes, flag: str) -> None:
    """A typo'd rule code selecting nothing would gate on thin air."""
    known = set(RULES) | set(PROGRAM_RULES)
    unknown = sorted(c for c in codes if c not in known)
    if unknown:
        raise ValueError(
            f"unknown rule code(s) for {flag}: {', '.join(unknown)} "
            f"(known: {', '.join(sorted(known))})")
