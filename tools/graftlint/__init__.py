"""graftlint — AST-based JAX/TPU correctness linter for deeplearning4j_tpu.

Twelve rules (JX001–JX012) targeting the failure modes a JAX reproduction
actually hits: tracer leaks across the host/device boundary, Python
control flow on tracers, hidden host syncs in hot loops, silent
recompilation, jit impurity, benchmark lies from async dispatch, and
per-iteration host↔device transfers that belong in a prefetch stage.

Usage:
    python -m tools.graftlint deeplearning4j_tpu/            # text output
    python -m tools.graftlint --format json path/to/file.py
    python -m tools.graftlint --write-baseline deeplearning4j_tpu/

Library API:
    from tools.graftlint import lint_source, lint_paths, Finding
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .analysis import analyze_module
from .core import Baseline, Finding, iter_python_files, parse_pragmas
from .rules import RULES, RULE_DOCS

__all__ = ["Finding", "Baseline", "RULES", "RULE_DOCS",
           "lint_source", "lint_file", "lint_paths", "iter_python_files"]


def lint_source(source: str, path: str = "<string>",
                select: Optional[Sequence[str]] = None,
                ignore: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one source string; returns findings after pragma filtering."""
    try:
        info = analyze_module(source, path)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 1, col=e.offset or 0,
                        rule="JX000", message=f"syntax error: {e.msg}")]
    pragmas = parse_pragmas(source)
    active = _active_rules(select, ignore)
    findings: List[Finding] = []
    for code in active:
        findings.extend(RULES[code](info))
    findings = [f for f in findings if not pragmas.suppressed(f)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str, select: Optional[Sequence[str]] = None,
              ignore: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    return lint_source(source, path, select=select, ignore=ignore)


def lint_paths(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               ignore: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for p in iter_python_files(paths):
        findings.extend(lint_file(p, select=select, ignore=ignore))
    return findings


def _active_rules(select: Optional[Sequence[str]],
                  ignore: Optional[Sequence[str]]) -> List[str]:
    codes = sorted(RULES)
    if select:
        wanted = {c.strip().upper() for c in select}
        _check_known(wanted, "--select")
        codes = [c for c in codes if c in wanted]
    if ignore:
        dropped = {c.strip().upper() for c in ignore}
        _check_known(dropped, "--ignore")
        codes = [c for c in codes if c not in dropped]
    return codes


def _check_known(codes, flag: str) -> None:
    """A typo'd rule code selecting nothing would gate on thin air."""
    unknown = sorted(c for c in codes if c not in RULES)
    if unknown:
        raise ValueError(
            f"unknown rule code(s) for {flag}: {', '.join(unknown)} "
            f"(known: {', '.join(sorted(RULES))})")
