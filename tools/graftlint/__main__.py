"""Entry point: ``python -m tools.graftlint <paths>``."""
import sys

from .cli import main

sys.exit(main())
