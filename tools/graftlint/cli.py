"""graftlint command-line interface."""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from . import RULE_DOCS, lint_paths, to_sarif
from .core import Baseline, iter_python_files

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST-based JAX/TPU correctness linter: module rules "
                    "JX001-JX017, JX022-JX032 + whole-program "
                    "concurrency rules JX018-JX021 (see tools/README.md)")
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON of accepted findings "
                        "(default: tools/graftlint/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file and exit")
    p.add_argument("--changed-only", metavar="GIT_REF", default=None,
                   help="lint only files changed vs GIT_REF (plus "
                        "untracked) — CI fast path; the whole-program "
                        "pass sees only the changed subset, so run a "
                        "full lint before merging")
    p.add_argument("--select", default=None,
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rule codes to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def _changed_files(ref: str, files: Sequence[str]) -> List[str]:
    """Intersect ``files`` with paths changed vs ``ref`` (committed,
    staged, working tree) plus untracked files."""
    if not files:
        return []
    # anchor git at the LINTED tree, not the process cwd: a CI step (or
    # operator) standing in a different repo would otherwise diff that
    # repo, intersect nothing, and report "clean" on real findings
    anchor = os.path.realpath(files[0])
    anchor = os.path.dirname(anchor) or "."
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         capture_output=True, text=True, cwd=anchor)
    if top.returncode != 0:
        raise RuntimeError(
            "--changed-only: the linted paths are not inside a git "
            f"repository: {top.stderr.strip()}")
    root = top.stdout.strip()
    changed: set = set()
    # both commands run FROM the repo root: `ls-files --others` scopes
    # (and relativizes) to its cwd, so running it where the operator
    # happens to stand would silently drop untracked files elsewhere in
    # the repo — rooting it makes every output line root-relative
    # core.quotepath=off: default git quotes non-ASCII names into octal
    # escape strings that would never match a real path
    for cmd in (["git", "-c", "core.quotepath=off", "diff",
                 "--name-only", ref, "--"],
                ["git", "-c", "core.quotepath=off", "ls-files",
                 "--others", "--exclude-standard"]):
        r = subprocess.run(cmd, capture_output=True, text=True, cwd=root)
        if r.returncode != 0:
            raise RuntimeError(
                f"--changed-only: `{' '.join(cmd)}` failed: "
                f"{r.stderr.strip()}")
        for line in r.stdout.splitlines():
            if line.strip():
                # realpath BOTH sides: git prints the physical root, while
                # the linted paths may come through a symlink (/tmp on
                # macOS) — logical-vs-physical mismatch must not turn
                # into an empty intersection
                changed.add(os.path.realpath(
                    os.path.join(root, line.strip())))
    return [f for f in files if os.path.realpath(f) in changed]


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for code in sorted(RULE_DOCS):
            print(f"{code}  {RULE_DOCS[code]}")
        return 0
    if not args.paths:
        build_parser().error("the following arguments are required: paths")
    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    if args.write_baseline and args.changed_only is not None:
        build_parser().error(
            "--write-baseline with --changed-only would overwrite the "
            "whole baseline from a changed-files subset; regenerate from "
            "a full run")
    try:
        files = list(iter_python_files(args.paths))
        if args.changed_only is not None:
            files = _changed_files(args.changed_only, files)
            if not files:
                if args.format == "text":
                    print("graftlint: clean (no changed .py files)")
                elif args.format == "json":
                    print("[]")
                else:
                    print(json.dumps(to_sarif([], RULE_DOCS), indent=2))
                return 0
        findings = lint_paths(files, select=select, ignore=ignore)
    except (FileNotFoundError, ValueError, RuntimeError) as e:
        build_parser().error(str(e))

    if args.write_baseline:
        Baseline.from_findings(findings).save(args.baseline)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    stale: List[str] = []
    if not args.no_baseline:
        findings, stale = Baseline.load(args.baseline).apply(findings)

    if args.format == "json":
        print(json.dumps([f.to_json() for f in findings], indent=2))
    elif args.format == "sarif":
        print(json.dumps(to_sarif(findings, RULE_DOCS), indent=2))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        print(f"graftlint: {n} finding(s)" if n else "graftlint: clean")

    # the ratchet: a baseline entry matching nothing means the suppressed
    # finding was fixed — the allowance must be deleted, not left armed to
    # absorb the next regression.  It can only judge what this run could
    # have seen: --changed-only subsets and --select/--ignore runs skip
    # it entirely, and an allowance is stale only when its file was
    # actually linted (or no longer exists at all — deleted/moved files
    # can never match again).
    if stale:
        linted = {os.path.relpath(f).replace(os.sep, "/") for f in files}
        abs_linted = {os.path.abspath(f).replace(os.sep, "/")
                      for f in files}
        # baseline keys are relative to the cwd the baseline was written
        # from.  A key that names a linted file but only as a path SUFFIX
        # (not an exact cwd-relative match) proves this run's cwd is NOT
        # that cwd — no key can be judged from here, so the whole ratchet
        # stands down rather than misread live entries as deleted.
        paths = [k.rsplit("::", 1)[0] for k in stale]
        if any(p not in linted
               and any(a.endswith("/" + p) for a in abs_linted)
               for p in paths):
            stale = []
        else:
            # the deleted-file branch resolves keys against the BASELINE
            # file's own repo root (keys are written repo-root-relative
            # by convention), not the process cwd — from a parent dir a
            # live allowance for an unlinted file would otherwise read as
            # deleted.  Outside git the baseline's directory is the best
            # available anchor.
            bl_dir = os.path.dirname(os.path.abspath(args.baseline)) or "."
            top = subprocess.run(
                ["git", "rev-parse", "--show-toplevel"],
                capture_output=True, text=True, cwd=bl_dir)
            root = top.stdout.strip() if top.returncode == 0 else bl_dir
            stale = [k for k, p in zip(stale, paths)
                     if p in linted
                     or not os.path.exists(os.path.join(root, p))]
    if stale and args.changed_only is None and not select and not ignore:
        print("graftlint: stale baseline entr{} (no matching finding — "
              "remove from {}):".format(
                  "y" if len(stale) == 1 else "ies", args.baseline),
              file=sys.stderr)
        for key in stale:
            print(f"  {key}", file=sys.stderr)
        return 2

    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
