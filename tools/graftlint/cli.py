"""graftlint command-line interface."""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import RULE_DOCS, lint_paths
from .core import Baseline

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST-based JAX/TPU correctness linter "
                    "(rules JX001-JX014; see tools/README.md)")
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON of accepted findings "
                        "(default: tools/graftlint/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file and exit")
    p.add_argument("--select", default=None,
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rule codes to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for code in sorted(RULE_DOCS):
            print(f"{code}  {RULE_DOCS[code]}")
        return 0
    if not args.paths:
        build_parser().error("the following arguments are required: paths")
    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    try:
        findings = lint_paths(args.paths, select=select, ignore=ignore)
    except (FileNotFoundError, ValueError) as e:
        build_parser().error(str(e))

    if args.write_baseline:
        Baseline.from_findings(findings).save(args.baseline)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    if not args.no_baseline:
        findings = Baseline.load(args.baseline).filter(findings)

    if args.format == "json":
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        print(f"graftlint: {n} finding(s)" if n else "graftlint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
