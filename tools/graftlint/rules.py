"""graftlint rule implementations.

Module-local rules JX001–JX017 and JX022–JX030 are functions ``rule(info:
ModuleInfo) -> list[Finding]`` registered in ``RULES``; they share the jit-scope + taint
machinery in ``analysis.py`` (memoized per module, so every rule runs off
one parse and one tree walk).  The whole-program concurrency pack
JX018–JX021 is registered in ``PROGRAM_RULES`` and runs once over the
:class:`~tools.graftlint.program.ProgramModel` built from every linted
module.  See ``tools/README.md`` for the catalog with rationale.
"""
from __future__ import annotations

import ast
import re
from typing import Callable, Dict, List, Optional

from .analysis import ModuleInfo, TaintInfo, call_name, dotted_name
from .core import Finding
from .program import ProgramModel, find_lock_cycles, receiver_is_shared

__all__ = ["RULES", "PROGRAM_RULES", "RULE_DOCS"]

RULES: Dict[str, Callable[[ModuleInfo], List[Finding]]] = {}
PROGRAM_RULES: Dict[str, Callable[[ProgramModel], List[Finding]]] = {}
RULE_DOCS: Dict[str, str] = {}

_HOT_FUNC_RE = re.compile(r"(^|_)(fit|train|step|epoch)", re.IGNORECASE)


def rule(code: str, doc: str):
    def deco(fn):
        RULES[code] = fn
        RULE_DOCS[code] = doc
        return fn
    return deco


def program_rule(code: str, doc: str):
    def deco(fn):
        PROGRAM_RULES[code] = fn
        RULE_DOCS[code] = doc
        return fn
    return deco


def _finding(info: ModuleInfo, node: ast.AST, code: str, msg: str) -> Finding:
    return Finding(path=info.path, line=getattr(node, "lineno", 1),
                   col=getattr(node, "col_offset", 0), rule=code, message=msg)


def _finding_at(path: str, node: ast.AST, code: str, msg: str) -> Finding:
    return Finding(path=path, line=getattr(node, "lineno", 1),
                   col=getattr(node, "col_offset", 0), rule=code, message=msg)


def _jit_scope_taints(info: ModuleInfo) -> Dict[ast.AST, TaintInfo]:
    return {f: info.taint(f) for f in info.jit_scopes}


def _in_loop_same_function(info: ModuleInfo, node: ast.AST) -> bool:
    """Is ``node`` inside a for/while loop without crossing a function
    boundary? (A jit() in a loop body recompiles per iteration only if
    the loop actually re-executes the call.)"""
    cur = info.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.Module)):
            return False
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return True
        cur = info.parent(cur)
    return False


# --------------------------------------------------------------------- JX001
@rule("JX001", "host numpy call on a traced value inside a jit scope")
def jx001(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    taints = _jit_scope_taints(info)
    for func, taint in taints.items():
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            if info.enclosing_function(node) not in taints:
                continue
            fname = call_name(node)
            if not fname:
                continue
            root = fname.split(".")[0]
            if root not in info.numpy_aliases or "." not in fname:
                continue
            args = list(node.args) + [k.value for k in node.keywords]
            if any(taints[info.enclosing_function(node)].expr_tainted(a)
                   for a in args):
                out.append(_finding(
                    info, node, "JX001",
                    f"host-numpy call `{fname}` on a traced value inside a "
                    "jit scope: runs at trace time on abstract tracers "
                    "(TracerArrayConversionError) — use jax.numpy"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX002
@rule("JX002", "Python if/while branches on a tracer value in a jit scope")
def jx002(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    taints = _jit_scope_taints(info)
    for func, _ in taints.items():
        for node in ast.walk(func):
            if not isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
                continue
            enc = info.enclosing_function(node)
            if enc not in taints:
                continue
            if taints[enc].expr_tainted(node.test):
                kind = {ast.If: "if", ast.While: "while",
                        ast.IfExp: "conditional expression",
                        ast.Assert: "assert"}[type(node)]
                out.append(_finding(
                    info, node, "JX002",
                    f"Python `{kind}` on a tracer-derived value inside a jit "
                    "scope: raises TracerBoolConversionError at trace time — "
                    "use jax.lax.cond/select or jnp.where"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX003
@rule("JX003", "host sync (.item()/float()/np.asarray) inside a training loop")
def jx003(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    # pure-host modules have no device arrays to sync on
    if not (info.jax_aliases or info.jnp_aliases):
        return out
    for func in info.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        if not _HOT_FUNC_RE.search(func.name):
            continue
        loops = [n for n in ast.walk(func)
                 if isinstance(n, (ast.For, ast.AsyncFor, ast.While))
                 and info.enclosing_function(n) is func]
        for loop in loops:
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                sync = _host_sync_kind(info, node)
                if sync:
                    out.append(_finding(
                        info, node, "JX003",
                        f"`{sync}` inside the loop of `{func.name}`: "
                        "host-syncs every iteration, serializing the loop "
                        "against dispatch RTT — keep values on device and "
                        "materialize once after the loop"))
    return _dedupe(out)


def _contains_static_access(node: ast.AST) -> bool:
    """Does the expression read a trace-static property (shape/ndim/…)?
    ``int(x.shape[0])`` and ``int(getattr(x, "shape", ...)[0])`` are host
    math on static metadata, not device syncs."""
    from .analysis import STATIC_ATTRS
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            return True
        if isinstance(n, ast.Call):
            cn = call_name(n)
            if (cn == "getattr" and len(n.args) >= 2
                    and isinstance(n.args[1], ast.Constant)
                    and n.args[1].value in STATIC_ATTRS):
                return True
            if cn == "len":
                return True
    return False


def _host_sync_kind(info: ModuleInfo, node: ast.Call) -> Optional[str]:
    # x.item() — unconditional device->host sync on jax/numpy arrays
    if (isinstance(node.func, ast.Attribute) and node.func.attr == "item"
            and not node.args and not node.keywords):
        return ".item()"
    fname = call_name(node)
    if not fname:
        return None
    if fname in ("float", "int") and len(node.args) == 1:
        a = node.args[0]
        # flag only direct materialization of a stored value by bare name
        # (float(loss), int(far)); subscripts/attributes are overwhelmingly
        # host containers (dicts, metadata), and static-shape reads never
        # sync at all
        if isinstance(a, ast.Name) and not _contains_static_access(a):
            return f"{fname}(...)"
        return None
    parts = fname.split(".")
    if (parts[0] in info.numpy_aliases and len(parts) == 2
            and parts[1] in ("asarray", "array", "asanyarray")):
        # building an array FROM Python lists/comprehensions is host ETL,
        # not a device fetch
        if (node.args
                and not isinstance(node.args[0],
                                   (ast.Constant, ast.List, ast.Tuple,
                                    ast.ListComp, ast.GeneratorExp))
                and not _contains_static_access(node.args[0])):
            return f"{fname}(...)"
        return None
    if parts[-1] == "device_get" and parts[0] in info.jax_aliases:
        return f"{fname}(...)"
    return None


# --------------------------------------------------------------------- JX004
@rule("JX004", "jax.jit called in a loop or invoked immediately (recompiles)")
def jx004(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for node in info.nodes(ast.Call):
        # jax.jit(f)(args): a fresh compile-cache entry per outer call when
        # f is rebuilt each time; even when cached it re-hashes — hoist it.
        if isinstance(node.func, ast.Call) and info.is_jit_call(node.func):
            out.append(_finding(
                info, node, "JX004",
                "`jax.jit(f)(...)` invoked immediately: wrapping per call "
                "defeats the compile cache when f is a fresh closure — "
                "hoist the jitted callable out of the call site"))
            continue
        if info.is_jit_call(node) and _in_loop_same_function(info, node):
            out.append(_finding(
                info, node, "JX004",
                "`jax.jit` called inside a loop: every iteration builds a "
                "new wrapper (and recompiles when the function object is "
                "fresh) — create the jitted function once outside the loop"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX005
@rule("JX005", "non-hashable static_argnums/static_argnames value")
def jx005(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for node in info.nodes(ast.Call):
        if not info.is_jit_call(node):
            continue
        for kw in node.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            bad = None
            if isinstance(kw.value, (ast.List, ast.Set, ast.Dict,
                                     ast.ListComp, ast.SetComp, ast.DictComp)):
                bad = "a non-hashable literal"
            elif isinstance(kw.value, ast.Call):
                cn = call_name(kw.value) or ""
                parts = cn.split(".")
                if (parts[0] in (info.numpy_aliases | info.jnp_aliases)
                        and parts[-1] in ("array", "asarray", "arange")):
                    bad = "an array value"
                elif parts[-1] in ("list", "dict", "set"):
                    bad = "a non-hashable value"
            if bad:
                out.append(_finding(
                    info, kw.value, "JX005",
                    f"`{kw.arg}` is {bad}: jit hashes static args for its "
                    "compile cache — pass a tuple of ints/strings"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX006
@rule("JX006", "mutation of self/global state inside a jit scope (impurity)")
def jx006(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for func in info.jit_scopes:
        if isinstance(func, ast.Lambda):
            continue
        global_names = set()
        for n in ast.walk(func):
            if isinstance(n, ast.Global):
                global_names.update(n.names)
        for node in ast.walk(func):
            if info.enclosing_function(node) is not func:
                continue
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                is_self_attr = (isinstance(t, (ast.Attribute, ast.Subscript))
                                and isinstance(base, ast.Name)
                                and base.id == "self")
                is_global = isinstance(t, ast.Name) and t.id in global_names
                if is_self_attr or is_global:
                    what = ("self attribute" if is_self_attr
                            else f"global `{t.id}`")
                    out.append(_finding(
                        info, node, "JX006",
                        f"mutating {what} inside a jit scope: the write "
                        "happens once at trace time, then never again on "
                        "cached executions — return the new value instead"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX007
@rule("JX007", "bare `except:` swallows KeyboardInterrupt/SystemExit")
def jx007(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for node in info.nodes(ast.ExceptHandler):
        if node.type is None:
            out.append(_finding(
                info, node, "JX007",
                "bare `except:` catches KeyboardInterrupt and SystemExit, "
                "making training loops unkillable — catch `Exception` (or "
                "narrower) instead"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX008
@rule("JX008", "mutable default argument")
def jx008(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for node in info.nodes(ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda):
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            bad = None
            if isinstance(d, (ast.List, ast.Dict, ast.Set,
                              ast.ListComp, ast.DictComp, ast.SetComp)):
                bad = "mutable literal"
            elif isinstance(d, ast.Call):
                cn = call_name(d) or ""
                if cn in ("list", "dict", "set", "bytearray"):
                    bad = f"`{cn}()`"
            if bad:
                out.append(_finding(
                    info, d, "JX008",
                    f"mutable default argument ({bad}): shared across every "
                    "call — default to None and construct inside"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX009
@rule("JX009", "timing around jax work without block_until_ready")
def jx009(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    if not (info.jax_aliases or info.jnp_aliases):
        return out
    for func in info.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        timers: List[ast.Call] = []
        uses_jax = False
        synced = False
        for n in ast.walk(func):
            if isinstance(n, ast.Call):
                fname = call_name(n) or ""
                parts = fname.split(".")
                # only the benchmark clocks: time.time() is the deadline/
                # timeout idiom, not a measurement
                if ((parts[0] in info.time_names and len(parts) == 2
                     and parts[1] in ("perf_counter", "monotonic"))
                        or (len(parts) == 1
                            and parts[0] in info.timer_names)):
                    timers.append(n)
                # fetching values (np.asarray/device_get) closes the async
                # gap just as well as block_until_ready
                if (len(parts) >= 2 and parts[0] in info.numpy_aliases
                        and parts[-1] in ("asarray", "array")):
                    synced = True
                if parts[-1] == "device_get":
                    synced = True
            if isinstance(n, ast.Attribute):
                if n.attr == "block_until_ready":
                    synced = True
                root = dotted_name(n)
                if root:
                    r = root.split(".")[0]
                    if r in (info.jnp_aliases | info.jax_aliases
                             | info.lax_aliases):
                        uses_jax = True
            if isinstance(n, ast.Name) and n.id in (info.jnp_aliases
                                                    | info.jax_aliases):
                uses_jax = True
        if len(timers) >= 2 and uses_jax and not synced:
            out.append(_finding(
                info, timers[-1], "JX009",
                f"`{func.name}` times jax work with no "
                "`block_until_ready()`: async dispatch returns before the "
                "device finishes, so this measures dispatch latency, not "
                "compute — sync the result before reading the clock"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX010
@rule("JX010", "float64 literal/dtype in jitted code (x64 promotion hazard)")
def jx010(info: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for func in info.jit_scopes:
        for node in ast.walk(func):
            if not info.in_jit_scope(node) and info.enclosing_function(
                    node) is not func:
                continue
            bad = None
            if isinstance(node, ast.Attribute) and node.attr in (
                    "float64", "complex128"):
                root = dotted_name(node)
                if root and root.split(".")[0] in (
                        info.numpy_aliases | info.jnp_aliases
                        | info.jax_aliases):
                    bad = root
            elif (isinstance(node, ast.Constant)
                  and isinstance(node.value, str)
                  and node.value in ("float64", "complex128")):
                par = info.parent(node)
                # only flag dtype-ish positions: dtype= kwarg or astype arg
                if isinstance(par, ast.keyword) and par.arg == "dtype":
                    bad = f'"{node.value}"'
                elif (isinstance(par, ast.Call)
                      and isinstance(par.func, ast.Attribute)
                      and par.func.attr in ("astype", "view")):
                    bad = f'"{node.value}"'
            if bad:
                out.append(_finding(
                    info, node, "JX010",
                    f"{bad} inside a jit scope: without jax_enable_x64 this "
                    "silently becomes float32; with it, it doubles HBM "
                    "traffic and forbids TPU vector math — thread the "
                    "model dtype through instead of hardcoding"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX011
@rule("JX011", "time.time() used for interval measurement (wall clock steps)")
def jx011(info: ModuleInfo) -> List[Finding]:
    """Flag the elapsed-interval idiom on the wall clock: ``t0 =
    time.time()`` later subtracted as ``time.time() - t0`` (or ``now -
    t0`` where both derive from ``time.time()``).  Wall clocks step under
    NTP slew/DST, so intervals must come from ``time.perf_counter()`` —
    in-package code uses the ``observability.clock`` helpers.  The
    deadline/timeout idiom (``deadline = time.time() + t``; ``time.time()
    > deadline``; ``deadline - time.time()``) never subtracts a stored
    wall-clock sample FROM a later one and stays legal, as do bare
    timestamps (no arithmetic)."""
    out: List[Finding] = []

    def is_walltime_call(n: ast.AST) -> bool:
        if not isinstance(n, ast.Call):
            return False
        fname = call_name(n) or ""
        parts = fname.split(".")
        if len(parts) == 2 and parts[0] in info.time_names \
                and parts[1] == "time":
            return True
        return len(parts) == 1 and parts[0] in info.walltime_names

    # module-wide fixpoint: names (and self.attrs) holding a bare
    # time.time() sample, including one-hop copies (now = time.time();
    # self._last = now)
    assigns: List = []
    for node in info.nodes(ast.Assign, ast.AnnAssign):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            key = dotted_name(t)
            if key:
                assigns.append((key, node.value))
    tracked: set = set()
    changed = True
    while changed:
        changed = False
        for key, value in assigns:
            if key in tracked:
                continue
            src = dotted_name(value)
            if is_walltime_call(value) or (src and src in tracked):
                tracked.add(key)
                changed = True

    def holds_sample(n: ast.AST) -> bool:
        if is_walltime_call(n):
            return True
        name = dotted_name(n)
        return name is not None and name in tracked

    for node in info.nodes(ast.BinOp):
        if isinstance(node.op, ast.Sub):
            # later-sample MINUS stored-sample = elapsed interval; the
            # right side must be a stored name (deadline math subtracts
            # a fresh call from a derived bound, which stays legal)
            right = dotted_name(node.right)
            if right is not None and right in tracked \
                    and holds_sample(node.left):
                out.append(_finding(
                    info, node, "JX011",
                    "interval measured with `time.time()`: the wall clock "
                    "steps under NTP/DST, skewing the measurement — use "
                    "`time.perf_counter()` (observability.clock helpers) "
                    "for durations; keep `time.time()` for timestamps and "
                    "deadlines"))
    return _dedupe(out)


def _expr_is_device_value(info: ModuleInfo, node: ast.AST,
                          tracked: set) -> bool:
    """Does this expression produce a device array? jnp./jax. dotted
    calls, bare device_put, or a tracked name / subscript of one.
    (Shared by JX012/JX015.)"""
    if isinstance(node, ast.Call):
        fname = call_name(node) or ""
        parts = fname.split(".")
        if len(parts) >= 2 and parts[0] in (info.jnp_aliases
                                            | info.jax_aliases):
            return True
        return len(parts) == 1 and parts[0] in info.deviceput_names
    name = dotted_name(node)
    return name is not None and name in tracked


def _device_names(info: ModuleInfo, cache: Dict[Optional[ast.AST], set],
                  func: Optional[ast.AST]) -> set:
    """Names in ``func`` (or module scope) assigned from device-valued
    expressions, with one-hop copies, fixpointed.  (Shared by
    JX012/JX015.)"""
    if func in cache:
        return cache[func]
    scope = func if func is not None else info.tree
    assigns = []
    for n in ast.walk(scope):
        if info.enclosing_function(n) is not func:
            continue    # nested functions track their own names
        targets = []
        if isinstance(n, ast.Assign):
            targets = n.targets
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            targets = [n.target]
        for t in targets:
            key = dotted_name(t)
            if key:
                assigns.append((key, n.value))
    tracked: set = set()
    changed = True
    while changed:
        changed = False
        for key, value in assigns:
            if key not in tracked and \
                    _expr_is_device_value(info, value, tracked):
                tracked.add(key)
                changed = True
    cache[func] = tracked
    return tracked


# --------------------------------------------------------------------- JX012
@rule("JX012", "per-iteration host<->device transfer inside a loop")
def jx012(info: ModuleInfo) -> List[Finding]:
    """Flag host↔device copies paid once per loop iteration: (a) any
    ``jax.device_put`` call inside a ``for``/``while`` body, and (b)
    ``np.asarray``/``np.array`` on a *device-derived* name (one assigned
    from a ``jnp.*``/``jax.*`` call in the same function) inside a loop.
    Each such call serializes the loop against transfer+dispatch RTT — the
    copy belongs in a prefetch stage (``data/pipeline.py``:
    ``DevicePrefetchIterator`` overlaps H2D with the in-flight step) or
    hoisted out of the loop.  Inside jit scopes the same spellings mean
    different things (sharding constraints / trace-time errors already
    covered by JX001), so jitted code is excluded."""
    out: List[Finding] = []
    if not (info.jax_aliases or info.jnp_aliases or info.deviceput_names):
        return out

    device_names_cache: Dict[Optional[ast.AST], set] = {}

    def device_names(func: Optional[ast.AST]) -> set:
        return _device_names(info, device_names_cache, func)

    for node in info.nodes(ast.Call):
        if info.in_jit_scope(node):
            continue
        if not _in_loop_same_function(info, node):
            continue
        fname = call_name(node) or ""
        parts = fname.split(".")
        is_dput = ((parts[-1] == "device_put" and parts[0] in info.jax_aliases)
                   or (len(parts) == 1 and parts[0] in info.deviceput_names))
        if is_dput:
            out.append(_finding(
                info, node, "JX012",
                "`jax.device_put` inside a loop: one host->device transfer "
                "per iteration, serialized against the step instead of "
                "overlapping it — move placement into a prefetch stage "
                "(data/pipeline.DevicePrefetchIterator) or hoist it out of "
                "the loop"))
            continue
        if (parts[0] in info.numpy_aliases and len(parts) == 2
                and parts[1] in ("asarray", "array", "asanyarray")
                and node.args and isinstance(node.args[0], ast.Name)):
            if node.args[0].id in device_names(info.enclosing_function(node)):
                out.append(_finding(
                    info, node, "JX012",
                    f"`{fname}` on a device array inside a loop: "
                    "device->host fetch every iteration, serializing the "
                    "loop against transfer RTT — keep the value on device "
                    "and materialize once after the loop"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX013
@rule("JX013", "jax.jit inside an instance method over a function closing "
               "over self (per-instance retrace hazard)")
def jx013(info: ModuleInfo) -> List[Finding]:
    """Flag ``jax.jit(...)`` constructed inside an instance method when the
    traced function closes over ``self``: the jitted callable (and its
    compile cache) is then rebuilt per instance — every ``clone()`` /
    master replica re-traces an identical program, and per-call closures
    defeat jit's cache entirely.  Key the step by structural config in a
    process-global cache instead (``nn/compile_cache.shared_jit``) and pass
    params/state as arguments.  Functions that only take ``self``-free
    closures (module-level builders over a conf) stay legal, as does jit
    outside methods."""
    out: List[Finding] = []

    def enclosing_self_method(node: ast.AST) -> Optional[ast.AST]:
        """Innermost-to-outermost: any enclosing FunctionDef that is a
        class method with a ``self`` first parameter."""
        cur = info.enclosing_function(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = [a.arg for a in (list(cur.args.posonlyargs)
                                        + list(cur.args.args))]
                if args[:1] == ["self"] and isinstance(info.parent(cur),
                                                       ast.ClassDef):
                    return cur
            cur = info.enclosing_function(cur)
        return None

    def closes_over_self(func: ast.AST) -> bool:
        """Does this function reference ``self`` as a FREE variable
        (not one of its own / a nested function's parameters)?"""
        own = {a.arg for a in (list(func.args.posonlyargs)
                               + list(func.args.args)
                               + list(func.args.kwonlyargs))}
        if "self" in own:
            return False
        body = func.body if not isinstance(func, ast.Lambda) \
            else [func.body]
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                params = {a.arg for a in (list(n.args.posonlyargs)
                                          + list(n.args.args)
                                          + list(n.args.kwonlyargs))}
                if "self" not in params:
                    stack.extend(ast.iter_child_nodes(n))
                continue
            if isinstance(n, ast.Name) and n.id == "self":
                return True
            stack.extend(ast.iter_child_nodes(n))
        return False

    def local_def(name: str, at: ast.AST) -> Optional[ast.AST]:
        """Resolve ``name`` to a FunctionDef in the enclosing function
        scopes of ``at``, innermost first."""
        cur = info.enclosing_function(at)
        while cur is not None:
            for n in ast.walk(cur):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n.name == name \
                        and info.enclosing_function(n) is cur:
                    return n
            cur = info.enclosing_function(cur)
        return None

    msg = ("`jax.jit` over a function closing over `self` inside an "
           "instance method: the jitted callable is per-instance, so every "
           "clone/replica re-traces an identical program — build the traced "
           "function from structural config (conf/tx) and cache it in the "
           "process-global trace cache (nn/compile_cache.shared_jit)")

    # call form: jax.jit(f, ...) / jit(f) / partial(jax.jit, ...)
    for node in info.nodes(ast.Call):
        if not info.is_jit_call(node):
            continue
        if enclosing_self_method(node) is None:
            continue
        cands: List[ast.AST] = list(node.args[:1])
        for kw in node.keywords:
            if kw.arg in ("fun", "f"):
                cands.append(kw.value)
        for cand in cands:
            target = None
            if isinstance(cand, ast.Lambda):
                target = cand
            elif isinstance(cand, ast.Name):
                target = local_def(cand.id, node)
            if target is not None and closes_over_self(target):
                out.append(_finding(info, node, "JX013", msg))
                break

    # decorator form: @jax.jit on a def nested inside a self-method
    for node in info.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        if not any(info.is_jit_ref(d) or info.is_jit_call(d)
                   for d in node.decorator_list):
            continue
        if enclosing_self_method(node) is None:
            continue
        if closes_over_self(node):
            out.append(_finding(info, node, "JX013", msg))
    return _dedupe(out)


# --------------------------------------------------------------------- JX014
_CKPT_STR_RE = re.compile(
    r"(checkpoint|ckpt|model\w*\.zip|\.ckpt)", re.IGNORECASE)
_CKPT_NAME_RE = re.compile(r"(checkpoint|ckpt)", re.IGNORECASE)


@rule("JX014", "raw write to a checkpoint-like path bypassing the "
               "atomic-commit helper")
def jx014(info: ModuleInfo) -> List[Finding]:
    """Flag direct ``open(.., "wb")`` / ``np.savez``/``np.save`` /
    ``zipfile.ZipFile(.., "w")`` writes whose target is a checkpoint-like
    path (a string mentioning checkpoint/ckpt/``...model*.zip``, a name
    spelled like one, or a name assigned from such a string): a crash
    mid-write leaves a truncated artifact that restore explodes on.
    Durable artifacts must commit through the atomic temp-then-rename
    helpers (``faulttolerance/atomic.py``: ``atomic_file`` /
    ``atomic_write_bytes`` / staged checkpoint dirs).  Reads, writes to
    non-checkpoint paths, and in-memory buffers stay legal — as do the
    helpers themselves, whose temp targets are runtime-derived names."""
    out: List[Finding] = []

    def expr_is_ckptish(node: ast.AST, tracked: set) -> bool:
        """Does this expression denote a checkpoint-like path? String
        constants / f-string parts matching the pattern, names spelled
        like checkpoints, or names assigned from matching expressions."""
        for n in ast.walk(node):
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and _CKPT_STR_RE.search(n.value):
                return True
        name = dotted_name(node)
        if name is not None:
            return bool(_CKPT_NAME_RE.search(name)) or name in tracked
        return False

    # per-SCOPE fixpoint: names/attrs assigned from checkpoint-like
    # expressions, including one-hop copies (path = join(d, "ckpt.zip");
    # dst = path).  Scoped like JX012's device tracking — a `path`
    # holding a checkpoint name in one function must not taint an
    # unrelated `path` in another; module-level assignments seed every
    # function's set.
    scope_cache: Dict[Optional[ast.AST], set] = {}

    def tracked_names(func: Optional[ast.AST]) -> set:
        if func in scope_cache:
            return scope_cache[func]
        scope = func if func is not None else info.tree
        assigns = []
        for node in ast.walk(scope):
            if info.enclosing_function(node) is not func:
                continue    # nested functions track their own names
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) and \
                    getattr(node, "value", None) is not None:
                targets = [node.target]
            for t in targets:
                key = dotted_name(t)
                if key:
                    assigns.append((key, node.value))
        tracked = set() if func is None else set(tracked_names(None))
        changed = True
        while changed:
            changed = False
            for key, value in assigns:
                if key not in tracked and expr_is_ckptish(value, tracked):
                    tracked.add(key)
                    changed = True
        scope_cache[func] = tracked
        return tracked

    def _mode_of(node: ast.Call, default: str = "r") -> Optional[str]:
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            return node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
        return default

    for node in info.nodes(ast.Call):
        fname = call_name(node) or ""
        parts = fname.split(".")
        target = node.args[0] if node.args else None
        if target is None or not expr_is_ckptish(
                target, tracked_names(info.enclosing_function(node))):
            continue
        bad = None
        if fname == "open":
            mode = _mode_of(node) or ""
            if ("w" in mode or "x" in mode) and "b" in mode:
                bad = f'open(.., "{mode}")'
        elif parts[-1] == "ZipFile" and len(parts) <= 2:
            mode = _mode_of(node) or "r"
            if mode in ("w", "x", "a"):
                bad = f'zipfile.ZipFile(.., "{mode}")'
        elif parts[0] in info.numpy_aliases and len(parts) == 2 and \
                parts[1] in ("save", "savez", "savez_compressed"):
            bad = f"{fname}(..)"
        if bad:
            out.append(_finding(
                info, node, "JX014",
                f"{bad} writes a checkpoint-like path in place: a crash "
                "mid-write leaves a truncated artifact restore explodes "
                "on — commit through the atomic temp-then-rename helper "
                "(faulttolerance/atomic.py: atomic_file / "
                "atomic_write_bytes)"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX015
_JX015_DTYPE_CTORS = frozenset((
    "float32", "float16", "bfloat16", "float64", "int32", "int64",
    "int16", "int8", "uint8", "uint32", "complex64"))


@rule("JX015", "per-iteration dtype cast inside a Python training loop "
               "(host-side cast churn)")
def jx015(info: ModuleInfo) -> List[Finding]:
    """Flag dtype casts paid once per loop iteration: (a)
    ``x.astype(...)`` on a *device-derived* name (assigned from a
    ``jnp.*``/``jax.*`` call in the same function) inside a ``for``/
    ``while`` body, and (b) ``jnp.float32(x)``-style dtype-constructor
    calls inside a loop.  Each such cast is a separate XLA dispatch (or
    an H2D copy) serialized against the step, and its output is a fresh
    buffer the jitted step then re-reads — dtype decisions belong to the
    conf-level ``PrecisionPolicy`` (``builder.precision(...)``), which
    casts inputs/params INSIDE the compiled step, or hoisted out of the
    loop.  Host numpy casts (ETL workers massaging ``np`` arrays) stay
    legal, as does jitted code (a cast there is traced, not dispatched).
    """
    out: List[Finding] = []
    if not (info.jax_aliases or info.jnp_aliases or info.deviceput_names):
        return out
    device_names_cache: Dict[Optional[ast.AST], set] = {}
    for node in info.nodes(ast.Call):
        if info.in_jit_scope(node):
            continue
        if not _in_loop_same_function(info, node):
            continue
        fname = call_name(node) or ""
        parts = fname.split(".")
        if len(parts) == 2 and parts[0] in info.jnp_aliases and \
                parts[1] in _JX015_DTYPE_CTORS and node.args:
            out.append(_finding(
                info, node, "JX015",
                f"`{fname}(..)` inside a loop: one cast dispatch (or H2D "
                "copy) per iteration — move the dtype decision into the "
                "jitted step via the conf-level PrecisionPolicy "
                "(builder.precision(...)) or hoist the cast out of the "
                "loop"))
            continue
        if parts[-1] == "astype" and len(parts) >= 2 and \
                isinstance(node.func, ast.Attribute):
            recv = dotted_name(node.func.value)
            if recv and recv in _device_names(
                    info, device_names_cache,
                    info.enclosing_function(node)):
                out.append(_finding(
                    info, node, "JX015",
                    f"`{recv}.astype(..)` on a device array inside a "
                    "loop: per-iteration cast churn serialized against "
                    "the step — the compute dtype belongs inside the "
                    "jitted step (conf-level PrecisionPolicy, "
                    "builder.precision(...)), or cast once before the "
                    "loop"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX016
_JX016_BACKOFF_CALLS = ("sleep", "backoff", "wait")
_JX016_BUDGET_NAME_RE = re.compile(
    r"attempt|retr|tries|budget|deadline|remaining", re.IGNORECASE)


def _jx016_names_in(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


@rule("JX016", "unbounded retry loop: while True + except + continue with "
               "no backoff and no attempt budget")
def jx016(info: ModuleInfo) -> List[Finding]:
    """Flag ``while True`` loops that retry on exception — an ``except``
    handler ending the iteration with ``continue`` — with neither a
    backoff call (``sleep``/``backoff``/``wait``) nor an attempt budget
    (a comparison on an attempt/retry/deadline-style name) anywhere in
    the loop body.  Such a loop hammers a dead dependency at full tilt
    forever: a hub restart becomes a busy-wait stampede, and the caller
    can never distinguish "still retrying" from "never coming back".
    Bound it with ``faulttolerance.RetryPolicy`` (budgeted, seeded
    exponential backoff) or an explicit deadline."""
    out: List[Finding] = []
    for loop in info.nodes(ast.While):
        test = loop.test
        if not (isinstance(test, ast.Constant) and test.value is True
                or isinstance(test, ast.Constant) and test.value == 1):
            continue
        # retry shape: a Continue inside an except handler whose nearest
        # enclosing loop is THIS while (a continue bound to an inner
        # for/while retries that loop, not this one)
        retry_node = None
        for sub in ast.walk(loop):
            if not isinstance(sub, ast.ExceptHandler):
                continue
            for stmt in ast.walk(sub):
                if isinstance(stmt, ast.Continue) and \
                        _nearest_loop(info, stmt) is loop:
                    retry_node = sub
                    break
            if retry_node is not None:
                break
        if retry_node is None:
            continue
        has_backoff = any(
            isinstance(sub, ast.Call) and (call_name(sub) or "").split(
                ".")[-1] in _JX016_BACKOFF_CALLS
            for sub in ast.walk(loop))
        has_budget = any(
            isinstance(sub, ast.Compare) and any(
                _JX016_BUDGET_NAME_RE.search(n)
                for n in _jx016_names_in(sub))
            for sub in ast.walk(loop))
        if has_backoff or has_budget:
            continue
        out.append(_finding(
            info, retry_node, "JX016",
            "unbounded retry: `while True` re-enters on exception with no "
            "backoff call and no attempt budget in the loop — a dead "
            "dependency is hammered forever at full tilt; bound it with "
            "faulttolerance.RetryPolicy (budgeted seeded backoff) or an "
            "explicit deadline/attempt counter"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX017
# scope: the request-path modules where an unbounded producer queue is a
# memory blowup under load (serving front-ends, streaming brokers,
# parallel dispatchers) — ETL/data modules size queues to their own
# prefetch depth and stay out of scope
_JX017_PATH_RE = re.compile(r"(^|[/\\])(serving|streaming|parallel)[/\\]")
_JX017_QUEUE_CLASSES = frozenset(("Queue", "LifoQueue", "PriorityQueue",
                                  "JoinableQueue"))
_JX017_QUEUE_MODULES = frozenset(("queue", "multiprocessing", "mp"))


@rule("JX017", "queue constructed without an explicit maxsize in a "
               "serving/streaming/parallel module")
def jx017(info: ModuleInfo) -> List[Finding]:
    """Flag ``queue.Queue()`` / ``multiprocessing.Queue()`` (and
    Lifo/Priority/Joinable variants) constructed with neither a
    positional size nor a ``maxsize=`` keyword, in modules under
    ``serving/``, ``streaming/``, or ``parallel/``.  Those modules sit on
    the request path: an unbounded queue there lets any
    producer-faster-than-consumer imbalance (slow device, dead consumer,
    request flood) grow host memory without limit until the process
    OOMs — the failure surfaces far from the queue that caused it.
    Bound the queue and shed/block at the bound (what admission control
    exists for).  An explicit ``maxsize=0`` stays legal — it spells the
    same unboundedness, but *deliberately*."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if not _JX017_PATH_RE.search(path):
        return out
    # alias map for `import queue as q` / `import multiprocessing as mp`
    # plus names bound by `from queue import Queue [as Q]`
    mod_aliases = set(_JX017_QUEUE_MODULES)
    bare_names = set()
    for node in info.nodes(ast.Import):
        for a in node.names:
            if a.name in ("queue", "multiprocessing"):
                mod_aliases.add(a.asname or a.name)
    for node in info.nodes(ast.ImportFrom):
        if node.module in ("queue", "multiprocessing"):
            for a in node.names:
                if a.name in _JX017_QUEUE_CLASSES:
                    bare_names.add(a.asname or a.name)
    for node in info.nodes(ast.Call):
        fname = call_name(node) or ""
        parts = fname.split(".")
        is_queue_ctor = (
            (len(parts) == 2 and parts[0] in mod_aliases
             and parts[1] in _JX017_QUEUE_CLASSES)
            or (len(parts) == 1 and parts[0] in bare_names))
        if not is_queue_ctor:
            continue
        if node.args or any(kw.arg == "maxsize" for kw in node.keywords):
            continue
        out.append(_finding(
            info, node, "JX017",
            f"`{fname}()` without an explicit maxsize in a "
            "serving/streaming/parallel module: an unbounded producer "
            "queue turns any producer/consumer imbalance into unbounded "
            "host-memory growth under load — pass maxsize and shed or "
            "block at the bound (maxsize=0 spells deliberate "
            "unboundedness)"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX022
_JX022_FACTORIES = frozenset(("counter", "gauge", "histogram"))


@rule("JX022", "registry child lookup inside a per-iteration loop "
               "(cache the child before the loop)")
def jx022(info: ModuleInfo) -> List[Finding]:
    """Flag metric-child resolution paid once per loop iteration:
    ``reg.counter(name, ...)`` / ``.gauge(...)`` / ``.histogram(...)``
    (recognized by the string-literal series name every registry lookup
    passes) and constant-argument ``.labels(...)`` calls inside a
    ``for``/``while`` body.  Each lookup is a dict probe + lock + (first
    time) child construction on the hot path; the observability
    registry's whole cost model rests on resolving children ONCE and
    paying only ``inc()/set()/observe()`` per event — the cached-child
    idiom PR 2 applied by hand.  ``.labels(...)`` with a *varying*
    argument (a per-worker id, a shard name computed in the loop) is the
    reason ``.labels`` exists and stays legal; only fully-constant label
    sets are hoistable and flagged."""
    out: List[Finding] = []
    for node in info.nodes(ast.Call):
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if not _in_loop_same_function(info, node):
            continue
        if func.attr in _JX022_FACTORIES:
            first = node.args[0] if node.args else None
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str):
                out.append(_finding(
                    info, node, "JX022",
                    f"`.{func.attr}({first.value!r}, ...)` inside a loop: "
                    "the name->series lookup (dict probe + lock) runs "
                    "every iteration — resolve the child once before the "
                    "loop and call only inc()/set()/observe() per event"))
        elif func.attr == "labels":
            args = list(node.args) + [kw.value for kw in node.keywords]
            if args and all(isinstance(a, ast.Constant) for a in args):
                out.append(_finding(
                    info, node, "JX022",
                    "`.labels(...)` with constant labels inside a loop: "
                    "the labelset->child lookup repeats every iteration "
                    "for the same child — hoist the `.labels(...)` result "
                    "out of the loop (varying label values are the legal "
                    "use and stay in)"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX023
# scope: the request-path modules where a repeated device->host sync
# multiplies by tokens generated, not by requests served — the decode
# tier (generation/) and the serving front-ends that drive it (serving/)
_JX023_PATH_RE = re.compile(r"(^|[/\\])(generation|serving)[/\\]")


@rule("JX023", "host sync (.item()/float()/np.asarray) inside a per-token "
               "loop in a generation/serving module")
def jx023(info: ModuleInfo) -> List[Finding]:
    """Flag ``float()`` / ``int()`` / ``.item()`` / ``np.asarray()`` on
    device-derived values inside a ``for``/``while`` body in modules
    under ``generation/`` or ``serving/``.  The decode loop is the
    tightest loop in the whole serving stack — one iteration per
    GENERATED TOKEN, for every active sequence — so a sync there pays
    the full dispatch round-trip (~24 ms behind this environment's
    tunnel) per token instead of overlapping the next step's dispatch:
    at 8 slots that single line caps the tier at ~40 tokens/s no matter
    how fast the chip is.  The engine's contract is ONE materialization
    per step boundary for the whole slot batch (``_decode_step``'s
    batched ``np.asarray``); anything per-token inside a loop is the
    naive re-forward pattern this subsystem exists to replace.  JX003
    is the same defect class for training loops; this rule covers the
    request path, where the loop is bounded by a user's token budget,
    not an epoch count.  Deliberate syncs (a warmup loop blocking on
    each bucket's compile) carry a pragma with justification."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if not _JX023_PATH_RE.search(path):
        return out
    # pure-host modules (HTTP plumbing with no jax/numpy) can't sync
    if not (info.jax_aliases or info.jnp_aliases or info.numpy_aliases):
        return out
    for node in info.nodes(ast.Call):
        if not _in_loop_same_function(info, node):
            continue
        sync = _host_sync_kind(info, node)
        if sync:
            out.append(_finding(
                info, node, "JX023",
                f"`{sync}` inside a per-token loop in a "
                "generation/serving module: pays a device->host "
                "round-trip every iteration of the request path's "
                "hottest loop — batch the materialization once per "
                "decode-step boundary (or pragma a deliberate "
                "warmup-blocking sync)"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX024
# scope: the sharded-training modules, where a params-sized pytree is
# deliberately laid out at 1/dp per device and one stray materialization
# silently reassembles the WHOLE model on one host, every iteration
_JX024_PATH_RE = re.compile(r"(^|[/\\])(parallel|nn)[/\\]")
_JX024_NAME_RE = re.compile(r"(^|_)(params?|opt_state|grads?)($|_)")
_JX024_NP_FNS = frozenset(("asarray", "array"))


def _jx024_params_typed(node: ast.AST) -> bool:
    """A params-typed expression: a (possibly subscripted) plain or
    dotted name whose final component spells params/grads/opt_state
    (``params``, ``new_params``, ``self.model.params``,
    ``params["layer_0"]``)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    name = dotted_name(node)
    if not name:
        return False
    return bool(_JX024_NAME_RE.search(name.split(".")[-1]))


@rule("JX024", "full-pytree materialization (device_get / np.asarray / "
               "all_gather of params) inside a sharded step loop")
def jx024(info: ModuleInfo) -> List[Finding]:
    """Flag ``jax.device_get(...)``, ``np.asarray(...)``/``np.array(...)``
    and unconstrained ``all_gather(...)`` applied to a params-typed name
    inside a ``for``/``while`` body in a ``parallel/`` or ``nn/`` module.
    The ZeRO-3 layout (``parallel/sharded.py``) holds params, grads and
    updater state at ~1/dp bytes per device; any of these calls on a
    params pytree in a step loop quietly reassembles the FULL model —
    host-side for device_get/np.asarray (a device→host copy of every
    shard plus peak global-params memory, once per iteration), on-device
    for a hand-written ``all_gather`` (resident global params, exactly
    what the sharding exists to avoid — the forward's gather is XLA's
    job, inserted from the sharding constraints and freed within the
    step).  Whole-model materializations belong at checkpoint/serialize
    boundaries (``save_sharded`` writes per-shard blocks and never one
    global array); a deliberate loop materialization carries a pragma
    with its justification."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if not _JX024_PATH_RE.search(path):
        return out
    if not (info.jax_aliases or info.jnp_aliases or info.numpy_aliases):
        return out
    for node in info.nodes(ast.Call):
        if not node.args or not _jx024_params_typed(node.args[0]):
            continue
        if not _in_loop_same_function(info, node):
            continue
        fname = call_name(node) or ""
        parts = fname.split(".")
        kind = None
        if parts[-1] == "device_get" and (
                len(parts) == 1 or parts[0] in info.jax_aliases):
            kind = f"{fname}(...)"
        elif len(parts) == 2 and parts[0] in info.numpy_aliases and \
                parts[1] in _JX024_NP_FNS:
            kind = f"{fname}(...)"
        elif parts[-1] == "all_gather":
            kind = f"{fname}(...)"
        if kind:
            out.append(_finding(
                info, node, "JX024",
                f"`{kind}` on a params-typed pytree inside a loop in a "
                "sharded-training module: this reassembles the FULL "
                "model (defeating the 1/dp ZeRO layout) once per "
                "iteration — let XLA insert the forward all-gather from "
                "the shardings, and materialize whole params only at "
                "checkpoint/serialize boundaries (or pragma a "
                "deliberate one)"))
    return _dedupe(out)


def _jx025_bounded_exit(loop: ast.While) -> bool:
    """True when the loop carries a bounded/cancellable exit shape: an
    ``if`` whose test is an ``is None`` comparison (drain-until-empty)
    or contains a ``wait``/``is_set`` call (stop-event), with a
    ``break``/``return``/``raise`` in that branch."""
    for sub in ast.walk(loop):
        if not isinstance(sub, ast.If):
            continue
        test = sub.test
        drains = isinstance(test, ast.Compare) and any(
            isinstance(op, ast.Is) for op in test.ops) and any(
            isinstance(c, ast.Constant) and c.value is None
            for c in test.comparators)
        cancels = any(
            isinstance(c, ast.Call) and (call_name(c) or "").split(
                ".")[-1] in ("wait", "is_set")
            for c in ast.walk(test))
        if not (drains or cancels):
            continue
        if any(isinstance(s, (ast.Break, ast.Return, ast.Raise))
               for n in sub.body for s in ast.walk(n)):
            return True
    return False


# --------------------------------------------------------------------- JX025
# scope: the cluster-runtime modules, where an unbounded barrier /
# rendezvous / lease-poll wait turns one dead peer into a permanently
# wedged survivor (the fleet's liveness rests on every wait being
# budgeted)
_JX025_PATH_RE = re.compile(r"(^|[/\\])(faulttolerance|parallel)[/\\]")
_JX025_SLEEP_CALLS = frozenset(("sleep", "wait", "poll", "backoff"))
_JX025_BUDGET_NAME_RE = re.compile(
    r"attempt|retr|tries|budget|deadline|timeout|remaining|expires",
    re.IGNORECASE)


@rule("JX025", "barrier/rendezvous wait loop with no timeout or "
               "RetryPolicy budget in a cluster-runtime module")
def jx025(info: ModuleInfo) -> List[Finding]:
    """Flag ``while`` loops in ``faulttolerance/`` / ``parallel/``
    modules that poll — a ``sleep``/``wait``/``poll``/``backoff`` call
    in the loop body — with no budget evidence anywhere in the loop: no
    comparison on a deadline/timeout/attempt/budget-style name.  These
    are the barrier and rendezvous waits of the cluster runtime
    (``expect_members``, lease polls, shard-block-marker waits); an
    unbudgeted one waits forever on a peer that died mid-protocol, so
    one SIGKILL wedges every survivor.  Bound the wait with an explicit
    deadline, or pace it with ``faulttolerance.RetryPolicy`` under an
    attempt budget.

    Three WAITING shapes stay legal because they are bounded or
    cancellable by construction: the stop-event loop (the wait IS the
    test, ``while not stop.wait(interval)``, or an ``if stop.wait(..):
    return/break`` in the body), the drain-until-empty loop (``x =
    q.poll(..); if x is None: break/return`` — it exits the moment the
    source is momentarily empty, the inverse of waiting for it), and
    any loop comparing a deadline/attempt-style name."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if not _JX025_PATH_RE.search(path):
        return out
    for loop in info.nodes(ast.While):
        test_calls = {id(sub) for sub in ast.walk(loop.test)
                      if isinstance(sub, ast.Call)}
        sleeps = [
            sub for sub in ast.walk(loop)
            if isinstance(sub, ast.Call) and id(sub) not in test_calls
            and (call_name(sub) or "").split(".")[-1] in _JX025_SLEEP_CALLS
            and _nearest_loop(info, sub) is loop]
        if not sleeps:
            continue
        # stop-event pattern in the TEST: `while not stop.wait(i)` /
        # `while not shutdown.is_set()` — cancellable per iteration
        if any((call_name(sub) or "").split(".")[-1]
               in ("wait", "is_set", "poll")
               for sub in ast.walk(loop.test)
               if isinstance(sub, ast.Call)):
            continue
        has_budget = any(
            isinstance(sub, ast.Compare) and any(
                _JX025_BUDGET_NAME_RE.search(n)
                for n in _jx016_names_in(sub))
            for sub in ast.walk(loop))
        if has_budget or _jx025_bounded_exit(loop):
            continue
        out.append(_finding(
            info, sleeps[0], "JX025",
            "unbudgeted rendezvous wait: this `while` loop polls "
            "(sleep/wait/poll) with no deadline or attempt-budget "
            "comparison anywhere in the loop — a peer that died "
            "mid-protocol wedges this process forever; bound the wait "
            "with an explicit deadline, or pace it with "
            "faulttolerance.RetryPolicy under an attempt budget"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX026
# scope: every non-test package module — the AST-side complement of
# graftaudit AX004 (the IR rule catches a callback that made it into a
# compiled steady-state program; this one catches the source line the
# moment it is written, wherever it would compile to)
_JX026_TEST_PATH_RE = re.compile(
    r"(^|[/\\])tests?([/\\]|$)|(^|[/\\])test_[^/\\]*\.py$|"
    r"(^|[/\\])conftest\.py$")
_JX026_DEBUG_LEAVES = frozenset(("print", "breakpoint", "callback"))
_JX026_CALLBACKS = frozenset(("pure_callback", "io_callback"))


@rule("JX026", "jax.debug.print/breakpoint or host callback "
               "(pure_callback/io_callback) in a non-test package module")
def jx026(info: ModuleInfo) -> List[Finding]:
    """Flag ``jax.debug.print`` / ``jax.debug.breakpoint`` /
    ``jax.debug.callback`` and ``pure_callback`` / ``io_callback``
    (dotted through a jax alias, or imported bare from
    ``jax``/``jax.experimental``) anywhere in a non-test package
    module.  Inside a jitted program each lowers to a callback primitive
    that stalls the device on a host round-trip EVERY execution — the
    forgotten-debug-line failure mode ships straight into the
    steady-state train/serve/decode programs, where graftaudit AX004
    would flag the compiled result; this rule stops the line at review
    time instead, and also outside jit scopes (a ``jax.debug.print`` in
    eager code is still a stray debug statement).  Test modules and
    conftest are out of scope — printing tracers is what debugging a
    test looks like.  A deliberate callback (a documented
    eval-time-only io_callback) carries a pragma with justification."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if _JX026_TEST_PATH_RE.search(path):
        return out
    # bare names imported from jax / jax.experimental, and jax.debug
    # module aliases (`from jax import debug`, `import jax.debug as d`)
    bare_callbacks: set = set()
    debug_mods: set = set()
    for node in info.nodes(ast.Import):
        for alias in node.names:
            if alias.name == "jax.debug" and alias.asname:
                debug_mods.add(alias.asname)
    for node in info.nodes(ast.ImportFrom):
        mod = node.module or ""
        if mod not in ("jax", "jax.experimental", "jax.debug"):
            continue
        for alias in node.names:
            name = alias.asname or alias.name
            if alias.name in _JX026_CALLBACKS:
                bare_callbacks.add(name)
            elif mod == "jax" and alias.name == "debug":
                debug_mods.add(name)
            elif mod == "jax.debug" and alias.name in _JX026_DEBUG_LEAVES:
                bare_callbacks.add(name)
    for node in info.nodes(ast.Call):
        fname = call_name(node)
        if not fname:
            continue
        parts = fname.split(".")
        hit = None
        if len(parts) == 1 and parts[0] in bare_callbacks:
            hit = fname
        elif len(parts) >= 2:
            root, leaf = parts[0], parts[-1]
            if root in info.jax_aliases and len(parts) >= 3 and \
                    parts[1] == "debug" and leaf in _JX026_DEBUG_LEAVES:
                hit = fname                      # jax.debug.print(...)
            elif root in info.jax_aliases and leaf in _JX026_CALLBACKS:
                hit = fname                      # jax.pure_callback(...)
            elif root in debug_mods and len(parts) == 2 and \
                    leaf in _JX026_DEBUG_LEAVES:
                hit = fname                      # debug.print(...)
        if hit:
            out.append(_finding(
                info, node, "JX026",
                f"`{hit}` in a non-test package module: inside jit this "
                "lowers to a host-callback primitive that stalls the "
                "device every execution (graftaudit AX004 catches the "
                "compiled form); outside jit it is a stray debug "
                "statement — remove it, or pragma a deliberate "
                "callback with its justification"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX027
# scope: every non-test package module — the AST-side complement of the
# nn/sparse densified embedding-gradient path: both source spellings of
# a dense-materialized embedding gradient.  The IR-side pin is the
# graftaudit `train_step[embedding_zero3]` card (no O(vocab·dim)
# collective); this rule stops the source line at review time.
_JX027_VOCAB_NAME_RE = re.compile(
    r"(^|_)(n_in|vocab|vocab_size|n_rows|num_embeddings|table_size|"
    r"n_tokens)$", re.IGNORECASE)
_JX027_SCATTER_METHS = frozenset(("add", "set"))


def _jx027_is_one_hot_call(info: ModuleInfo, node: ast.AST,
                           bare: set, nn_mods: set) -> bool:
    """Is ``node`` a call to jax's one_hot (dotted through a jax/jnp
    alias or a ``jax.nn`` module alias, or imported bare from jax.nn),
    possibly behind a transpose (``one_hot(...).T``)?"""
    if isinstance(node, ast.Attribute) and node.attr in ("T", "mT"):
        node = node.value
    if not isinstance(node, ast.Call):
        return False
    fname = call_name(node)
    if not fname:
        return False
    parts = fname.split(".")
    if len(parts) == 1:
        return parts[0] in bare
    return parts[-1] == "one_hot" and \
        parts[0] in (info.jax_aliases | info.jnp_aliases | nn_mods)


def _jx027_vocabish_zeros(info: ModuleInfo, node: ast.AST) -> bool:
    """Is ``node`` a ``zeros((vocabish, ...))`` call — a jnp/np zeros
    whose FIRST shape element is a name spelled like a vocabulary size
    (n_in / vocab / num_embeddings / ...)?"""
    if not isinstance(node, ast.Call) or not node.args:
        return False
    fname = call_name(node)
    if not fname:
        return False
    parts = fname.split(".")
    if parts[-1] != "zeros" or len(parts) < 2 or parts[0] not in (
            info.jnp_aliases | info.numpy_aliases | info.jax_aliases):
        return False
    shape = node.args[0]
    first = shape.elts[0] if isinstance(shape, (ast.Tuple, ast.List)) \
        and shape.elts else shape
    name = dotted_name(first)
    if not name:
        return False
    return bool(_JX027_VOCAB_NAME_RE.search(name.split(".")[-1]))


@rule("JX027", "dense-materialized embedding gradient: one_hot(...) @ W "
               "lookup, or a full-vocab zeros scatter target, in a "
               "non-test package module")
def jx027(info: ModuleInfo) -> List[Finding]:
    """Both source spellings that materialize an O(vocab·dim) dense
    tensor for what is a row-sparse lookup/gradient: (a) an embedding
    lookup written as ``jax.nn.one_hot(ids, vocab) @ W`` — the matmul
    is O(batch·vocab·dim) MXU work AND its backward builds the dense
    one-hot cotangent, where a gather is O(batch·dim) and the sparse
    path exchanges only touched rows; (b) a gradient/update accumulated
    by scattering into a full-vocab ``jnp.zeros((n_in, ...))`` buffer
    (direct chain or a one-hop assigned name) — exactly the dense
    cotangent ``nn/sparse`` exists to avoid.  Use the embedding layers'
    gather path (``sparse_grad=True`` for the densified exchange);
    a deliberate dense materialization (a host-side test/interop
    conversion like ``SparseRows.to_dense``) carries a pragma with its
    justification.  Test modules are out of scope."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if _JX026_TEST_PATH_RE.search(path):
        return out
    bare_one_hot: set = set()
    nn_mods: set = set()
    for node in info.nodes(ast.ImportFrom):
        mod = node.module or ""
        if mod in ("jax.nn", "jax.experimental.nn"):
            for alias in node.names:
                if alias.name == "one_hot":
                    bare_one_hot.add(alias.asname or alias.name)
        elif mod == "jax":
            for alias in node.names:
                if alias.name == "nn":          # from jax import nn
                    nn_mods.add(alias.asname or alias.name)
    # (a) one_hot(...) @ W  /  W @ one_hot(...)  /  one_hot(...).T @ W
    for node in info.nodes(ast.BinOp):
        if not isinstance(node.op, ast.MatMult):
            continue
        if _jx027_is_one_hot_call(info, node.left, bare_one_hot,
                                  nn_mods) or \
                _jx027_is_one_hot_call(info, node.right, bare_one_hot,
                                       nn_mods):
            out.append(_finding(
                info, node, "JX027",
                "one_hot(...) @ table: a dense O(batch*vocab*dim) matmul "
                "(and a dense one-hot cotangent on the backward) for what "
                "is a row gather — index the table (EmbeddingLayer id "
                "path; sparse_grad=True for the densified touched-rows "
                "exchange)"))
    # (b) full-vocab zeros scatter targets, direct or one-hop — TWO
    # module-wide phases (not per-function), so module- and class-level
    # scatters are covered too; the one-hop name map is module-global,
    # a deliberate over-approximation the pragma escape covers
    zeros_names: set = set()
    for node in info.nodes(ast.Assign):
        if len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                _jx027_vocabish_zeros(info, node.value):
            zeros_names.add(node.targets[0].id)
    for node in info.nodes(ast.Call):
        if not isinstance(node.func, ast.Attribute) or \
                node.func.attr not in _JX027_SCATTER_METHS:
            continue
        sub = node.func.value
        if not isinstance(sub, ast.Subscript) or \
                not isinstance(sub.value, ast.Attribute) or \
                sub.value.attr != "at":
            continue
        target = sub.value.value
        hit = _jx027_vocabish_zeros(info, target) or (
            isinstance(target, ast.Name) and target.id in zeros_names)
        if hit:
            out.append(_finding(
                info, node, "JX027",
                "scatter into a full-vocab zeros buffer materializes "
                "the dense [vocab, dim] gradient every step — carry "
                "coalesced row indices + values instead (nn/sparse "
                "SparseRows; the train step's densified exchange), or "
                "pragma a deliberate host-side densification with its "
                "justification"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX028
# scope: every non-test package module EXCEPT nn/compile_cache.py — the
# one module allowed to touch jax.jit directly, because it is the
# counted/recorded/auditable compile path everything else must route
# through.  A stray jax.jit elsewhere compiles programs graftaudit
# never sees: no compile counters, no captured call specs (so no
# caller-liveness for the AX007 donation solver), no cards.
_JX028_COMPILE_CACHE_RE = re.compile(r"(^|[/\\])nn[/\\]compile_cache\.py$")
_JX028_WRAPPERS = frozenset(("jit", "pmap"))


@rule("JX028", "stray jax.jit/jax.pmap outside nn/compile_cache.py in a "
               "non-test package module")
def jx028(info: ModuleInfo) -> List[Finding]:
    """Flag every reference to ``jax.jit`` / ``jax.pmap`` (dotted
    through a jax alias — covering direct calls, bare ``@jax.jit``
    decorators, and ``functools.partial(jax.jit, ...)`` — and the bare
    ``from jax import jit/pmap`` import) in any non-test package module
    other than ``nn/compile_cache.py``.  All steady-state program
    construction must go through ``InstrumentedJit``/``audit_lower``:
    that is where compiles are counted (AX006 churn), call specs are
    recorded (the AX007 caller-liveness probe), and the trace cache the
    IR audit + cards walk is populated.  A raw ``jax.jit`` is an
    invisible second compile cache — its programs never reach the
    differential gate.  Deliberate exceptions (a one-shot capability
    probe, a static-argnames kernel wrapper InstrumentedJit does not
    support yet) carry a pragma with the justification; test modules
    are out of scope."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if _JX026_TEST_PATH_RE.search(path) or \
            _JX028_COMPILE_CACHE_RE.search(path):
        return out
    for node in info.nodes(ast.ImportFrom):
        if (node.module or "") != "jax":
            continue
        for alias in node.names:
            if alias.name in _JX028_WRAPPERS:
                out.append(_finding(
                    info, node, "JX028",
                    f"`from jax import {alias.name}`: route program "
                    "construction through nn/compile_cache "
                    "(InstrumentedJit) — a raw jit/pmap is an unaudited "
                    "compile path (no counters, no call specs, no IR "
                    "cards)"))
    for node in info.nodes(ast.Attribute):
        name = dotted_name(node)
        if not name:
            continue
        parts = name.split(".")
        if len(parts) == 2 and parts[0] in info.jax_aliases and \
                parts[1] in _JX028_WRAPPERS:
            out.append(_finding(
                info, node, "JX028",
                f"`{name}` outside nn/compile_cache.py: this compiles a "
                "program graftaudit never sees (no compile counters, no "
                "recorded call specs for the donation solver, no card) "
                "— use InstrumentedJit, or pragma a deliberate "
                "exception with its justification"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX029
# the ONE module licensed to fence inside a loop: the step profiler's
# SAMPLED block_until_ready is the honest-device-slice measurement, paid
# every sample_every-th step by design and counted in stepprof_fences_total
_JX029_PROFILER_RE = re.compile(
    r"(^|[/\\])observability[/\\]profiler\.py$")


@rule("JX029", "block_until_ready inside a for/while loop in a non-test "
               "package module (unsampled fence in a hot path)")
def jx029(info: ModuleInfo) -> List[Finding]:
    """Flag ``jax.block_until_ready(...)`` (dotted through a jax alias),
    the bare ``from jax import block_until_ready`` form, and
    ``.block_until_ready()`` method calls inside a ``for``/``while``
    body in any non-test package module outside
    ``observability/profiler.py``.  A fence in a loop serializes host
    and device every iteration — exactly the per-step sync the fit
    loops' async-dispatch design (and the PR 16 host-sync sweep) removed;
    one such line reintroduces the dispatch round-trip (~24 ms behind
    this environment's tunnel) per step and pins the profiler's
    dispatch-depth gauge at 0.  The step profiler's own fence is legal
    because it is SAMPLED (every ``sample_every``-th step, counted in
    ``stepprof_fences_total``) — which is why profiler.py is the one
    path-exempt module.  A deliberate loop fence elsewhere (a benchmark
    timing an aggregation round) carries a pragma with justification."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if _JX026_TEST_PATH_RE.search(path) or _JX029_PROFILER_RE.search(path):
        return out
    bare: set = set()
    for node in info.nodes(ast.ImportFrom):
        if (node.module or "") == "jax":
            for alias in node.names:
                if alias.name == "block_until_ready":
                    bare.add(alias.asname or alias.name)
    for node in info.nodes(ast.Call):
        if not _in_loop_same_function(info, node):
            continue
        fn = node.func
        name = dotted_name(fn)
        dotted = bool(name) and name.split(".")[0] in info.jax_aliases \
            and name.endswith(".block_until_ready")
        is_bare = isinstance(fn, ast.Name) and fn.id in bare
        method = isinstance(fn, ast.Attribute) \
            and fn.attr == "block_until_ready" and not dotted
        if dotted or is_bare or method:
            out.append(_finding(
                info, node, "JX029",
                f"`{name or 'block_until_ready'}` inside a loop: an "
                "every-iteration fence serializes the async dispatch "
                "pipeline (the host-sync class the fit loops removed) — "
                "sample it like observability/profiler.py's fence, hoist "
                "it past the loop, or pragma a deliberate timing sync "
                "with its justification"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX030
# the per-step host work the dispatch pipeline must fit inside one device
# step: a pytree rebuild in a fit/step loop is O(leaves) of Python per
# iteration, the dominant term on the dispatch-bound arm
_JX030_HOT_PATH_RE = re.compile(r"(^|[/\\])(nn|parallel)[/\\]")
_JX030_TREE_FNS = frozenset((
    "tree_map", "tree_flatten", "tree_unflatten", "tree_leaves",
    "tree_structure", "tree_map_with_path", "tree_all", "tree_reduce"))
_JX030_TREE_SHORT = frozenset((   # the jax.tree.* spellings
    "map", "flatten", "unflatten", "leaves", "structure", "all", "reduce"))
_JX030_PYTREE_NAME_RE = re.compile(
    r"param|grad|state|opt|update|mu\b|nu\b", re.IGNORECASE)


def _jx030_in_loop_body(info: ModuleInfo, node: ast.AST) -> bool:
    """Like ``_in_loop_same_function`` but a call in a loop HEADER
    (``for x in tree_leaves(p):`` / ``while tree_all(p):``... the
    ``for`` form runs once, and header position marks intent either
    way) does not count that loop — only code the loop body re-executes
    per iteration is a per-step rebuild."""
    prev: ast.AST = node
    cur = info.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.Module)):
            return False
        if isinstance(cur, (ast.For, ast.AsyncFor)):
            if prev is not cur.iter:
                return True
        elif isinstance(cur, ast.While):
            if prev is not cur.test:
                return True
        prev = cur
        cur = info.parent(cur)
    return False


@rule("JX030", "pytree rebuild (tree_map/tree_flatten/... or a dict/list "
               "comprehension over a params-like tree) inside a for/while "
               "loop in an nn// or parallel/ hot path")
def jx030(info: ModuleInfo) -> List[Finding]:
    """Flag per-iteration pytree traversal in the packages that own the
    train loops: ``jax.tree_util.tree_map``/``tree_flatten``/... (any
    jax alias, ``jax.tree.*`` short forms, and bare ``from jax.tree_util
    import tree_map`` included) inside a ``for``/``while`` body in a
    non-test ``nn/`` or ``parallel/`` module, plus dict/list
    comprehensions rebuilding a params-like tree (an iterable named
    param*/grad*/state/opt*/update*) in the same position.  The bounded
    dispatch pipeline only overlaps host work with device execution
    while the host's per-step cost stays under the device step time —
    an O(n_leaves) Python traversal per iteration is exactly the term
    that breaks that on real models (thousands of leaves, every step).
    Hoist the traversal out of the loop (trace it into the step program,
    or restructure so placement/flattening happens once per fit), or
    pragma a deliberate per-iteration rebuild with its justification."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if _JX026_TEST_PATH_RE.search(path) or \
            not _JX030_HOT_PATH_RE.search(path):
        return out
    bare: set = set()
    for node in info.nodes(ast.ImportFrom):
        if (node.module or "") in ("jax.tree_util", "jax.tree"):
            for alias in node.names:
                if alias.name in _JX030_TREE_FNS | _JX030_TREE_SHORT:
                    bare.add(alias.asname or alias.name)
    for node in info.nodes(ast.Call):
        if not _jx030_in_loop_body(info, node):
            continue
        fn = node.func
        name = dotted_name(fn)
        dotted = False
        if name:
            parts = name.split(".")
            if parts[0] in info.jax_aliases:
                dotted = parts[-1] in _JX030_TREE_FNS or (
                    len(parts) >= 2 and parts[-2] == "tree"
                    and parts[-1] in _JX030_TREE_SHORT)
        is_bare = isinstance(fn, ast.Name) and fn.id in bare
        if dotted or is_bare:
            out.append(_finding(
                info, node, "JX030",
                f"`{name or fn.id}` inside a loop in a train-loop "
                "package: an O(n_leaves) pytree traversal per iteration "
                "is host work the bounded dispatch pipeline cannot hide "
                "— hoist it out of the loop (or into the jitted step), "
                "or pragma a deliberate per-iteration rebuild with its "
                "justification"))
    for node in list(info.nodes(ast.DictComp)) + list(info.nodes(ast.ListComp)):
        if not _jx030_in_loop_body(info, node):
            continue
        for gen in node.generators:
            it = gen.iter
            base = it
            if isinstance(it, ast.Call) and \
                    isinstance(it.func, ast.Attribute) and \
                    it.func.attr in ("items", "values", "keys"):
                base = it.func.value
            name = dotted_name(base)
            if name and _JX030_PYTREE_NAME_RE.search(name.split(".")[-1]):
                out.append(_finding(
                    info, node, "JX030",
                    f"dict/list comprehension over `{name}` inside a "
                    "loop in a train-loop package: a per-iteration "
                    "rebuild of a params-like tree is O(n_leaves) host "
                    "work the dispatch pipeline cannot hide — hoist it, "
                    "or pragma a deliberate rebuild with its "
                    "justification"))
                break
    return _dedupe(out)


# --------------------------------------------------------------------- JX031
# scope: the paged-KV request path — block tables are fixed-shape int32
# DATA passed whole to the two steady programs; per-block Python on the
# host side is the O(blocks)-dispatches pattern paging must not reintroduce
_JX031_PATH_RE = re.compile(r"(^|[/\\])generation[/\\]")
_JX031_TABLE_RE = re.compile(
    r"(^|_)(block_)?(tables?|table_rows?)($|_)|(^|_)block_ids($|_)")
_JX031_XFER = frozenset(("device_put", "device_get"))


def _jx031_table_named(node: ast.AST) -> bool:
    """A block-table-typed expression: a (possibly subscripted) plain or
    dotted name whose final component spells a table (``tables``,
    ``table_row``, ``self.ring.tables[slot]``, ``block_ids``)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    name = dotted_name(node)
    if not name:
        return False
    return bool(_JX031_TABLE_RE.search(name.split(".")[-1]))


def _jx031_subscripts_table(node: ast.AST) -> bool:
    """True when the expression subscripts (or IS) a block-table-named
    value — ``tables[slot, i]``, ``row[i]`` where row spells a table."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Subscript) and _jx031_table_named(sub):
            return True
    return False


def _jx031_xfer_kind(info: ModuleInfo, node: ast.Call) -> Optional[str]:
    """Classify a per-block transfer/sync call: ``jax.device_put`` /
    ``jax.device_get`` (any jax alias or bare import) or ``.item()``."""
    if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
            and not node.args:
        return ".item()"
    name = call_name(node) or ""
    parts = name.split(".")
    if parts[-1] in _JX031_XFER and (
            len(parts) == 1 or parts[0] in info.jax_aliases):
        return f"{name}(...)"
    return None


@rule("JX031", "per-block host iteration over a KV block table "
               "(device_put/device_get/.item() per block) in a "
               "generation/ loop body")
def jx031(info: ModuleInfo) -> List[Finding]:
    """Flag per-block device traffic on the paged-KV request path: a
    ``jax.device_put``/``jax.device_get``/``.item()`` call inside a
    ``for`` loop iterating over a block-table-named value, or such a
    call subscripting a table-named value inside any loop body, in a
    non-test ``generation/`` module.  The paged cache's contract is
    that block tables are fixed-shape int32 DATA shipped whole once per
    program call (``paged_prefill`` takes the slot's full table row,
    ``paged_decode`` the whole ``[slots, blocks]`` matrix) and every
    gather happens inside the traced program; Python iterating the
    table and touching the device per BLOCK turns one dispatch into
    O(blocks_per_slot) round-trips per step — at 16-token blocks and
    2k-token sequences that is 128 dispatches where the design pays
    one, and it grows with sequence length exactly the way paging
    exists to prevent.  Host-side bookkeeping loops over tables
    (allocator refcounts, numpy mirror updates) are fine — only the
    per-block device transfer is the defect.  JX023 catches generic
    per-token syncs; this rule catches the per-BLOCK shape specific to
    the paged layout.  A deliberate per-block transfer (a debug dump
    tool) carries a pragma with its justification."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if not _JX031_PATH_RE.search(path) or _JX026_TEST_PATH_RE.search(path):
        return out
    if not (info.jax_aliases or info.jnp_aliases or info.numpy_aliases):
        return out
    table_loops: List[ast.AST] = [
        loop for loop in list(info.nodes(ast.For)) +
        list(info.nodes(ast.AsyncFor))
        if _jx031_table_named(loop.iter) or (
            isinstance(loop.iter, ast.Call) and
            isinstance(loop.iter.func, ast.Attribute) and
            loop.iter.func.attr in ("tolist", "items", "values") and
            _jx031_table_named(loop.iter.func.value))]
    for node in info.nodes(ast.Call):
        kind = _jx031_xfer_kind(info, node)
        if kind is None:
            continue
        in_table_loop = any(
            node in ast.walk(loop) and node is not loop.iter
            for loop in table_loops)
        per_block_arg = _in_loop_same_function(info, node) and (
            _jx031_subscripts_table(node.func) or
            any(_jx031_subscripts_table(a) for a in node.args))
        if in_table_loop or per_block_arg:
            out.append(_finding(
                info, node, "JX031",
                f"`{kind}` per block of a KV block table inside a loop "
                "in a generation/ module: the table is fixed-shape "
                "int32 data the steady programs take WHOLE — per-block "
                "host transfers turn one dispatch into O(blocks) "
                "round-trips per step and scale with sequence length; "
                "ship the full table as a program argument and gather "
                "inside the trace (or pragma a deliberate debug dump)"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX032
# scope: the serving tier — admission/routing locks are metadata locks;
# holding one across an engine dispatch or HTTP client call serializes
# the whole replica fleet behind a single request
_JX032_PATH_RE = re.compile(r"(^|[/\\])serving[/\\]")
_JX032_LOCK_RE = re.compile(r"(lock|mutex)\d*$")
# blocking dispatch surfaces: engine request entry points, fleet-wide
# swaps, and the JSON/HTTP client verbs (import_session/put_nowait-style
# enqueues are O(1) bookkeeping and stay legal under a lock)
_JX032_DISPATCH = frozenset((
    "submit", "generate", "predict", "predict_versioned", "stream",
    "hot_swap", "promote_latest", "warmup", "post", "get_text",
    "stream_lines"))


def _jx032_lock_item(item: ast.withitem) -> bool:
    """A ``with`` item whose context expression spells a lock: a plain
    or dotted name ending in lock/mutex (``self._lock``,
    ``sess.lock``, ``self._fleet_lock``)."""
    expr = item.context_expr
    if isinstance(expr, ast.Call):      # with self._lock.acquire_timeout(...)
        expr = expr.func
        if isinstance(expr, ast.Attribute):
            expr = expr.value
    name = dotted_name(expr)
    if not name:
        return False
    return bool(_JX032_LOCK_RE.search(name.split(".")[-1].lower()))


@rule("JX032", "engine dispatch or HTTP client call while holding a "
               "lock in a serving/ module")
def jx032(info: ModuleInfo) -> List[Finding]:
    """Flag a blocking dispatch — an engine request entry point
    (``submit``/``generate``/``predict``/``predict_versioned``/
    ``stream``), a fleet-wide swap (``hot_swap``/``promote_latest``/
    ``warmup``), or a JSON client verb (``post``/``get_text``/
    ``stream_lines``) — made INSIDE a ``with <lock>:`` body in a
    non-test ``serving/`` module.  Serving-tier locks (router state,
    session tables, slot pointers) are metadata locks: they exist to
    make a handful of pointer reads/writes atomic and are taken on
    EVERY request.  A dispatch held under one turns the lock's
    nanosecond critical section into the full engine round-trip (queue
    wait + device step + possibly an HTTP hop), so every other request
    — including requests bound for perfectly idle replicas — convoys
    behind it, and a wedged replica holding the dispatch wedges the
    entire admission front with it.  The fleet pattern is
    snapshot-then-dispatch: copy the routing decision out under the
    lock, release it, dispatch outside.  O(1) bookkeeping
    (``import_session`` enqueue, queue puts, counter bumps) stays legal
    under a lock; a deliberate lock-held dispatch carries a pragma with
    its justification."""
    out: List[Finding] = []
    path = info.path.replace("\\", "/")
    if not _JX032_PATH_RE.search(path) or _JX026_TEST_PATH_RE.search(path):
        return out
    lock_withs = [
        w for w in list(info.nodes(ast.With)) +
        list(info.nodes(ast.AsyncWith))
        if any(_jx032_lock_item(item) for item in w.items)]
    if not lock_withs:
        return out
    for node in info.nodes(ast.Call):
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in _JX032_DISPATCH):
            continue
        held = any(
            any(node in ast.walk(stmt) for stmt in w.body)
            for w in lock_withs)
        if not held:
            continue
        recv = dotted_name(node.func.value) or "?"
        out.append(_finding(
            info, node, "JX032",
            f"`{recv}.{node.func.attr}(...)` while holding a lock in a "
            "serving/ module: routing/session locks are metadata locks "
            "taken on every request — a dispatch held under one convoys "
            "the whole fleet behind a single engine round-trip (and a "
            "wedged replica wedges the admission front); snapshot the "
            "routing decision under the lock, release it, dispatch "
            "outside (or pragma a deliberate O(1)-bounded call)"))
    return _dedupe(out)


# ===================================================================== #
# Whole-program concurrency pack (JX018-JX021): these run ONCE over the  #
# ProgramModel built from every linted module — see program.py for the   #
# thread-entry / guarded-by / lock-order machinery they share.           #
# ===================================================================== #


# --------------------------------------------------------------------- JX018
@program_rule("JX018", "shared attribute written from a background thread "
                       "with inconsistent lock guarding")
def jx018(program: ProgramModel) -> List[Finding]:
    """For every class that spawns threads: an instance attribute written
    from a thread-entry function and also accessed from the caller side
    must be *consistently* guarded.  Fires at each unguarded mutation
    (outside ``__init__``) when either (a) some other access of the same
    attribute IS lock-guarded — the discipline exists, the mutation skips
    it — or (b) the unguarded mutation is a read-modify-write
    (``self.x += 1``), which loses updates under any interleaving
    regardless of discipline.  Lock/queue/event-typed attributes are
    internally synchronized and exempt; plain single assignments with no
    guard evidence anywhere stay legal (flag-style publication).

    HTTP-handler classes get a second arm: the framework runs one
    handler instance per connection, so ``self`` is private but the
    server reference every request shares is not — an unguarded
    ``srv.counter += 1`` there loses updates across concurrent
    requests.  Receivers built fresh in the function (parsers, local
    accumulators) are single-threaded and stay legal."""
    out: List[Finding] = []
    for cls in program.classes:
        if cls.is_handler:
            for target, held, func in cls.foreign_augs:
                if held or not receiver_is_shared(func, target):
                    continue
                recv = dotted_name(target.value) or "?"
                out.append(_finding_at(
                    cls.path, target, "JX018",
                    f"unguarded read-modify-write to "
                    f"`{recv}.{target.attr}` in handler `{cls.name}`: "
                    "request handlers run one thread per connection, and "
                    f"`{recv}` is shared server state — concurrent "
                    "requests lose updates; guard the counter with a "
                    "lock on the server object"))
        if not cls.entry_funcs:
            continue
        for attr in sorted(cls.attrs()):
            if attr in cls.lock_attrs or attr in cls.safe_attrs:
                continue
            acc = [a for a in cls.accesses if a.attr == attr]
            writes = [a for a in acc if a.write and not a.in_init]
            entry_writes = [w for w in writes if w.func in cls.entry_funcs]
            if not entry_writes:
                continue
            outside = [a for a in acc
                       if a.func not in cls.entry_funcs and not a.in_init]
            if not outside:
                continue           # thread-private state
            guarded = [a for a in acc if a.held]
            unguarded_muts = [w for w in writes if not w.held]
            if not guarded:
                # no discipline to be inconsistent WITH: only the
                # always-unsafe read-modify-writes fire
                unguarded_muts = [w for w in unguarded_muts if w.aug]
            if not unguarded_muts:
                continue
            guards = sorted({lk for a in guarded for lk in a.held})
            for w in unguarded_muts:
                how = ("read-modify-write" if w.aug else
                       "item write" if w.subscript else "write")
                why = (f"other accesses hold self.{guards[0]}"
                       if guards else
                       "a concurrent increment loses updates")
                out.append(_finding_at(
                    cls.path, w.node, "JX018",
                    f"unguarded {how} to `self.{attr}` in "
                    f"`{cls.name}`: the attribute is written from a "
                    f"thread-entry function and read from other threads, "
                    f"but this mutation holds no lock ({why}) — guard "
                    "every access with one lock, or make the attribute a "
                    "thread-safe primitive / registry metric"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX019
@program_rule("JX019", "non-daemon background thread started but never "
                       "joined on any shutdown/close/__exit__ path")
def jx019(program: ProgramModel) -> List[Finding]:
    """A non-daemon thread (``daemon=`` unset or False) that is
    ``start()``-ed but has no ``join()`` (or Timer ``cancel()``) anywhere
    on the owning class — or, for a function-local thread, in the
    creating function — keeps the interpreter alive after main exits and
    leaks a runner that can keep mutating shared state after its owner
    is logically gone.  Threads handed to the caller (returned, passed
    on, stored in containers) are the caller's to join and stay legal,
    as do ``executor.submit`` tasks (the executor owns their
    lifecycle)."""
    out: List[Finding] = []
    spawns = [(cls.path, cls, s)
              for cls in program.classes for s in cls.spawns]
    spawns += [(info.path, None, s) for info, s in program.module_spawns]
    for path, cls, s in spawns:
        if s.kind == "submit":
            continue
        if s.daemon:
            continue
        if not s.started or s.joined:
            continue
        if s.self_attr is None and s.escapes:
            continue
        where = (f"self.{s.self_attr}" if s.self_attr is not None
                 else s.binding or "an unbound handle")
        cleanup = "join()" if s.kind != "timer" else "cancel()/join()"
        out.append(_finding_at(
            path, s.node, "JX019",
            f"non-daemon {s.kind} ({where}) started but never joined: "
            "no shutdown/close/__exit__ path calls "
            f"{cleanup}, so process exit hangs on it and the runner can "
            "outlive its owner — join it on the teardown path, or mark "
            "it daemon=True if it owns no in-flight state"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX020
@program_rule("JX020", "lock-order cycle across nested acquisitions "
                       "(potential deadlock)")
def jx020(program: ProgramModel) -> List[Finding]:
    """Acquiring lock B while holding lock A orders A before B.  If the
    program's lock-order graph — nested ``with`` scopes plus one-hop
    calls into methods that acquire locks (same-class and
    constructor-typed attributes) — contains a cycle, two threads
    entering the cycle from different sides deadlock.  One finding per
    cycle, anchored at one participating acquisition."""
    out: List[Finding] = []
    for nodes, site, path in find_lock_cycles(program.lock_edges()):
        labels = [n.label() for n in nodes]
        out.append(_finding_at(
            path, site, "JX020",
            "lock-order cycle: " + " -> ".join(labels + [labels[0]])
            + " — two threads taking these locks in opposite orders "
            "deadlock; impose one global acquisition order (or collapse "
            "to a single lock)"))
    return _dedupe(out)


# --------------------------------------------------------------------- JX021
@program_rule("JX021", "check-then-act on a shared container outside its "
                       "inferred guard")
def jx021(program: ProgramModel) -> List[Finding]:
    """``if k in self._d: ... self._d[k]`` is two operations; between
    them another thread can remove the key (KeyError) or replace the
    value.  Fires when the container attribute HAS an inferred lock
    guard (so the class does practice locking around it) but the
    check-then-act sequence runs without it.  Also fires on
    ``qsize()``/``empty()``-gated ``get`` in thread-spawning classes:
    the queue's internal lock makes each call atomic but not the pair —
    a sibling consumer wins the race and the gated ``get`` blocks
    forever.  Use ``with lock:`` around the pair, ``dict.get``/``pop``
    with a default, or ``get_nowait`` + ``except Empty``."""
    out: List[Finding] = []
    for cls in program.classes:
        for node, kind, target, key, held in cls.check_then_act:
            if kind == "membership":
                guards = cls.guards(target)
                if not guards or held & guards:
                    continue
                out.append(_finding_at(
                    cls.path, node, "JX021",
                    f"check-then-act on `self.{target}` outside its "
                    f"inferred guard (self.{sorted(guards)[0]}): the key "
                    "can vanish between the membership test and the "
                    "access — hold the guard across the pair, or use "
                    ".get()/.pop() with a default"))
            else:
                if not cls.entry_funcs:
                    continue
                out.append(_finding_at(
                    cls.path, node, "JX021",
                    f"`{target}.qsize()/.empty()`-gated get: the check "
                    "and the get are two operations, and a sibling "
                    "consumer can drain the queue between them, blocking "
                    "this get forever — use get_nowait() and handle "
                    "queue.Empty"))
    return _dedupe(out)


def _nearest_loop(info: ModuleInfo, node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing for/while of ``node`` without crossing a
    function boundary."""
    cur = info.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.Module)):
            return None
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return cur
        cur = info.parent(cur)
    return None


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen = set()
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.path, f.line, f.col, f.rule)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out
