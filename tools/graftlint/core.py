"""graftlint core: findings, pragmas, file walking, baseline handling.

The linter is deliberately dependency-free (stdlib ``ast`` + ``json``)
so it can run in any environment the package itself runs in — including
the minimal TPU-pod images where dev-tooling wheels are unavailable.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "PragmaIndex", "Baseline", "iter_python_files",
    "parse_pragmas", "to_sarif", "RULE_CODE_RE",
]

RULE_CODE_RE = re.compile(r"JX\d{3}")

# `# graftlint: disable=JX001[,JX002…]` — same line, or a standalone
# pragma-only line applying to the next line.  `disable-file=` at any
# column disables rules for the whole file.  Anything after the code
# list (a justifying comment, as the docs encourage) is ignored.
_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*(disable(?:-file)?)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


@dataclass(frozen=True)
class Finding:
    """One lint finding: ``path:line:col RULE message``."""
    path: str
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message}


class PragmaIndex:
    """Inline suppression pragmas for one source file.

    ``# graftlint: disable=JX003`` on a line suppresses those rules for
    that line; on a line holding only the pragma (plus whitespace) it
    suppresses them for the following line.  ``disable-file=JX003``
    suppresses the rules everywhere in the file.
    """

    def __init__(self, source: str):
        self.line_rules: Dict[int, Set[str]] = {}
        self.file_rules: Set[str] = set()
        for lineno, text in enumerate(source.splitlines(), start=1):
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            kind, codes_raw = m.group(1), m.group(2)
            codes = {c.strip().upper() for c in codes_raw.split(",")
                     if c.strip()}
            codes = {c for c in codes if RULE_CODE_RE.fullmatch(c)}
            if not codes:
                continue
            if kind == "disable-file":
                self.file_rules |= codes
            else:
                target = lineno
                if text[:m.start()].strip() == "":
                    # pragma-only line: applies to the next code line
                    target = lineno + 1
                self.line_rules.setdefault(target, set()).update(codes)
                # also apply to the pragma's own line so trailing pragmas
                # placed on the first line of a multi-line statement work
                if target != lineno:
                    self.line_rules.setdefault(lineno, set()).update(codes)

    def suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_rules:
            return True
        return finding.rule in self.line_rules.get(finding.line, set())


def parse_pragmas(source: str) -> PragmaIndex:
    return PragmaIndex(source)


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    """Expand files/directories into a sorted list of ``.py`` files.

    Raises on nonexistent or non-``.py`` file arguments: a typo'd path
    silently linting nothing would report "clean" in a gate forever.
    """
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif not os.path.exists(p):
            raise FileNotFoundError(f"no such file or directory: {p}")
        elif p.endswith(".py"):
            out.append(p)
        else:
            raise ValueError(f"not a .py file or directory: {p}")
    return out


def _norm(path: str) -> str:
    return os.path.relpath(path).replace(os.sep, "/")


def to_sarif(findings: Sequence[Finding],
             rule_docs: Optional[Dict[str, str]] = None) -> dict:
    """SARIF 2.1.0 document for CI annotation (GitHub code scanning et
    al.): one run, one result per finding, rule metadata from the
    catalog."""
    rule_docs = rule_docs or {}
    seen_rules = sorted({f.rule for f in findings})
    rules = [{"id": code,
              "shortDescription": {"text": rule_docs.get(code, code)}}
             for code in seen_rules]
    rule_index = {code: i for i, code in enumerate(seen_rules)}
    results = [{
        "ruleId": f.rule,
        "ruleIndex": rule_index[f.rule],
        "level": "warning",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": _norm(f.path),
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(1, f.line),
                           "startColumn": max(1, f.col + 1)},
            }
        }],
    } for f in findings]
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "rules": rules,
            }},
            "results": results,
        }],
    }


class Baseline:
    """Checked-in allowance for deliberate findings.

    Format: ``{"<path>::<rule>": count}`` — line numbers are deliberately
    NOT part of the key so unrelated edits above a baselined finding don't
    churn the file.  A finding is absorbed while the (path, rule) budget
    lasts; anything beyond the budget is reported.
    """

    def __init__(self, allowances: Optional[Dict[str, int]] = None):
        self.allowances: Dict[str, int] = dict(allowances or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        return cls({k: int(v) for k, v in data.get("allow", {}).items()})

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        allow: Dict[str, int] = {}
        for f in findings:
            key = f"{_norm(f.path)}::{f.rule}"
            allow[key] = allow.get(key, 0) + 1
        return cls(allow)

    def save(self, path: str) -> None:
        payload = {
            "comment": "graftlint baseline: '<path>::<rule>': allowed count. "
                       "Regenerate with --write-baseline; keep near-empty.",
            "allow": dict(sorted(self.allowances.items())),
        }
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=False)
            fh.write("\n")

    def filter(self, findings: Sequence[Finding]) -> List[Finding]:
        """Return the findings NOT absorbed by the baseline."""
        return self.apply(findings)[0]

    def apply(self, findings: Sequence[Finding]
              ) -> "Tuple[List[Finding], List[str]]":
        """(kept findings, stale allowance keys).  A stale key is a
        baseline entry no current finding matches at all — the suppressed
        bug was fixed (or the file moved), so the suppression must be
        deleted rather than lie in wait to absorb a NEW bug.  The ratchet:
        baselines can only shrink."""
        budget = dict(self.allowances)
        matched: Set[str] = set()
        kept: List[Finding] = []
        for f in findings:
            key = f"{_norm(f.path)}::{f.rule}"
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                matched.add(key)
            else:
                kept.append(f)
        stale = sorted(k for k in self.allowances if k not in matched)
        return kept, stale
