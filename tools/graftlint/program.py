"""Whole-program concurrency model for graftlint.

Module-local rules (JX001–JX017) see one file at a time; the concurrency
rule pack (JX018–JX021) needs facts that only exist at package scope:
*which functions run on background threads*, *which lock protects which
attribute*, and *in what order locks nest across classes*.  This module
builds that model from the already-parsed :class:`ModuleInfo` set — one
parse per file, shared with the module rules.

Three layers:

1. **Thread entries** — for every class, the set of functions that
   execute on a spawned thread: targets of ``threading.Thread(...)`` /
   ``threading.Timer`` / ``multiprocessing.Process`` /
   ``executor.submit(...)``, resolved through bound methods
   (``target=self._loop``), bare/local functions, one-hop local aliases
   (``fn = self._loop; Thread(target=fn)``), lambdas, and — program-wide
   — methods of *other* classes reached through a constructor-typed
   variable (``w = Worker(); Thread(target=w.run)``).  The entry set is
   closed over same-class ``self.m()`` calls, so a helper two calls below
   the thread target is still "on the thread".

2. **Guarded-by inference** — every ``self.<attr>`` access is recorded
   with the set of class locks held at that point: ``with self._lock:``
   scopes, sequential ``acquire()``/``release()`` pairs (including the
   ``acquire(); try: ... finally: release()`` idiom), and
   property-aliased locks (``@property def lock: return self._lock``).
   A lock that guards a write to an attribute is that attribute's
   *inferred guard*.

3. **Lock-order graph** — acquiring lock B while holding lock A adds the
   edge A→B; calls made while holding a lock add one-hop edges into the
   locks the callee acquires (same-class ``self.m()`` and
   attribute-typed ``self.peer.m()``).  A cycle in this graph is a
   potential deadlock (JX020).

Everything is stdlib-``ast``; imprecision is deliberately on the *quiet*
side (unresolvable targets/receivers are dropped, not guessed) so
findings stay actionable.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .analysis import ModuleInfo, call_name, dotted_name

__all__ = ["ProgramModel", "ClassModel", "AttrAccess", "ThreadSpawn",
           "LockNode", "build_program", "find_lock_cycles"]

# threading/multiprocessing constructors that create LOCKS (guard tokens)
_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"))
# constructors whose objects are internally synchronized: attributes
# holding these are thread-safe by construction and never JX018 targets
_SAFE_CTORS = frozenset(("Event", "Queue", "LifoQueue", "PriorityQueue",
                         "JoinableQueue", "SimpleQueue", "Barrier",
                         "local")) | _LOCK_CTORS
_THREADING_MODULES = frozenset(("threading", "multiprocessing", "mp",
                                "queue"))
# thread-handle methods whose receiver use is lifecycle, not an escape
_HANDLE_ATTRS = frozenset(("start", "join", "cancel", "daemon",
                           "setDaemon", "is_alive", "name", "ident"))


def _daemonish(v: ast.AST) -> bool:
    """True when ``v`` sets (or MAY set) daemon: a truthy constant, or a
    non-constant expression (``daemon=flag``) whose runtime value we
    cannot resolve — the unknown drops on the quiet side, so JX019 never
    fires on a possibly-daemon thread."""
    return not isinstance(v, ast.Constant) or bool(v.value)


@dataclass
class AttrAccess:
    """One ``self.<attr>`` access with its lock context."""
    attr: str
    node: ast.AST                 # anchor for findings (lineno/col)
    func: ast.AST                 # innermost enclosing function def
    write: bool
    aug: bool = False             # read-modify-write (x += 1)
    subscript: bool = False       # container item write (self.d[k] = v)
    held: FrozenSet[str] = frozenset()
    in_init: bool = False


@dataclass
class ThreadSpawn:
    """One thread/timer/process/submit creation site."""
    node: ast.Call
    kind: str                     # "thread" | "timer" | "process" | "submit"
    func: ast.AST                 # function the spawn happens in
    daemon: Optional[bool]        # None/False = non-daemon; True also
                                  # covers unresolvable daemon= exprs
    targets: List[ast.AST] = field(default_factory=list)   # resolved defs
    # unresolved cross-object targets: (receiver local name, method name)
    foreign: List[Tuple[str, str]] = field(default_factory=list)
    binding: Optional[str] = None          # local var name, if bound
    self_attr: Optional[str] = None        # self.<attr> it is stored to
    started: bool = False
    joined: bool = False
    escapes: bool = False         # returned / yielded / passed / aliased


@dataclass(frozen=True)
class LockNode:
    """A lock identity in the program lock-order graph."""
    cls: str
    attr: str
    path: str

    def label(self) -> str:
        return f"{self.cls}.{self.attr}"


# ---------------------------------------------------------------- helpers
def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _self_subscript(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Subscript):
        return _self_attr(node.value)
    return None


def _self_method_call(n: ast.Call) -> Optional[str]:
    if isinstance(n.func, ast.Attribute) and \
            isinstance(n.func.value, ast.Name) and n.func.value.id == "self":
        return n.func.attr
    return None


def _unpack_pairs(stmt: ast.Assign) -> List[Tuple[ast.AST, ast.AST]]:
    """Element-wise (target, value) pairs, unpacking parallel tuple
    assignments like ``t, self._w = self._w, None``."""
    pairs: List[Tuple[ast.AST, ast.AST]] = []
    for t in stmt.targets:
        if isinstance(t, (ast.Tuple, ast.List)) and \
                isinstance(stmt.value, (ast.Tuple, ast.List)) and \
                len(t.elts) == len(stmt.value.elts):
            pairs.extend(zip(t.elts, stmt.value.elts))
        else:
            pairs.append((t, stmt.value))
    return pairs


def _repr_of(node: ast.AST) -> Optional[str]:
    """Stable textual identity for key/receiver matching."""
    try:
        return ast.unparse(node)
    except Exception:
        return None


def _acquire_release(stmt: ast.stmt, lock_of, which: str) -> Optional[str]:
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        c = stmt.value
        if isinstance(c.func, ast.Attribute) and c.func.attr == which:
            return lock_of(c.func.value)
    return None


class ClassModel:
    """Per-class concurrency facts extracted from one module."""

    def __init__(self, info: ModuleInfo, node: ast.ClassDef):
        self.info = info
        self.node = node
        self.name = node.name
        self.path = info.path
        self.methods: Dict[str, ast.AST] = {}
        self.lock_attrs: Set[str] = set()
        self.safe_attrs: Set[str] = set()
        self.lock_aliases: Dict[str, str] = {}   # property name -> lock attr
        self.attr_ctor: Dict[str, str] = {}      # attr -> ClassName string
        self.accesses: List[AttrAccess] = []
        self.spawns: List[ThreadSpawn] = []
        self.entry_funcs: Set[ast.AST] = set()
        # lock-order facts: (held lock attr, acquired lock attr, site)
        self.lock_edges: List[Tuple[str, str, ast.AST]] = []
        # (held frozenset, call node, receiver expr string, method name)
        self.calls_while_held: List[
            Tuple[FrozenSet[str], ast.Call, str, str]] = []
        # func def -> lock attrs it acquires anywhere in its body
        self.func_locks: Dict[ast.AST, Set[str]] = {}
        # check-then-act candidates: (If/While node, kind, attr/queue expr,
        # key repr or None, held locks at the check)
        self.check_then_act: List[
            Tuple[ast.AST, str, str, Optional[str], FrozenSet[str]]] = []
        # aug-assigns through a non-self receiver: (target node, held,
        # func) — the shared-state shape in handler classes, where `self`
        # is per-connection and shared state arrives via the server ref
        self.foreign_augs: List[
            Tuple[ast.Attribute, FrozenSet[str], ast.AST]] = []
        # HTTP-handler classes run one instance per connection: every
        # request method is effectively a thread entry
        self.is_handler = any(
            "Handler" in (dotted_name(b) or "").split(".")[-1]
            for b in node.bases)

        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[child.name] = child
        self._collect_attr_kinds()
        self._collect_lock_aliases()
        for m in self.methods.values():
            _MethodWalker(self, m).run()
        for m in self.methods.values():
            for spawn in scan_spawns(self.info, m, cls=self):
                self.spawns.append(spawn)
        self._resolve_entries()

    # -------------------------------------------------------- attr kinds
    def _collect_attr_kinds(self) -> None:
        """Classify ``self.X = <ctor>()`` assignments: locks, thread-safe
        primitives, and program-class-typed attributes."""
        # resolve module aliases the same way spawn detection does:
        # `import threading as th` must qualify th.Lock() exactly like
        # th.Thread() — asymmetry here turned fully locked classes into
        # JX018 false positives and silenced JX020/JX021
        mods, _ = _thread_aliases(self.info)
        thread_mods = _THREADING_MODULES | mods
        for m in self.methods.values():
            for n in ast.walk(m):
                if not isinstance(n, ast.Assign) or \
                        not isinstance(n.value, ast.Call):
                    continue
                ctor = call_name(n.value) or ""
                parts = ctor.split(".")
                leaf = parts[-1]
                qualified = (len(parts) >= 2 and parts[0] in thread_mods)
                for tgt, val in _unpack_pairs(n):
                    if val is not n.value:
                        continue
                    attr = _self_attr(tgt)
                    # `conns_lock = self._conns_lock = threading.Lock()`
                    # chains: every target of the Assign gets the kind
                    if attr is None:
                        continue
                    if leaf in _LOCK_CTORS and (qualified or len(parts) == 1):
                        self.lock_attrs.add(attr)
                    elif leaf in _SAFE_CTORS and (qualified
                                                  or len(parts) == 1):
                        self.safe_attrs.add(attr)
                    elif len(parts) == 1 and leaf[:1].isupper():
                        # plain ClassName(...) — resolved program-wide
                        self.attr_ctor[attr] = leaf
        # usage-typed locks: an attr entered as a `with self.X:` context
        # or used as an acquire()/release() receiver IS a lock however it
        # was constructed (injected via a ctor parameter, built by a
        # helper).  Guards this infers only SUPPRESS findings, so a
        # non-lock context manager misread as a lock errs quiet.
        # Property names are skipped — the alias pass maps them onto
        # their backing attr so each lock keeps ONE token.
        for m in self.methods.values():
            for n in ast.walk(m):
                attr = None
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        attr = _self_attr(item.context_expr)
                        if attr is not None and attr not in self.safe_attrs \
                                and attr not in self.methods:
                            self.lock_attrs.add(attr)
                    continue
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in ("acquire", "release"):
                    attr = _self_attr(n.func.value)
                if attr is not None and attr not in self.safe_attrs \
                        and attr not in self.methods:
                    self.lock_attrs.add(attr)

    def _collect_lock_aliases(self) -> None:
        """``@property def lock(self): return self._lock`` makes
        ``with self.lock:`` guard the same token as ``self._lock``."""
        for name, m in self.methods.items():
            if not isinstance(m, ast.FunctionDef):
                continue
            if not any(isinstance(d, ast.Name) and d.id == "property"
                       for d in m.decorator_list):
                continue
            body = [s for s in m.body
                    if not (isinstance(s, ast.Expr)
                            and isinstance(s.value, ast.Constant))]
            if len(body) == 1 and isinstance(body[0], ast.Return):
                attr = _self_attr(body[0].value)
                if attr in self.lock_attrs:
                    self.lock_aliases[name] = attr

    # ----------------------------------------------------- thread entries
    def _resolve_entries(self) -> None:
        for spawn in self.spawns:
            self.entry_funcs.update(spawn.targets)
        self.close_entries()

    def close_entries(self) -> None:
        """Close the entry set over same-class ``self.m()`` calls: a
        helper called from a thread-entry function runs on the thread."""
        changed = True
        while changed:
            changed = False
            for f in list(self.entry_funcs):
                for n in ast.walk(f):
                    if isinstance(n, ast.Call):
                        m = _self_method_call(n)
                        if m and m in self.methods and \
                                self.methods[m] not in self.entry_funcs:
                            self.entry_funcs.add(self.methods[m])
                            changed = True

    # ---------------------------------------------------------- inference
    def guards(self, attr: str) -> Set[str]:
        """Locks inferred to guard ``attr``: any lock held at a non-init
        write, or held at two or more accesses."""
        out: Set[str] = set()
        counts: Dict[str, int] = {}
        for a in self.accesses:
            if a.attr != attr:
                continue
            for lk in a.held:
                counts[lk] = counts.get(lk, 0) + 1
                if a.write and not a.in_init:
                    out.add(lk)
        out.update(lk for lk, c in counts.items() if c >= 2)
        return out

    def attrs(self) -> Set[str]:
        return {a.attr for a in self.accesses}

    def joins_attr(self, attr: str) -> bool:
        """Is ``self.<attr>.join()`` (or ``.cancel()``) called anywhere in
        the class — directly, or through a local alias assigned from the
        attribute (the ``t, self._worker = self._worker, None; t.join()``
        double-buffer idiom)?"""
        for m in self.methods.values():
            local_aliases: Set[str] = set()
            for n in ast.walk(m):
                if isinstance(n, ast.Assign):
                    for tgt, val in _unpack_pairs(n):
                        if _self_attr(val) == attr and \
                                isinstance(tgt, ast.Name):
                            local_aliases.add(tgt.id)
            for n in ast.walk(m):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in ("join", "cancel"):
                    base = n.func.value
                    if _self_attr(base) == attr:
                        return True
                    if isinstance(base, ast.Name) and \
                            base.id in local_aliases:
                        return True
        return False

    def daemonizes_attr(self, attr: str) -> bool:
        """``self.<attr>.daemon = True`` / ``.setDaemon(True)`` anywhere."""
        for m in self.methods.values():
            for n in ast.walk(m):
                if isinstance(n, ast.Assign):
                    for tgt, val in _unpack_pairs(n):
                        if isinstance(tgt, ast.Attribute) and \
                                tgt.attr == "daemon" and \
                                _self_attr(tgt.value) == attr and \
                                _daemonish(val):
                            return True
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "setDaemon" and \
                        _self_attr(n.func.value) == attr and n.args and \
                        _daemonish(n.args[0]):
                    return True
        return False

    def starts_attr(self, attr: str) -> bool:
        for m in self.methods.values():
            for n in ast.walk(m):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "start" and \
                        _self_attr(n.func.value) == attr:
                    return True
        return False


class _MethodWalker:
    """Walk one method recording attr accesses, lock context, lock-order
    edges, calls-under-lock, and check-then-act shapes."""

    def __init__(self, cls: ClassModel, method: ast.AST):
        self.cls = cls
        self.method = method
        self.in_init = getattr(method, "name", "") == "__init__"

    def run(self) -> None:
        self.cls.func_locks.setdefault(self.method, set())
        self._block(self.method.body, set(), self.method)

    # ------------------------------------------------------------ helpers
    def _lock_token(self, expr: ast.AST) -> Optional[str]:
        """Resolve a with-context / acquire receiver to a class lock
        attr, through property aliases."""
        attr = _self_attr(expr)
        if attr is None:
            return None
        if attr in self.cls.lock_attrs:
            return attr
        return self.cls.lock_aliases.get(attr)

    def _acquired(self, lock: str, held: Set[str], site: ast.AST) -> None:
        self.cls.func_locks.setdefault(self.method, set()).add(lock)
        for h in held:
            if h != lock:
                self.cls.lock_edges.append((h, lock, site))

    # -------------------------------------------------------------- walk
    def _block(self, stmts: Sequence[ast.stmt], held: Set[str],
               func: ast.AST) -> None:
        held = set(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                newly: Set[str] = set()
                for item in stmt.items:
                    self._expr(item.context_expr, held, func)
                    lk = self._lock_token(item.context_expr)
                    if lk is not None:
                        self._acquired(lk, held | newly, stmt)
                        newly.add(lk)
                self._block(stmt.body, held | newly, func)
            elif isinstance(stmt, (ast.If, ast.While)):
                self._expr(stmt.test, held, func)
                self._check_then_act(stmt, held, func)
                self._block(stmt.body, held, func)
                self._block(stmt.orelse, held, func)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._target(stmt.target, held, func)
                self._expr(stmt.iter, held, func)
                self._block(stmt.body, held, func)
                self._block(stmt.orelse, held, func)
            elif isinstance(stmt, ast.Try):
                # the acquire(); try: ... finally: release() idiom: the
                # sequential acquire above already put the lock in `held`
                self._block(stmt.body, held, func)
                for h in stmt.handlers:
                    self._block(h.body, held, func)
                self._block(stmt.orelse, held, func)
                self._block(stmt.finalbody, held, func)
                for s in stmt.finalbody:
                    rl = _acquire_release(s, self._lock_token, "release")
                    if rl is not None:
                        held.discard(rl)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs LATER (thread bodies, callbacks): its
                # accesses carry no lock from the defining scope
                self._block(stmt.body, set(), stmt)
            elif isinstance(stmt, ast.ClassDef):
                continue
            else:
                lk = _acquire_release(stmt, self._lock_token, "acquire")
                if lk is not None:
                    self._acquired(lk, held, stmt)
                    held.add(lk)
                    continue
                rl = _acquire_release(stmt, self._lock_token, "release")
                if rl is not None:
                    held.discard(rl)
                    continue
                self._stmt(stmt, held, func)

    def _stmt(self, stmt: ast.stmt, held: Set[str], func: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._target(t, held, func)
            self._expr(stmt.value, held, func)
        elif isinstance(stmt, ast.AugAssign):
            attr = _self_attr(stmt.target)
            sub = _self_subscript(stmt.target)
            if attr is not None:
                self._record(attr, stmt, func, held, write=True, aug=True)
            elif sub is not None:
                self._record(sub, stmt, func, held, write=True,
                             aug=True, subscript=True)
            else:
                if isinstance(stmt.target, ast.Attribute):
                    self.cls.foreign_augs.append(
                        (stmt.target, frozenset(held), func))
                self._target(stmt.target, held, func)
            self._expr(stmt.value, held, func)
        elif isinstance(stmt, ast.AnnAssign):
            self._target(stmt.target, held, func)
            if stmt.value is not None:
                self._expr(stmt.value, held, func)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                attr = _self_attr(t)
                sub = _self_subscript(t)
                if attr is not None:
                    self._record(attr, t, func, held, write=True)
                elif sub is not None:
                    self._record(sub, t, func, held, write=True,
                                 subscript=True)
                else:
                    self._expr(t, held, func)
        elif isinstance(stmt, (ast.Expr, ast.Return)):
            if stmt.value is not None:
                self._expr(stmt.value, held, func)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._expr(stmt.exc, held, func)
        elif isinstance(stmt, ast.Assert):
            self._expr(stmt.test, held, func)

    def _target(self, t: ast.AST, held: Set[str], func: ast.AST) -> None:
        attr = _self_attr(t)
        if attr is not None:
            self._record(attr, t, func, held, write=True)
            return
        sub = _self_subscript(t)
        if sub is not None:
            self._record(sub, t, func, held, write=True, subscript=True)
            self._expr(t.slice, held, func)
            return
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e, held, func)
        elif isinstance(t, ast.Starred):
            self._target(t.value, held, func)
        elif isinstance(t, (ast.Subscript, ast.Attribute)):
            self._expr(t.value, held, func)

    def _expr(self, node: ast.AST, held: Set[str], func: ast.AST) -> None:
        """Record reads and calls-under-lock in an expression subtree."""
        if node is None:
            return
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and \
                    isinstance(n.value, ast.Name) and n.value.id == "self":
                if n.attr in self.cls.methods or \
                        n.attr in self.cls.lock_attrs or \
                        n.attr in self.cls.lock_aliases:
                    continue
                # receiver of a method call (self.x.foo()) is a read of x
                self._record(n.attr, n, func, held, write=False)
            elif isinstance(n, ast.Call) and held and \
                    isinstance(n.func, ast.Attribute):
                recv = dotted_name(n.func.value)
                if recv is not None:
                    self.cls.calls_while_held.append(
                        (frozenset(held), n, recv, n.func.attr))

    def _record(self, attr: str, node: ast.AST, func: ast.AST,
                held: Set[str], write: bool, aug: bool = False,
                subscript: bool = False) -> None:
        self.cls.accesses.append(AttrAccess(
            attr=attr, node=node, func=func, write=write, aug=aug,
            subscript=subscript, held=frozenset(held),
            in_init=self.in_init and func is self.method))

    # -------------------------------------------------- check-then-act
    def _check_then_act(self, stmt: ast.AST, held: Set[str],
                        func: ast.AST) -> None:
        test = stmt.test
        # membership check on a self container: `if k in self._d:`
        for cmp_node in [n for n in ast.walk(test)
                         if isinstance(n, ast.Compare)]:
            if len(cmp_node.ops) != 1 or not isinstance(
                    cmp_node.ops[0], (ast.In, ast.NotIn)):
                continue
            attr = _self_attr(cmp_node.comparators[0])
            if attr is None or attr in self.cls.safe_attrs:
                continue
            key = _repr_of(cmp_node.left)
            if key is None:
                continue
            if _branch_uses_key(stmt, attr, key):
                self.cls.check_then_act.append(
                    (stmt, "membership", attr, key, frozenset(held)))
        # qsize()/empty()-gated get on a queue-like receiver.  Held locks
        # are given the benefit of the doubt: a lock-disciplined drain is
        # only racy against consumers that skip the lock, which JX018
        # covers from the attribute side.
        gated = _queue_gate(test)
        if gated is not None and not held:
            for n in ast.walk(stmt):
                if n is test:
                    continue
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in ("get", "get_nowait") and \
                        _repr_of(n.func.value) == gated:
                    self.cls.check_then_act.append(
                        (stmt, "queue", gated, None, frozenset(held)))
                    break


def _branch_uses_key(stmt: ast.AST, attr: str, key: str) -> bool:
    """Does the If/While body (or orelse) index/pop ``self.<attr>`` with
    the same key expression the test checked?"""
    for part in list(getattr(stmt, "body", [])) + list(
            getattr(stmt, "orelse", [])):
        for n in ast.walk(part):
            if isinstance(n, ast.Subscript) and \
                    _self_attr(n.value) == attr and \
                    _repr_of(n.slice) == key:
                return True
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("pop", "remove") and \
                    _self_attr(n.func.value) == attr and n.args and \
                    _repr_of(n.args[0]) == key:
                return True
    return False


def _queue_gate(test: ast.AST) -> Optional[str]:
    """If the test gates on ``X.qsize()`` / ``X.empty()``, return the
    receiver expression string."""
    for n in ast.walk(test):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("qsize", "empty"):
            return _repr_of(n.func.value)
    return None


# ------------------------------------------------------- spawn detection
def _thread_aliases(info: ModuleInfo) -> Tuple[Set[str], Dict[str, str]]:
    """(module aliases for threading/multiprocessing, bare-name map
    name -> Thread|Timer|Process from from-imports)."""
    cached = getattr(info, "_thread_aliases", None)
    if cached is not None:
        return cached
    mods: Set[str] = set()
    bare: Dict[str, str] = {}
    for node in info.nodes(ast.Import):
        for a in node.names:
            if a.name in ("threading", "multiprocessing") or \
                    a.name.startswith("multiprocessing."):
                mods.add(a.asname or a.name.split(".")[0])
    for node in info.nodes(ast.ImportFrom):
        if node.module in ("threading", "multiprocessing",
                           "multiprocessing.context"):
            for a in node.names:
                if a.name in ("Thread", "Timer", "Process"):
                    bare[a.asname or a.name] = a.name
    info._thread_aliases = (mods, bare)
    return mods, bare


def scan_spawns(info: ModuleInfo, func: ast.AST,
                cls: Optional[ClassModel] = None) -> List[ThreadSpawn]:
    """Thread/timer/process/submit creation sites in ``func`` (including
    its nested defs), with target resolution and lifecycle facts
    (started / joined / daemonized / escaping)."""
    mods, bare = _thread_aliases(info)
    spawns: List[ThreadSpawn] = []
    for n in ast.walk(func):
        if not isinstance(n, ast.Call):
            continue
        kind = None
        target_expr: Optional[ast.AST] = None
        fname = call_name(n) or ""
        parts = fname.split(".")
        if isinstance(n.func, ast.Attribute) and n.func.attr == "submit":
            kind = "submit"
            target_expr = n.args[0] if n.args else None
        elif (len(parts) == 2 and parts[0] in mods and
              parts[1] in ("Thread", "Timer", "Process")) or \
                (len(parts) == 1 and parts[0] in bare):
            leaf = bare[parts[0]] if len(parts) == 1 else parts[1]
            kind = {"Thread": "thread", "Timer": "timer",
                    "Process": "process"}[leaf]
            if kind == "timer" and len(n.args) > 1:
                target_expr = n.args[1]
        if kind is None:
            continue
        daemon: Optional[bool] = None
        for kw in n.keywords:
            if kw.arg == "target" and target_expr is None:
                target_expr = kw.value
            elif kw.arg == "function" and kind == "timer" and \
                    target_expr is None:
                target_expr = kw.value
            elif kw.arg == "daemon":
                daemon = _daemonish(kw.value)
        spawn = ThreadSpawn(node=n, kind=kind, func=func, daemon=daemon)
        scope = info.enclosing_function(n) or func
        if target_expr is not None:
            _resolve_target(target_expr, info, cls, scope, spawn)
        _finalize_spawn(info, spawn, scope, cls)
        spawns.append(spawn)
    return spawns


def _resolve_target(expr: ast.AST, info: ModuleInfo,
                    cls: Optional[ClassModel], scope: ast.AST,
                    spawn: ThreadSpawn, hops: int = 1) -> None:
    """Resolve a spawn target expression onto function-def nodes:
    ``self.m`` → method; bare name → local def / one-hop local alias /
    module-level def; ``obj.m`` → recorded as foreign for program-level
    resolution; lambda → the lambda plus any ``self.m()`` it calls."""
    if isinstance(expr, ast.Lambda):
        spawn.targets.append(expr)
        if cls is not None:
            for n in ast.walk(expr.body):
                if isinstance(n, ast.Call):
                    m = _self_method_call(n)
                    if m and m in cls.methods:
                        spawn.targets.append(cls.methods[m])
        return
    attr = _self_attr(expr)
    if attr is not None:
        if cls is not None and attr in cls.methods:
            spawn.targets.append(cls.methods[attr])
        return
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        spawn.foreign.append((expr.value.id, expr.attr))
        return
    if not isinstance(expr, ast.Name):
        return
    name = expr.id
    # local def in the enclosing function chain
    cur: Optional[ast.AST] = scope
    while cur is not None:
        for n in ast.walk(cur):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.name == name and \
                    info.enclosing_function(n) is cur:
                spawn.targets.append(n)
                return
        cur = info.enclosing_function(cur)
    # one-hop local alias: fn = self._loop / fn = other_fn
    if hops > 0:
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign):
                for tgt, val in _unpack_pairs(n):
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        _resolve_target(val, info, cls, scope, spawn,
                                        hops=hops - 1)
                        return
    # module-level def
    for n in info.tree.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                n.name == name:
            spawn.targets.append(n)
            return
    if cls is not None and name in cls.methods:
        spawn.targets.append(cls.methods[name])


def _finalize_spawn(info: ModuleInfo, spawn: ThreadSpawn, scope: ast.AST,
                    cls: Optional[ClassModel]) -> None:
    """Bind the spawn to its variable and derive lifecycle facts."""
    par = info.parent(spawn.node)
    if isinstance(par, ast.Attribute) and par.attr == "start":
        spawn.started = True           # Thread(...).start() chained
    if isinstance(par, ast.Assign):
        for tgt, val in _unpack_pairs(par):
            if val is spawn.node:
                attr = _self_attr(tgt)
                if attr is not None:
                    spawn.self_attr = attr
                elif isinstance(tgt, ast.Name):
                    spawn.binding = tgt.id

    b = spawn.binding
    if b is not None:
        for n in ast.walk(scope):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id == b:
                if n.func.attr == "start":
                    spawn.started = True
                elif n.func.attr in ("join", "cancel"):
                    spawn.joined = True
                elif n.func.attr == "setDaemon" and n.args and \
                        _daemonish(n.args[0]):
                    spawn.daemon = True
            elif isinstance(n, ast.Assign):
                for tgt, val in _unpack_pairs(n):
                    if isinstance(val, ast.Name) and val.id == b:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            spawn.self_attr = attr
                        else:
                            spawn.escapes = True   # aliased away: quiet
                    if isinstance(tgt, ast.Attribute) and \
                            tgt.attr == "daemon" and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == b and _daemonish(val):
                        spawn.daemon = True
            elif isinstance(n, ast.Name) and n.id == b and \
                    isinstance(n.ctx, ast.Load):
                p = info.parent(n)
                if isinstance(p, ast.Attribute) and \
                        p.attr in _HANDLE_ATTRS:
                    continue
                if isinstance(p, ast.Assign) and p.value is n:
                    continue                       # handled above
                if isinstance(p, (ast.Return, ast.Yield, ast.Tuple,
                                  ast.List, ast.Set, ast.Dict, ast.Call,
                                  ast.keyword, ast.Starred)):
                    spawn.escapes = True

    if spawn.self_attr is not None and cls is not None:
        a = spawn.self_attr
        spawn.started = spawn.started or cls.starts_attr(a)
        spawn.joined = spawn.joined or cls.joins_attr(a)
        if cls.daemonizes_attr(a):
            spawn.daemon = True


# --------------------------------------------------------------- program
class ProgramModel:
    """All :class:`ClassModel` s across the linted module set, with
    program-wide resolution (cross-class thread targets, attribute-typed
    call edges) and the global lock-order graph."""

    def __init__(self, infos: Sequence[ModuleInfo]):
        self.infos = list(infos)
        self.classes: List[ClassModel] = []
        self.by_name: Dict[str, List[ClassModel]] = {}
        # spawns in module-level functions, outside any class
        self.module_spawns: List[Tuple[ModuleInfo, ThreadSpawn]] = []
        for info in self.infos:
            class_funcs: Set[ast.AST] = set()
            for node in info.nodes(ast.ClassDef):
                cm = ClassModel(info, node)
                self.classes.append(cm)
                self.by_name.setdefault(cm.name, []).append(cm)
                class_funcs.update(ast.walk(node))
            for fn in info.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
                if fn in class_funcs:
                    continue
                if info.enclosing_function(fn) is not None:
                    continue     # nested defs covered by the parent walk
                for spawn in scan_spawns(info, fn):
                    self.module_spawns.append((info, spawn))
        self._resolve_foreign_targets()
        self._edges: Optional[List[Tuple[LockNode, LockNode, ast.AST,
                                         str]]] = None

    # ------------------------------------------------- cross-class entry
    def _resolve_foreign_targets(self) -> None:
        """``w = Worker(...); Thread(target=w.run)`` marks ``Worker.run``
        (and its same-class closure) as a thread entry."""
        all_spawns = [(cls, s) for cls in self.classes
                      for s in cls.spawns]
        all_spawns += [(None, s) for _, s in self.module_spawns]
        for owner, spawn in all_spawns:
            for recv, meth in spawn.foreign:
                tname = _local_ctor_type(spawn.func, recv)
                if tname is None and owner is not None:
                    tname = owner.attr_ctor.get(recv)
                for target_cls in self.by_name.get(tname or "", []):
                    m = target_cls.methods.get(meth)
                    if m is not None:
                        spawn.targets.append(m)
                        target_cls.entry_funcs.add(m)
                        target_cls.close_entries()

    # ------------------------------------------------------- lock graph
    def lock_edges(self) -> List[Tuple[LockNode, LockNode, ast.AST, str]]:
        """(held, acquired, site, path) edges of the program lock-order
        graph: within-class nesting plus one-hop call edges."""
        if self._edges is not None:
            return self._edges
        edges: List[Tuple[LockNode, LockNode, ast.AST, str]] = []
        for cls in self.classes:
            for h, l, site in cls.lock_edges:
                edges.append((LockNode(cls.name, h, cls.path),
                              LockNode(cls.name, l, cls.path),
                              site, cls.path))
            for held, call, recv, meth in cls.calls_while_held:
                for callee_cls, callee in self._resolve_call(
                        cls, recv, meth):
                    for lk in callee_cls.func_locks.get(callee, ()):
                        for h in held:
                            src = LockNode(cls.name, h, cls.path)
                            tgt = LockNode(callee_cls.name, lk,
                                           callee_cls.path)
                            if src != tgt:
                                edges.append((src, tgt, call, cls.path))
        self._edges = edges
        return edges

    def _resolve_call(self, cls: ClassModel, recv: str,
                      meth: str) -> List[Tuple[ClassModel, ast.AST]]:
        out: List[Tuple[ClassModel, ast.AST]] = []
        tname: Optional[str] = None
        if recv == "self":
            m = cls.methods.get(meth)
            if m is not None:
                out.append((cls, m))
            return out
        parts = recv.split(".")
        if len(parts) == 2 and parts[0] == "self":
            tname = cls.attr_ctor.get(parts[1])
        if tname is not None:
            for target_cls in self.by_name.get(tname, []):
                m = target_cls.methods.get(meth)
                if m is not None:
                    out.append((target_cls, m))
        return out


def receiver_is_shared(func: ast.AST, target: ast.Attribute) -> bool:
    """Is the receiver of ``<recv>.attr += 1`` shared state?  True when
    the receiver chain roots at ``self`` or a function parameter, or at a
    local aliased FROM a ``self.…`` chain (``srv = self.server_ref``).
    Locals built fresh in the function (``r = Reader(data)``) are
    private — their mutation is single-threaded."""
    recv = dotted_name(target.value)
    if recv is None:
        return False
    root = recv.split(".")[0]
    if root == "self":
        return True
    args = getattr(func, "args", None)
    if args is not None:
        params = {a.arg for a in (list(args.posonlyargs) + list(args.args)
                                  + list(args.kwonlyargs))}
        if root in params and root != "self":
            return True
    for n in ast.walk(func):
        if isinstance(n, ast.Assign):
            for tgt, val in _unpack_pairs(n):
                if isinstance(tgt, ast.Name) and tgt.id == root:
                    v = dotted_name(val)
                    return bool(v) and v.split(".")[0] == "self"
    return False


def _local_ctor_type(func: ast.AST, name: str) -> Optional[str]:
    """Type of local ``name`` when assigned ``name = ClassName(...)``."""
    for n in ast.walk(func):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            ctor = call_name(n.value) or ""
            if "." in ctor or not ctor[:1].isupper():
                continue
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return ctor
    return None


def build_program(infos: Sequence[ModuleInfo]) -> ProgramModel:
    return ProgramModel(infos)


# --------------------------------------------------------- cycle finding
def find_lock_cycles(edges: Sequence[Tuple[LockNode, LockNode, ast.AST,
                                           str]]
                     ) -> List[Tuple[List[LockNode], ast.AST, str]]:
    """Cycles of length >= 2 in the lock-order graph (Tarjan SCCs).
    Returns (cycle node list, representative site, path) per cycle."""
    graph: Dict[LockNode, Set[LockNode]] = {}
    site_of: Dict[Tuple[LockNode, LockNode], Tuple[ast.AST, str]] = {}
    for a, b, site, path in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
        site_of.setdefault((a, b), (site, path))

    index: Dict[LockNode, int] = {}
    low: Dict[LockNode, int] = {}
    on_stack: Set[LockNode] = set()
    stack: List[LockNode] = []
    sccs: List[List[LockNode]] = []
    counter = [0]

    def strongconnect(v: LockNode) -> None:
        work = [(v, iter(sorted(graph[v], key=lambda n: n.label())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append(
                        (w, iter(sorted(graph[w],
                                        key=lambda x: x.label()))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) >= 2:
                    sccs.append(scc)

    for v in sorted(graph, key=lambda n: n.label()):
        if v not in index:
            strongconnect(v)

    out: List[Tuple[List[LockNode], ast.AST, str]] = []
    for scc in sccs:
        nodes = sorted(scc, key=lambda n: n.label())
        site, path = None, None
        for a in nodes:
            for b in nodes:
                if (a, b) in site_of:
                    site, path = site_of[(a, b)]
                    break
            if site is not None:
                break
        out.append((nodes, site, path))
    return out
