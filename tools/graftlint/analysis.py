"""Shared semantic analysis for graftlint rules.

Three layers, all stdlib-``ast``:

1. **Import aliases** — which local names mean ``numpy`` / ``jax`` /
   ``jax.numpy`` / ``jax.lax`` / ``jax.jit`` / ``functools.partial``,
   resolved from the module's import statements so rules never
   string-match on spelling conventions.
2. **Jit scopes** — the set of function definitions whose bodies execute
   under a JAX trace: decorated with ``@jax.jit`` (directly or via
   ``partial``), wrapped by a ``jax.jit(f)`` call expression, passed as
   the body of a ``jax.lax`` control-flow combinator (``scan`` /
   ``while_loop`` / ``fori_loop`` / ``cond`` / ``switch`` /
   ``associative_scan``) or ``jax.vmap`` / ``jax.pmap`` /
   ``jax.grad`` / ``jax.value_and_grad`` / ``jax.checkpoint``, or
   lexically nested inside such a function.
3. **Taint** — a per-function fixpoint over simple assignments marking
   which local names derive from the function's parameters (i.e. are
   tracer-valued under jit).  Shape/static accessors (``.shape``,
   ``.ndim``, ``.dtype``, ``.size``, ``len()``, ``isinstance()``,
   ``type()``) BLOCK taint: branching on a traced array's *shape* is
   legal and idiomatic, branching on its *value* is a TracerBoolError.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["ModuleInfo", "analyze_module", "TaintInfo", "taint_function",
           "dotted_name", "call_name", "parent_chain"]

# attribute accesses whose RESULT is static even when the base is traced
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "sharding",
                "aval", "weak_type"}
# call targets whose result is static regardless of argument taint.
# tree_leaves/tree_flatten/tree_structure: the returned CONTAINER's
# truthiness/length is static (pytree structure is trace-static) — the
# deliberate imprecision is that element access through it loses taint.
STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr", "id",
                "repr", "str.format", "tree_leaves", "tree_flatten",
                "tree_structure"}

_LAX_COMBINATORS = {"scan", "while_loop", "fori_loop", "cond", "switch",
                    "associative_scan", "map"}
_JAX_TRANSFORMS = {"vmap", "pmap", "grad", "value_and_grad", "checkpoint",
                   "remat", "custom_jvp", "custom_vjp"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` attribute chain as a string, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


class ModuleInfo:
    """Resolved aliases + jit-scope membership for one parsed module."""

    def __init__(self, tree: ast.Module, path: str, source: str):
        self.tree = tree
        self.path = path
        self.source = source
        self.numpy_aliases: Set[str] = set()
        self.jnp_aliases: Set[str] = set()
        self.jax_aliases: Set[str] = set()
        self.lax_aliases: Set[str] = set()
        self.jit_names: Set[str] = set()        # names bound to jax.jit itself
        self.partial_names: Set[str] = set()
        self.time_names: Set[str] = set()       # names bound to the time module
        self.timer_names: Set[str] = set()      # perf_counter/monotonic imported bare
        self.walltime_names: Set[str] = set()   # time.time imported bare
        self.deviceput_names: Set[str] = set()  # jax.device_put imported bare
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.jit_scopes: Set[ast.AST] = set()   # FunctionDef/AsyncFunctionDef/Lambda
        # func -> parameter names declared static via static_argnums/names
        # (static args are NOT tracers: branching on them is legal)
        self.static_params: Dict[ast.AST, Set[str]] = {}
        # one full-tree walk, indexed by node type: rules iterate
        # ``nodes(ast.Call)`` instead of each re-walking the whole tree
        self._node_index: Dict[type, List[ast.AST]] = {}
        self._taint_cache: Dict[ast.AST, "TaintInfo"] = {}
        self._build_parents()
        self._collect_imports()
        self._collect_jit_scopes()

    # ------------------------------------------------------------ node index
    def nodes(self, *types: type) -> List[ast.AST]:
        """All nodes of the given type(s), from ONE cached full-tree walk
        (document order).  The shared index is what lets every rule run
        off a single parse+walk per module instead of re-walking."""
        if not self._node_index:
            index: Dict[type, List[ast.AST]] = {}
            for node in ast.walk(self.tree):
                index.setdefault(type(node), []).append(node)
            self._node_index = index
        if len(types) == 1:
            return self._node_index.get(types[0], [])
        out: List[ast.AST] = []
        for t in types:
            out.extend(self._node_index.get(t, []))
        return out

    def taint(self, func: ast.AST) -> "TaintInfo":
        """Memoized per-function taint analysis (JX001/JX002 both need
        every jit scope's taints; compute each once per module)."""
        ti = self._taint_cache.get(func)
        if ti is None:
            ti = self._taint_cache[func] = TaintInfo(self, func)
        return ti

    # ---------------------------------------------------------- parents
    def _build_parents(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    # ---------------------------------------------------------- imports
    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    if alias.name == "numpy":
                        self.numpy_aliases.add(name)
                    elif alias.name == "jax.numpy":
                        self.jnp_aliases.add(alias.asname or "jax")
                    elif alias.name == "jax.lax":
                        self.lax_aliases.add(alias.asname or "jax")
                    elif alias.name == "jax" or alias.name.startswith("jax."):
                        self.jax_aliases.add(name)
                    elif alias.name == "time":
                        self.time_names.add(name)
                    elif alias.name == "functools":
                        pass  # functools.partial resolved via dotted name
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    name = alias.asname or alias.name
                    if mod == "jax" and alias.name == "jit":
                        self.jit_names.add(name)
                    elif mod == "jax" and alias.name == "device_put":
                        self.deviceput_names.add(name)
                    elif mod == "jax" and alias.name == "numpy":
                        self.jnp_aliases.add(name)
                    elif mod == "jax" and alias.name == "lax":
                        self.lax_aliases.add(name)
                    elif mod == "functools" and alias.name == "partial":
                        self.partial_names.add(name)
                    elif mod == "time" and alias.name in ("perf_counter",
                                                          "monotonic"):
                        self.timer_names.add(name)
                    elif mod == "time" and alias.name == "time":
                        self.walltime_names.add(name)
                    elif mod == "numpy":
                        # `from numpy import asarray` — track per-name as a
                        # numpy alias usable bare (rules check dotted paths,
                        # so record as "name" with implicit numpy base)
                        pass

    # ----------------------------------------------------- jit detection
    def is_jit_ref(self, node: ast.AST) -> bool:
        """Is this expression a reference to ``jax.jit`` itself?"""
        if isinstance(node, ast.Name):
            return node.id in self.jit_names
        d = dotted_name(node)
        if d is None:
            return False
        root, _, rest = d.partition(".")
        return root in self.jax_aliases and rest == "jit"

    def is_jit_call(self, node: ast.AST) -> bool:
        """Is this a ``jax.jit(...)`` / ``jit(...)`` /
        ``partial(jax.jit, ...)`` call expression?"""
        if not isinstance(node, ast.Call):
            return False
        if self.is_jit_ref(node.func):
            return True
        # functools.partial(jax.jit, ...)
        fname = call_name(node)
        if fname and (fname in self.partial_names
                      or fname.endswith("functools.partial")
                      or fname == "functools.partial"):
            return bool(node.args) and self.is_jit_ref(node.args[0])
        return False

    def _is_trace_entry_call(self, node: ast.Call) -> Tuple[bool, List[ast.AST]]:
        """Calls whose function-valued arguments run under a trace:
        ``jax.lax.scan(f, ...)``, ``jax.vmap(f)``, ``jax.grad(f)``…
        Returns (is_entry, candidate function-expression args)."""
        d = call_name(node)
        if d is None:
            return False, []
        parts = d.split(".")
        root, leaf = parts[0], parts[-1]
        is_lax = ((root in self.lax_aliases and leaf in _LAX_COMBINATORS
                   and (len(parts) == 1 or "lax" in parts or root == "lax"))
                  or (root in self.jax_aliases and len(parts) >= 2
                      and parts[1] == "lax" and leaf in _LAX_COMBINATORS))
        is_tx = (root in self.jax_aliases and len(parts) == 2
                 and leaf in _JAX_TRANSFORMS)
        if not (is_lax or is_tx):
            return False, []
        cands: List[ast.AST] = list(node.args[:2])
        for kw in node.keywords:
            if kw.arg in ("f", "fun", "body_fun", "cond_fun", "body",
                          "true_fun", "false_fun"):
                cands.append(kw.value)
        return True, cands

    @staticmethod
    def _static_names_from_call(call: ast.Call, func: ast.AST) -> Set[str]:
        """Parameter names made static by static_argnums/static_argnames
        keywords on a jit(...) call applied to ``func``."""
        names: Set[str] = set()
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return names
        params = [a.arg for a in (list(func.args.posonlyargs)
                                  + list(func.args.args))]
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for v in _iter_constants(kw.value):
                    if isinstance(v, str):
                        names.add(v)
            elif kw.arg == "static_argnums":
                for v in _iter_constants(kw.value):
                    if isinstance(v, int) and 0 <= v < len(params):
                        names.add(params[v])
        return names

    def _record_static_params(self, call: ast.Call, func: ast.AST) -> None:
        names = self._static_names_from_call(call, func)
        if names:
            self.static_params.setdefault(func, set()).update(names)

    def _collect_jit_scopes(self) -> None:
        funcs_by_scope: Dict[Tuple[ast.AST, str], List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = self.enclosing_function(node) or self.tree
                funcs_by_scope.setdefault((scope, node.name), []).append(node)

        def mark_name(name_node: ast.AST, at: ast.AST,
                      jit_call: Optional[ast.Call] = None) -> None:
            if isinstance(name_node, ast.Lambda):
                self.jit_scopes.add(name_node)
                return
            if not isinstance(name_node, ast.Name):
                return
            scope = self.enclosing_function(at) or self.tree
            # resolve in the enclosing scope chain, innermost first
            cur: Optional[ast.AST] = scope
            while cur is not None:
                hits = funcs_by_scope.get((cur, name_node.id))
                if hits:
                    self.jit_scopes.update(hits)
                    if jit_call is not None:
                        for h in hits:
                            self._record_static_params(jit_call, h)
                    return
                cur = (self.enclosing_function(cur)
                       if cur is not self.tree else None)
                if cur is None and scope is not self.tree:
                    cur = self.tree
                    scope = self.tree  # last round

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self.is_jit_ref(dec) or self.is_jit_call(dec):
                        self.jit_scopes.add(node)
                        if isinstance(dec, ast.Call):
                            self._record_static_params(dec, node)
            if isinstance(node, ast.Call):
                if self.is_jit_call(node):
                    for arg in node.args[:1]:
                        mark_name(arg, node, jit_call=node)
                    for kw in node.keywords:
                        if kw.arg in ("fun", "f"):
                            mark_name(kw.value, node, jit_call=node)
                else:
                    is_entry, cands = self._is_trace_entry_call(node)
                    if is_entry:
                        for c in cands:
                            mark_name(c, node)

        # lexical nesting: a function defined inside a jit scope is traced
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.tree):
                if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda))
                        and node not in self.jit_scopes):
                    enc = self.enclosing_function(node)
                    if enc is not None and enc in self.jit_scopes:
                        self.jit_scopes.add(node)
                        changed = True

    def in_jit_scope(self, node: ast.AST) -> bool:
        cur = self.enclosing_function(node)
        while cur is not None:
            if cur in self.jit_scopes:
                return True
            cur = self.enclosing_function(cur)
        return False


def _iter_constants(node: ast.AST):
    """Yield constant values from a literal or tuple/list of literals."""
    if isinstance(node, ast.Constant):
        yield node.value
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for e in node.elts:
            if isinstance(e, ast.Constant):
                yield e.value


def analyze_module(source: str, path: str) -> ModuleInfo:
    tree = ast.parse(source, filename=path)
    return ModuleInfo(tree, path, source)


# ------------------------------------------------------------------ taint
class TaintInfo:
    """Which expressions in a function derive from its parameters."""

    def __init__(self, info: ModuleInfo, func: ast.AST):
        self.info = info
        self.func = func
        self.tainted: Set[str] = set()
        args = func.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            self.tainted.add(a.arg)
        if args.vararg:
            self.tainted.add(args.vararg.arg)
        if args.kwarg:
            self.tainted.add(args.kwarg.arg)
        # static jit args are concrete Python values, not tracers
        self.tainted -= info.static_params.get(func, set())
        self._fixpoint()

    def _own_statements(self) -> List[ast.AST]:
        """Nodes belonging to this function, excluding nested functions."""
        out: List[ast.AST] = []
        body = self.func.body if not isinstance(self.func, ast.Lambda) else [self.func.body]
        stack = list(body)
        while stack:
            n = stack.pop()
            out.append(n)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))
        return out

    def _fixpoint(self) -> None:
        nodes = self._own_statements()
        for _ in range(8):
            before = len(self.tainted)
            for n in nodes:
                if isinstance(n, ast.Assign):
                    if self.expr_tainted(n.value):
                        for t in n.targets:
                            self._taint_target(t)
                elif isinstance(n, ast.AugAssign):
                    if (self.expr_tainted(n.value)
                            or self.expr_tainted(n.target)):
                        self._taint_target(n.target)
                elif isinstance(n, ast.AnnAssign) and n.value is not None:
                    if self.expr_tainted(n.value):
                        self._taint_target(n.target)
                elif isinstance(n, ast.For):
                    if self.expr_tainted(n.iter):
                        self._taint_target(n.target)
                elif isinstance(n, ast.withitem):
                    if n.optional_vars is not None and self.expr_tainted(
                            n.context_expr):
                        self._taint_target(n.optional_vars)
                elif isinstance(n, (ast.NamedExpr,)):
                    if self.expr_tainted(n.value):
                        self._taint_target(n.target)
            if len(self.tainted) == before:
                break

    def _taint_target(self, t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            self.tainted.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._taint_target(e)
        elif isinstance(t, ast.Starred):
            self._taint_target(t.value)

    def expr_tainted(self, node: ast.AST) -> bool:
        """Does this expression's VALUE derive from a parameter, with
        static accessors (shape/dtype/len/…) blocking propagation?"""
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            fname = call_name(node)
            if fname:
                leaf = fname.split(".")[-1]
                if fname in STATIC_CALLS or leaf in STATIC_CALLS:
                    return False
            # a call is tainted if its function or any argument is
            if self.expr_tainted(node.func):
                return True
            return (any(self.expr_tainted(a) for a in node.args)
                    or any(self.expr_tainted(k.value)
                           for k in node.keywords))
        if isinstance(node, ast.BinOp):
            return self.expr_tainted(node.left) or self.expr_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return (self.expr_tainted(node.left)
                    or any(self.expr_tainted(c) for c in node.comparators))
        if isinstance(node, ast.Subscript):
            return self.expr_tainted(node.value)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return (any(self.expr_tainted(v) for v in node.values)
                    or any(k is not None and self.expr_tainted(k)
                           for k in node.keys))
        if isinstance(node, ast.IfExp):
            return (self.expr_tainted(node.body)
                    or self.expr_tainted(node.orelse))
        if isinstance(node, ast.Starred):
            return self.expr_tainted(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return (self.expr_tainted(node.elt)
                    or any(self.expr_tainted(g.iter)
                           for g in node.generators))
        if isinstance(node, ast.DictComp):
            return (self.expr_tainted(node.key)
                    or self.expr_tainted(node.value)
                    or any(self.expr_tainted(g.iter)
                           for g in node.generators))
        if isinstance(node, ast.NamedExpr):
            return self.expr_tainted(node.value)
        return False


def taint_function(info: ModuleInfo, func: ast.AST) -> TaintInfo:
    return TaintInfo(info, func)
