"""One-time derivation of bundled CJK lexicon DATA + independent gold
fixtures (VERDICT r3 item 6: break the lexicon-author == gold-author
circularity and close the "data isn't there" gap).

Sources — public, Apache-2.0-licensed DATA files the reference itself
vendors; used here as corpora/wordlists with attribution, re-derived into
this project's own format (word<TAB>log-prob), never copied file-for-file:

- ansj ``core.dic`` (deeplearning4j-nlp-chinese/src/main/resources) —
  Chinese words with per-POS corpus counts -> zh unigram frequencies.
- kuromoji ``bocchan-ipadic-features.txt`` (deeplearning4j-nlp-japanese/
  src/test/resources) — Natsume Soseki's public-domain novel "Botchan"
  tokenized by IPADIC (69k tokens).  The FIRST 80% of spans trains the ja
  unigram counts; the held-out last 20% becomes gold segmentation
  fixtures, so the fixtures grade a lexicon that never saw them.
- kuromoji ``search-segmentation-tests.txt`` — hand-written segmentation
  gold by the kuromoji authors.

Run on the build host (needs /root/reference) and COMMIT the outputs:
    deeplearning4j_tpu/nlp/data/zh_ansj.tsv
    deeplearning4j_tpu/nlp/data/ja_ipadic.tsv
    tests/resources/cjk_gold_ja_bocchan.txt
    tests/resources/cjk_gold_ja_kuromoji.txt
"""
import math
import os
import re

REF = "/root/reference/deeplearning4j-nlp-parent"
OUT_DATA = "deeplearning4j_tpu/nlp/data"
OUT_RES = "tests/resources"

MIN_LOGP = -9.4          # must stay above the lattice's -9.5 OOV-char score


def _is_han(ch):
    return "一" <= ch <= "鿿" or "㐀" <= ch <= "䶿"


def _is_kana(ch):
    return "぀" <= ch <= "ゟ" or "゠" <= ch <= "ヿ" \
        or ch == "ー"          # long-vowel mark


def build_zh():
    path = f"{REF}/deeplearning4j-nlp-chinese/src/main/resources/core.dic"
    freqs = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) < 6:
                continue
            word, natures = parts[1], parts[5]
            if not (1 <= len(word) <= 6 and all(_is_han(c) for c in word)):
                continue
            freq = sum(int(m) for m in re.findall(r"=(\d+)", natures))
            if freq > 0:
                freqs[word] = freqs.get(word, 0) + freq
    total = sum(freqs.values())
    os.makedirs(OUT_DATA, exist_ok=True)
    with open(f"{OUT_DATA}/zh_ansj.tsv", "w", encoding="utf-8") as f:
        f.write("# Chinese unigram log-probs derived from the ansj_seg "
                "core dictionary\n# (Apache-2.0; counts summed over POS "
                "natures, ln(freq/total), floor %.1f).\n" % MIN_LOGP)
        for w in sorted(freqs):
            logp = max(math.log(freqs[w] / total), MIN_LOGP)
            f.write(f"{w}\t{logp:.3f}\n")
    print(f"zh_ansj.tsv: {len(freqs)} entries from {total} counted tokens")


def _bocchan_spans():
    """Token spans (split at any token containing non-kana/han chars) from
    the IPADIC-tokenized Botchan text."""
    path = (f"{REF}/deeplearning4j-nlp-japanese/src/test/resources/"
            "bocchan-ipadic-features.txt")
    spans, cur = [], []
    with open(path, encoding="utf-8") as f:
        for line in f:
            tok = line.rstrip("\n").split("\t")[0]
            if tok and all(_is_han(c) or _is_kana(c) for c in tok):
                cur.append(tok)
            else:
                if cur:
                    spans.append(cur)
                cur = []
    if cur:
        spans.append(cur)
    return spans


def build_ja():
    spans = _bocchan_spans()
    cut = int(len(spans) * 0.8)
    train, held = spans[:cut], spans[cut:]
    counts = {}
    for span in train:
        for tok in span:
            counts[tok] = counts.get(tok, 0) + 1
    total = sum(counts.values())
    os.makedirs(OUT_DATA, exist_ok=True)
    with open(f"{OUT_DATA}/ja_ipadic.tsv", "w", encoding="utf-8") as f:
        f.write("# Japanese unigram log-probs learned from the first 80%% "
                "of the kuromoji test corpus\n# (IPADIC-tokenized 'Botchan'"
                ", Natsume Soseki, public domain; Apache-2.0 test\n"
                "# resource; ln(count/total), floor %.1f).  The held-out "
                "20%% is the gold fixture\n# cjk_gold_ja_bocchan.txt — "
                "the lexicon never saw it.\n" % MIN_LOGP)
        for w in sorted(counts):
            logp = max(math.log(counts[w] / total), MIN_LOGP)
            f.write(f"{w}\t{logp:.3f}\n")
    print(f"ja_ipadic.tsv: {len(counts)} entries from {total} train tokens "
          f"({cut}/{len(spans)} spans)")

    gold = [s for s in held if 4 <= len(s) <= 25][:250]
    with open(f"{OUT_RES}/cjk_gold_ja_bocchan.txt", "w",
              encoding="utf-8") as f:
        f.write("# Gold Japanese segmentations: held-out 20% of the "
                "IPADIC-tokenized 'Botchan'\n# (kuromoji test corpus, "
                "Apache-2.0; novel public domain).  Independent of the\n"
                "# bundled lexicon's training split by construction "
                "(tools/build_cjk_lexicons.py).\n")
        for span in gold:
            f.write(" ".join(span) + "\n")
    print(f"cjk_gold_ja_bocchan.txt: {len(gold)} sentences")


def build_ja_kuromoji_gold():
    path = (f"{REF}/deeplearning4j-nlp-japanese/src/test/resources/"
            "search-segmentation-tests.txt")
    rows = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "\t" not in line:
                continue
            text, seg = line.split("\t", 1)
            toks = seg.split()
            if "".join(toks) != text:
                continue           # a few entries segment mid-normalization
            if not all(_is_han(c) or _is_kana(c) for c in text):
                continue           # latin/digit cases need no lattice
            rows.append(toks)
    with open(f"{OUT_RES}/cjk_gold_ja_kuromoji.txt", "w",
              encoding="utf-8") as f:
        f.write("# Gold Japanese segmentations hand-written by the "
                "kuromoji authors\n# (search-segmentation-tests.txt, "
                "Apache-2.0) — compound decomposition cases;\n# fully "
                "independent of this project.\n")
        for toks in rows:
            f.write(" ".join(toks) + "\n")
    print(f"cjk_gold_ja_kuromoji.txt: {len(rows)} sentences")


if __name__ == "__main__":
    build_zh()
    build_ja()
    build_ja_kuromoji_gold()
