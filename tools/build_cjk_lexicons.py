"""One-time derivation of bundled CJK lexicon DATA + independent gold
fixtures (VERDICT r3 item 6: break the lexicon-author == gold-author
circularity and close the "data isn't there" gap).

Sources — public, Apache-2.0-licensed DATA files the reference itself
vendors; used here as corpora/wordlists with attribution, re-derived into
this project's own format (word<TAB>log-prob), never copied file-for-file:

- ansj ``core.dic`` (deeplearning4j-nlp-chinese/src/main/resources) —
  Chinese words with per-POS corpus counts -> zh unigram frequencies.
- kuromoji ``bocchan-ipadic-features.txt`` (deeplearning4j-nlp-japanese/
  src/test/resources) — Natsume Soseki's public-domain novel "Botchan"
  tokenized by IPADIC (69k tokens).  The FIRST 80% of spans trains the ja
  unigram counts; the held-out last 20% becomes gold segmentation
  fixtures, so the fixtures grade a lexicon that never saw them.
- kuromoji ``search-segmentation-tests.txt`` — hand-written segmentation
  gold by the kuromoji authors.

Run on the build host (needs /root/reference) and COMMIT the outputs:
    deeplearning4j_tpu/nlp/data/zh_ansj.tsv
    deeplearning4j_tpu/nlp/data/ja_ipadic.tsv
    tests/resources/cjk_gold_ja_bocchan.txt
    tests/resources/cjk_gold_ja_kuromoji.txt
"""
import math
import os
import re

REF = "/root/reference/deeplearning4j-nlp-parent"
OUT_DATA = "deeplearning4j_tpu/nlp/data"
OUT_RES = "tests/resources"

MIN_LOGP = -9.4          # must stay above the lattice's -9.5 OOV-char score


def _is_han(ch):
    return "一" <= ch <= "鿿" or "㐀" <= ch <= "䶿"


def _is_kana(ch):
    return "぀" <= ch <= "ゟ" or "゠" <= ch <= "ヿ" \
        or ch == "ー"          # long-vowel mark


def build_zh():
    path = f"{REF}/deeplearning4j-nlp-chinese/src/main/resources/core.dic"
    freqs = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) < 6:
                continue
            word, natures = parts[1], parts[5]
            if not (1 <= len(word) <= 6 and all(_is_han(c) for c in word)):
                continue
            freq = sum(int(m) for m in re.findall(r"=(\d+)", natures))
            if freq > 0:
                freqs[word] = freqs.get(word, 0) + freq
    total = sum(freqs.values())
    os.makedirs(OUT_DATA, exist_ok=True)
    with open(f"{OUT_DATA}/zh_ansj.tsv", "w", encoding="utf-8") as f:
        f.write("# Chinese unigram log-probs derived from the ansj_seg "
                "core dictionary\n# (Apache-2.0; counts summed over POS "
                "natures, ln(freq/total), floor %.1f).\n" % MIN_LOGP)
        for w in sorted(freqs):
            logp = max(math.log(freqs[w] / total), MIN_LOGP)
            f.write(f"{w}\t{logp:.3f}\n")
    print(f"zh_ansj.tsv: {len(freqs)} entries from {total} counted tokens")


def _bocchan_spans():
    """Token spans (split at any token containing non-kana/han chars) from
    the IPADIC-tokenized Botchan text."""
    path = (f"{REF}/deeplearning4j-nlp-japanese/src/test/resources/"
            "bocchan-ipadic-features.txt")
    spans, cur = [], []
    with open(path, encoding="utf-8") as f:
        for line in f:
            tok = line.rstrip("\n").split("\t")[0]
            if tok and all(_is_han(c) or _is_kana(c) for c in tok):
                cur.append(tok)
            else:
                if cur:
                    spans.append(cur)
                cur = []
    if cur:
        spans.append(cur)
    return spans


def build_ja():
    spans = _bocchan_spans()
    cut = int(len(spans) * 0.8)
    train, held = spans[:cut], spans[cut:]
    counts = {}
    for span in train:
        for tok in span:
            counts[tok] = counts.get(tok, 0) + 1
    total = sum(counts.values())
    os.makedirs(OUT_DATA, exist_ok=True)
    with open(f"{OUT_DATA}/ja_ipadic.tsv", "w", encoding="utf-8") as f:
        f.write("# Japanese unigram log-probs learned from the first 80%% "
                "of the kuromoji test corpus\n# (IPADIC-tokenized 'Botchan'"
                ", Natsume Soseki, public domain; Apache-2.0 test\n"
                "# resource; ln(count/total), floor %.1f).  The held-out "
                "20%% is the gold fixture\n# cjk_gold_ja_bocchan.txt — "
                "the lexicon never saw it.\n" % MIN_LOGP)
        for w in sorted(counts):
            logp = max(math.log(counts[w] / total), MIN_LOGP)
            f.write(f"{w}\t{logp:.3f}\n")
    print(f"ja_ipadic.tsv: {len(counts)} entries from {total} train tokens "
          f"({cut}/{len(spans)} spans)")

    gold = [s for s in held if 4 <= len(s) <= 25][:250]
    with open(f"{OUT_RES}/cjk_gold_ja_bocchan.txt", "w",
              encoding="utf-8") as f:
        f.write("# Gold Japanese segmentations: held-out 20% of the "
                "IPADIC-tokenized 'Botchan'\n# (kuromoji test corpus, "
                "Apache-2.0; novel public domain).  Independent of the\n"
                "# bundled lexicon's training split by construction "
                "(tools/build_cjk_lexicons.py).\n")
        for span in gold:
            f.write(" ".join(span) + "\n")
    print(f"cjk_gold_ja_bocchan.txt: {len(gold)} sentences")


def build_ja_bigrams():
    """Bigram transition bonuses from the SAME 80% Botchan train split the
    unigrams came from (VERDICT r4 item 5 — the ansj ``NgramLibrary``/
    kuromoji ``ViterbiSearcher`` transition-cost mechanism).  Emitted as
    positive PMI values: ln(c12 * N / (c1 * c2)) for every pair seen (count floor 1),
    clipped to [0, 6].  The lattice adds beta * pmi on an edge whose word
    pair is in the table — unseen pairs fall back to pure unigram scoring,
    so rare-but-valid transitions are never penalized.  ``<s>`` rows carry
    span-initial transitions (what may START a run).

    Count floor and beta were selected on a dev split carved from INSIDE
    the train spans (fit 90% / dev 10%; min_c 1 + beta 0.75 won) — the
    held-out gold fixtures never touched the choice."""
    import collections
    spans = _bocchan_spans()
    cut = int(len(spans) * 0.8)
    train = spans[:cut]
    uni = collections.Counter()
    bi = collections.Counter()
    for span in train:
        prev = "<s>"
        for tok in span:
            uni[tok] += 1
            bi[(prev, tok)] += 1
            prev = tok
    uni["<s>"] = len(train)
    total = sum(c for w, c in uni.items() if w != "<s>")
    rows = []
    for (w1, w2), c12 in bi.items():
        pmi = math.log(c12 * total / (uni[w1] * uni[w2]))
        if pmi <= 0:
            continue
        rows.append((w1, w2, min(pmi, 6.0)))
    with open(f"{OUT_DATA}/ja_bigram.tsv", "w", encoding="utf-8") as f:
        f.write("# Japanese bigram transition bonuses (positive PMI, "
                "clipped to 6.0) learned from\n# the first 80% of the "
                "IPADIC-tokenized 'Botchan' (kuromoji test corpus,\n"
                "# Apache-2.0; novel public domain) — the same split the "
                "ja_ipadic.tsv unigrams\n# use, so the held-out gold stays "
                "independent.  '<s>' = span-initial.\n"
                "# Derivation: tools/build_cjk_lexicons.py build_ja_bigrams.\n")
        for w1, w2, pmi in sorted(rows):
            f.write(f"{w1}\t{w2}\t{pmi:.3f}\n")
    print(f"ja_bigram.tsv: {len(rows)} transitions from {total} tokens")


# Korean vocabulary tiers (VERDICT r4 item 8).  Unlike zh (ansj core.dic)
# and ja (kuromoji's IPADIC-tokenized corpus), the reference bundles NO
# Korean data: deeplearning4j-nlp-korean wraps the KOMORAN jar
# (KoreanTokenizerFactory.java) whose dictionary lives inside the jar, and
# no Korean corpus exists anywhere in the reference tree (verified round
# 5: src/main has two .java files, src/test none with data).  With zero
# egress there is nothing to derive from, so this tier is CURATED —
# everyday vocabulary written for coverage, graded into the same
# frequency bands the zh/ja cores use, and measured against the ko gold
# fixture like any other tier.
_KO_HIGH = """
마십니 씁니 삽니 탑니 배웁니 기다립니 드립니 모릅니 부릅니 만납니
봅니 줍니 다닙니 지냅니 떠납니 보냅니 가르칩니 들으 걸으 물으
나쁩니 비쌉니 핍니 놉니 붑니 납니 잡니 사십니 십니 보입니 열립니
바꿉니 빠릅니 겠 었 았 셨 으셨 으세요 예요 에요
거 니 요리 취소 저금 정원 연결 변경 설치 저장 확인 서울역 실험실
전화번호 단풍 조개 도착 출발 편리 통과 들려주 세웠 주웠 좋아졌
새로 새로운 바닷가
사람 시간 일 말 집 물 밥 돈 몸 맘 마음 생각 친구 학교 회사 나라 세상
이름 얼굴 소리 이야기 문제 경우 정도 때문 모습 모양 부분 전체 처음
마지막 다음 이번 지난번 오늘 내일 어제 아침 점심 저녁 밤 낮 주말 평일
올해 작년 내년 지금 요즘 나중 먼저 항상 가끔 자주 매일 매주 매달 매년
아버지 어머니 아빠 엄마 부모 형 누나 오빠 언니 동생 아들 딸 아이 어른
남자 여자 가족 부부 남편 아내 할아버지 할머니 선생님 학생 의사 경찰
"""
_KO_MID = """
소년 소녀 청년 노인 아기 손자 손녀 삼촌 이모 고모 사촌 친척 이웃 동료
선배 후배 애인 신랑 신부 주인 손님 고객 회원 시민 국민 주민 인간 인류
개인 타인 본인 자신 교사 교수 대학생 유학생 졸업생 간호사 환자 약사
변호사 판사 검사 군인 소방관 공무원 회사원 직원 사원 사장 부장 과장
대리 비서 기자 작가 시인 화가 가수 배우 감독 선수 코치 심판 농부 어부
요리사 운전사 기사 기술자 과학자 연구원 번역가 점원 판매원 미용사
머리 눈 코 입 귀 목 어깨 팔 손 손가락 다리 발 무릎 허리 배 가슴 등
피부 머리카락 눈물 땀 피 심장 뼈 근육 건강 병 감기 열 기침 두통 상처
약 주사 수술 치료 검사 진료 입원 퇴원 병원 의원 약국 응급실
방 거실 부엌 주방 화장실 욕실 침실 현관 마당 지붕 창문 문 벽 바닥
천장 계단 아파트 빌딩 건물 사무실 회의실 교실 강의실 도서관 식당
카페 레스토랑 시장 마트 백화점 편의점 가게 상점 서점 은행 우체국
대학교 고등학교 중학교 초등학교 유치원 학원 교회 성당 절 박물관
미술관 영화관 극장 경기장 체육관 수영장 공원 광장 놀이터 동물원
식물원 역 정류장 터미널 공항 항구 주차장 주유소 호텔 여관 교차로
인도 차도 도로 고속도로 다리 터널 골목 거리 시내 도심 교외 시골
도시 마을 동네 지역 지방 수도 세계 지구 우주 바다 해변 섬 산 숲 강
호수 연못 폭포 계곡 들판 사막 동굴 하늘 땅
시각 하루 이틀 모레 그제 오전 정오 오후 새벽 자정 요일 월요일 화요일
수요일 목요일 금요일 토요일 일요일 이번주 지난주 다음주 이번달
지난달 다음달 재작년 계절 봄 여름 가을 겨울 방학 휴가 명절 설날 추석
생일 기념일 새해 연휴 기간 동안 순간 최근 옛날 과거 현재 미래 장래
음식 쌀 반찬 국 찌개 김치 된장 고추장 간장 소금 설탕 후추 기름 식초
밀가루 빵 떡 면 국수 라면 냉면 비빔밥 김밥 불고기 갈비 삼겹살 치킨
생선 고기 소고기 돼지고기 닭고기 계란 달걀 두부 채소 야채 과일 사과
배 포도 딸기 수박 참외 복숭아 감 귤 오렌지 바나나 토마토 감자 고구마
양파 마늘 파 배추 무 오이 당근 시금치 버섯 콩 옥수수 호박 차 녹차
홍차 우유 주스 콜라 맥주 소주 와인 술 음료수 간식 과자 사탕 초콜릿
케이크 빙수 물건 물품 제품 상품 가구 책상 의자 침대 소파 옷장 책장
서랍 선반 거울 시계 손목시계 달력 액자 그림 사진 꽃병 이불 베개 담요
커튼 전화 전화기 휴대폰 핸드폰 냉장고 세탁기 청소기 선풍기 밥솥
다리미 충전기 리모컨 옷 한복 양복 정장 셔츠 바지 청바지 치마 원피스
코트 점퍼 재킷 스웨터 조끼 속옷 양말 신발 구두 운동화 슬리퍼 부츠
모자 장갑 목도리 넥타이 벨트 안경 선글라스 반지 목걸이 귀걸이 팔찌
가방 핸드백 배낭 지갑 우산 열쇠 수건 비누 샴푸 치약 칫솔 화장품 향수
휴지 쓰레기 쓰레기통 책 공책 연필 볼펜 지우개 자 가위 칼 풀 테이프
종이 편지 엽서 봉투 우표 신문 잡지 사전 교과서 지도 표 현금 동전
지폐 차 자동차 승용차 시내버스 고속버스 기차 열차 지하철 전철 자전거
오토바이 트럭 비행기 헬리콥터 배 여객선 보트 교통 운전 승차 하차
환승 정거장 노선 표지판 신호등 속도 사고 날씨 기온 온도 일기예보
맑음 흐림 구름 비 소나기 장마 눈 눈사람 바람 태풍 천둥 번개 무지개
안개 서리 이슬 얼음 홍수 가뭄 지진 해 태양 달 별 행성 햇빛 햇살 그늘
공기 산소 불 연기 먼지 흙 모래 바위 유리 플라스틱
나무 꽃 장미 벚꽃 무궁화 잎 나뭇잎 뿌리 줄기 가지 씨 씨앗 열매 풀
잔디 대나무 소나무 동물 개 강아지 고양이 새 참새 비둘기 까치 닭 오리
소 돼지 말 양 염소 토끼 쥐 호랑이 사자 코끼리 원숭이 곰 여우 늑대
사슴 기린 뱀 개구리 물고기 고래 상어 거북이 게 새우 오징어 문어 곤충
나비 벌 개미 모기 파리 거미 잠자리 정신 기분 감정 느낌 사랑 우정
행복 기쁨 슬픔 분노 화 걱정 고민 스트레스 두려움 공포 놀람 감동 감사
존경 믿음 신뢰 의심 희망 소망 꿈 목표 계획 약속 비밀 거짓말 진실
사실 진리 이유 원인 결과 목적 방법 수단 과정 순서 단계 기회 경험
추억 기억 지식 지혜 정보 소식 뉴스 대화 토론 회의 발표 연설 질문
대답 답변 설명 소개 인사 칭찬 비판 충고 조언 부탁 요청 명령 허락
금지 규칙 법 법률 제도 정책 정치 정부 대통령 국회 선거 투표 경제
시장 무역 수출 수입 산업 농업 공업 상업 기업 공장 사업 장사 직업
업무 근무 출근 퇴근 출장 회식 월급 급여 연봉 지출 가격 값 비용 요금
세금 저축 투자 보험 대출 이자 부자 가난 문화 예술 음악 노래 춤 미술
조각 문학 소설 시 수필 연극 영화 드라마 공연 전시회 축제 행사 파티
결혼식 장례식 종교 기독교 불교 천주교 역사 전통 풍습 예절 언어
한국어 영어 중국어 일본어 단어 문장 문법 발음 글 글자 한글 한자
교육 공부 학습 수업 강의 숙제 시험 성적 점수 합격 불합격 입학 졸업
전공 학과 학년 학기 등록금 장학금 운동 축구 야구 농구 배구 테니스
탁구 배드민턴 골프 수영 스키 스케이트 등산 달리기 마라톤 체조
태권도 유도 씨름 경기 시합 대회 올림픽 월드컵 우승 승리 패배 기록
여행 관광 구경 휴식 취미 독서 게임 오락 장난 산책 낚시 사냥 캠핑
소풍 나들이 쇼핑 외출 모임 데이트 과학 기술 발명 발견 실험 연구
이론 원리 법칙 자연 환경 오염 공해 재활용 에너지 전기 전자 기계
장치 도구 장비 시설 건설 공사 수리 제작 생산 제조 개발 발전 진보
변화 개선 혁신 성공 실패 노력 도전 경쟁 협력 협동 단결 통일 평화
전쟁 군대 무기 안전 위험 재난 구조 보호 예방 대비 한국 서울 부산
대구 인천 광주 대전 울산 제주 경기도 강원도 미국 일본 중국 영국
프랑스 독일 러시아 인도 베트남 태국 호주 캐나다 브라질 아시아 유럽
아프리카 아메리카
매우 아주 너무 정말 진짜 조금 약간 거의 전혀 늘 때때로 보통 다시 또
나중에 빨리 천천히 일찍 늦게 같이 함께 혼자 모두 다 전부 조용히
열심히 잘 못 안 더 덜 가장 제일 특히 역시 아마 혹시 만약 물론 갑자기
드디어 결국 마침내 벌써 이미 아직 이제 방금 곧 금방 오래 잠깐 잠시
먹 먹었 먹는 마시 마셨 보 봤 보는 듣 들었 듣는 말하 말했 읽 읽었 쓰
썼 쓰는 사 샀 사는 팔 팔았 파는 만들 만들었 만드는 만나 만났 만나는
기다리 기다렸 돕 도왔 돕는 배우 배웠 배우는 가르치 가르쳤 놀 놀았
노는 쉬 쉬었 쉬는 자 잤 자는 일어나 일어났 앉 앉았 앉는 서 섰 서는
걷 걸었 걷는 뛰 뛰었 뛰는 달리 달렸 달리는 오 왔 오는 가 갔 가는
주 줬 주는 받 받았 받는 넣 넣었 넣는 빼 뺐 빼는 열 열었 여는 닫 닫았
닫는 찾 찾았 찾는 잃 잃었 잃는 얻 얻었 얻는 배 웠 입 입었 입는 벗
벗었 벗는 신 신었 신는 씻 씻었 씻는 닦 닦았 닦는 던지 던졌 잡 잡았
잡는 놓 놓았 놓는 들 들었 드는 올리 올렸 내리 내렸 밀 밀었 미는 끌
끌었 끄는 누르 눌렀 돌리 돌렸 바꾸 바꿨 바꾸는 고치 고쳤 고치는 짓
지었 짓는 부수 부쉈 심 심었 심는 기르 길렀 키우 키웠 키우는 씹 삼키
뱉 불 불었 부는 웃 웃었 웃는 울 울었 우는 느끼 느꼈 느끼는 알 알았
아는 모르 몰랐 모르는 믿 믿었 믿는 바라 바랐 바라는 원하 원했 원하는
좋아하 좋아했 싫어하 싫어했 사랑하 사랑했 미워하 무서워하 두려워하
부러워하 그리워하 지내 지냈 살 살았 사는 죽 죽었 죽는 남 남았 남는
떠나 떠났 떠나는 도착하 도착했 출발하 출발했 시작하 시작했 끝나
끝났 끝나는 계속하 계속했 멈추 멈췄 그치 그쳤 생각하 생각했 생각하는 말 했 하
한 할 해 해서 했다 한다 하겠 되 된 될 됐 돼 되어 있 있다 있어 있으면
없 없다 없어 없으면 보이 보였 보이는 들리 들렸 들리는 나 났 나는
나오 나왔 나오는 들어가 들어갔 들어오 들어왔 올라가 올라갔 내려가
내려갔 돌아가 돌아갔 돌아오 돌아왔 지나가 지나갔 건너 건넜 따라가
따라갔 데려가 데려왔 가져가 가져왔 가져오 보내 보냈 보내는 전하
전했 알리 알렸 묻 물었 묻는 대답하 대답했 부르 불렀 부르는 외치
외쳤 속삭이 노래하 노래했 연주하 춤추 그리 그렸 그리는 찍 찍었
찍는 만지 만졌 두드리 흔들 흔들었 당기 당겼 감 감았 뜨 떴 쳐다보
바라보 바라봤 살피 살폈 지켜보 발견하 발견했 관찰하 조사하 조사했
확인하 확인했 점검하 검토하 준비하 준비했 연습하 연습했 훈련하
공부했 공부하는 연구하 연구했 가르쳤다 익히 익혔 외우 외웠 복습하
예습하 풀 풀었 푸는 계산하 계산했 측정하 비교하 비교했 분석하
분석했 정리하 정리했 기록하 기록했 작성하 작성했 저장하 저장했
삭제하 삭제했 수정하 수정했 편집하 입력하 입력했 출력하 검색하
검색했 사용하 사용했 사용하는 이용하 이용했 활용하 적용하 개발하
개발했 설계하 제작하 생산하 판매하 판매했 구입하 구입했 구매하
주문하 주문했 배달하 배달했 포장하 교환하 환불하 결제하 지불하
계약하 약속하 약속했 취소하 취소했 연기하 변경하 신청하 신청했
등록하 등록했 제출하 제출했 발송하 수령하 보관하 관리하 관리했
운영하 경영하 담당하 처리하 처리했 해결하 해결했 개선하 수행하
진행하 진행했 완료하 완성하 완성했 실패하 실패했 성공하 성공했
"""
_KO_LOW = """
컴퓨터 노트북 태블릿 텔레비전 라디오 카메라 비디오 오디오 에어컨
전자레인지 드라이기 배터리 스피커 이어폰 헤드폰 마이크 키보드
마우스 모니터 프린터 스캐너 인터넷 스마트폰 이메일 메시지 프로그램
소프트웨어 하드웨어 데이터 파일 폴더 웹사이트 홈페이지 블로그 채팅
온라인 오프라인 다운로드 업로드 로그인 로그아웃 비밀번호 아이디
버튼 클릭 애니메이션 만화 콘서트 앨범 노래방 메뉴 서비스 프런트
체크인 체크아웃 티켓 택시 버스 엘리베이터 에스컬레이터 오피스텔
센터 슈퍼마켓 쇼핑몰 브랜드 디자인 스타일 패션 모델 사이즈 컬러
테스트 프로젝트 세미나 미팅 스케줄 플랜 아이디어 시스템 네트워크
서버 클라우드 인공지능 로봇 스포츠 피트니스 헬스 요가 다이어트
비타민 샌드위치 샐러드 스파게티 피자 햄버거 아이스크림 커피 카메라맨
프로그래머 엔지니어 디자이너 아나운서 리포터 매니저 아르바이트
인터뷰 리포트 세미나 캠퍼스 동아리 서클 멤버 리더 캡틴 코치
챔피언 토너먼트 리그 시즌 스타디움 트랙 필드 골 슛 패스 드리블
홈런 배트 글러브 라켓 코트 네트 스코어 파울 게임기 레벨 스테이지
아이템 캐릭터 유저 버전 업데이트 업그레이드 설치 삭제 저장 복사
붙여넣기 검색 조회 입력 출력 접속 연결 차단 해제 설정 기능 옵션
화면 배경 아이콘 폰트 커서 창 탭 링크 주소창 북마크 즐겨찾기
알림 진동 무음 벨소리 통화 문자 영상통화 셀카 셀피 필터 해상도
화질 음질 볼륨 재생 정지 일시정지 녹음 녹화 편집 자막 더빙
일월 이월 삼월 사월 오월 유월 칠월 팔월 구월 시월 십일월 십이월
수원 성남 고양 용인 창원 청주 전주 천안 포항 김해 평택 경주 춘천
강릉 여수 순천 목포 안동 충청도 전라도 경상도 제주도 한강 낙동강
설악산 한라산 지리산 백두산 동해 서해 남해 독도 울릉도 광화문 명동
강남 홍대 이태원 종로 시청 남산 한옥 궁궐 경복궁 사찰 온돌 마루
소방서 세탁소 미용실 문구점 꽃집 빵집 정육점 분식집 떡볶이 순대
김치찌개 된장찌개 삼계탕 설렁탕 갈비탕 만두 전 부침개 잡채 나물
젓가락 숟가락 그릇 접시 컵 냄비 프라이팬 주전자 도마 행주 앞치마
상 밥상 식탁 찬장 싱크대 가스레인지 군인 군대 육군 해군 공군 장군
병사 훈련소 제대 입대 예비군 민방위 통역 번역 원어민 발표회 연수
자격증 이력서 면접 채용 합격자 신입 경력 승진 퇴직 은퇴 연금 실업
취업 구직 창업 부동산 전세 월세 임대 계약서 보증금 이사 입주 분양
하나 둘 셋 넷 다섯 여섯 일곱 여덟 아홉 열 스물 서른 마흔 쉰 예순
일흔 여든 아흔 백 천 만 억 조 영 공 일 이 삼 사 오 육 칠 팔 구 십
한 두 세 네 개 명 분 마리 권 장 병 잔 그릇 켤레 벌 채 대 척 편 곡
번 차례 살 세 원 달러 킬로 미터 센티 그램 리터 시간당 퍼센트
좋 나쁘 크 작 많 적 높 낮 길 짧 넓 좁 무겁 가볍 강하 약하 빠르 느리
가깝 멀 쉽 어렵 같 다르 새롭 낡 밝 어둡 희 검 붉 푸르 노랗 파랗
빨갛 하얗 까맣 덥 춥 따뜻하 시원하 뜨겁 차갑 달 쓰 맵 짜 시 싱겁
고소하 배고프 배부르 목마르 졸리 피곤하 아프 건강하 깨끗하 더럽
조용하 시끄럽 바쁘 한가하 즐겁 슬프 기쁘 무섭 외롭 심심하 재미있
재미없 맛있 맛없 멋있 예쁘 귀엽 잘생기 못생기 친절하 착하 나쁘
똑똑하 어리석 부지런하 게으르 용감하 정직하 겸손하 교만하 유명하
평범하 특별하 중요하 필요하 충분하 부족하 가능하 불가능하 편리하
불편하 위험하 안전하 복잡하 간단하 비슷하 똑같 다양하 풍부하
"""


def build_ko():
    """Curated Korean vocabulary tiers -> ko_curated.tsv (see the module
    comment above _KO_HIGH for why this one is curated, not derived)."""
    bands = [(_KO_HIGH, -5.5), (_KO_MID, -7.0), (_KO_LOW, -8.0)]
    entries = {}
    for text, logp in bands:
        for w in text.split():
            if w not in entries:          # first (highest) band wins
                entries[w] = logp
    # Granularity guards: the gold convention (KOMORAN-style, the existing
    # cjk_gold_ko.txt) separates surface-separable grammar morphemes:
    # past markers 았/었 when they are their own syllable (받|았|습니|다)
    # and the light verb 하다 off its noun (공부|를|합니|다, 도착|했).
    # Fused entries would swallow those boundaries, so drop any form whose
    # tail is such a morpheme and whose bare stem is itself in the
    # vocabulary.  Contracted pasts (봤, 왔, 눌렀 — fusion inside one
    # syllable) are unsplittable on the surface and stay whole.
    for w in [w for w in entries
              if len(w) > 1 and w[-1] in "았었" and w[:-1] in entries]:
        del entries[w]
    _HA_TAILS = ("하", "했", "하는", "합니", "해서", "했다", "한다", "하겠",
                 "하면", "하여", "하고", "해")
    for w in [w for w in entries
              for t in _HA_TAILS
              if len(w) > len(t) and w.endswith(t) and w[:-len(t)] in entries]:
        entries.pop(w, None)
    # Pronoun+josa surface collisions: 나는 is the participle of 나다, but
    # as a surface string it is overwhelmingly 나|는 (pronoun + topic
    # particle), which the lattice must keep splitting.
    for w in ("나는", "나를", "나도", "너는", "너를"):
        entries.pop(w, None)
    with open(f"{OUT_DATA}/ko_curated.tsv", "w", encoding="utf-8") as f:
        f.write("# Curated Korean vocabulary tiers (no derivable corpus "
                "exists in the reference:\n# deeplearning4j-nlp-korean "
                "wraps the KOMORAN jar and bundles no data files).\n"
                "# Bands -5.5 / -7.0 / -8.0 mirror the zh/ja curated "
                "cores; derivation (and the\n# full rationale): "
                "tools/build_cjk_lexicons.py build_ko.\n")
        for w in sorted(entries):
            f.write(f"{w}\t{entries[w]:.1f}\n")
    print(f"ko_curated.tsv: {len(entries)} entries")


def build_ja_kuromoji_gold():
    path = (f"{REF}/deeplearning4j-nlp-japanese/src/test/resources/"
            "search-segmentation-tests.txt")
    rows = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "\t" not in line:
                continue
            text, seg = line.split("\t", 1)
            toks = seg.split()
            if "".join(toks) != text:
                continue           # a few entries segment mid-normalization
            if not all(_is_han(c) or _is_kana(c) for c in text):
                continue           # latin/digit cases need no lattice
            rows.append(toks)
    with open(f"{OUT_RES}/cjk_gold_ja_kuromoji.txt", "w",
              encoding="utf-8") as f:
        f.write("# Gold Japanese segmentations hand-written by the "
                "kuromoji authors\n# (search-segmentation-tests.txt, "
                "Apache-2.0) — compound decomposition cases;\n# fully "
                "independent of this project.\n")
        for toks in rows:
            f.write(" ".join(toks) + "\n")
    print(f"cjk_gold_ja_kuromoji.txt: {len(rows)} sentences")


if __name__ == "__main__":
    build_zh()
    build_ja()
    build_ja_bigrams()
    build_ja_kuromoji_gold()
    build_ko()
