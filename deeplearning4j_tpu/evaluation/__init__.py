"""Evaluation suite (reference ``deeplearning4j-nn/.../eval/``): multi-class,
binary multi-label, regression, ROC family, calibration, HTML export."""
from .binary import EvaluationBinary
from .calibration import (EvaluationCalibration, Histogram,
                          ReliabilityDiagram)
from .classification import ConfusionMatrix, Evaluation
from .regression import RegressionEvaluation
from .roc import ROC, PrecisionRecallCurve, ROCBinary, ROCMultiClass, RocCurve
from .tools import (calibration_to_html, export_calibration_to_html,
                    export_roc_charts_to_html, rocs_to_html)

__all__ = ["Evaluation", "ConfusionMatrix", "EvaluationBinary",
           "EvaluationCalibration", "Histogram", "ReliabilityDiagram",
           "RegressionEvaluation", "ROC", "ROCBinary", "ROCMultiClass",
           "RocCurve", "PrecisionRecallCurve", "rocs_to_html",
           "calibration_to_html", "export_roc_charts_to_html",
           "export_calibration_to_html"]
