"""ROC / AUC evaluation.

Analogue of ``eval/ROC.java:34-74`` (exact mode default :74, thresholded via
``thresholdSteps`` :57), ``eval/ROCBinary.java``, ``eval/ROCMultiClass.java``
and the curve classes in ``eval/curves/`` (RocCurve, PrecisionRecallCurve).

Exact mode stores all (probability, label) pairs and computes exact AUROC /
AUPRC; thresholded mode accumulates fixed-threshold counts (memory-bounded,
for huge datasets) — both reference semantics.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

_trapz = getattr(np, "trapezoid", None) or np.trapz


class RocCurve:
    def __init__(self, thresholds, fpr, tpr):
        self.thresholds = np.asarray(thresholds)
        self.fpr = np.asarray(fpr)
        self.tpr = np.asarray(tpr)

    def calculate_auc(self) -> float:
        order = np.argsort(self.fpr, kind="stable")
        return float(_trapz(self.tpr[order], self.fpr[order]))


class PrecisionRecallCurve:
    def __init__(self, thresholds, precision, recall):
        self.thresholds = np.asarray(thresholds)
        self.precision = np.asarray(precision)
        self.recall = np.asarray(recall)

    def calculate_auprc(self) -> float:
        order = np.argsort(self.recall, kind="stable")
        return float(_trapz(self.precision[order], self.recall[order]))


class ROC:
    """Binary ROC. threshold_steps=0 → exact (reference default)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self.is_exact = threshold_steps == 0
        self._probs: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []
        if not self.is_exact:
            n = threshold_steps + 1
            self.thresholds = np.linspace(0.0, 1.0, n)
            self.tp = np.zeros(n)
            self.fp = np.zeros(n)
            self.fn = np.zeros(n)
            self.tn = np.zeros(n)

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        if labels.ndim == 2 and labels.shape[-1] == 2:
            # [P(class0), P(class1)] convention: positive = column 1
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        elif labels.ndim == 2 and labels.shape[-1] > 2:
            raise ValueError(
                f"ROC is binary-only (got {labels.shape[-1]} output columns); "
                "use ROCMultiClass (reference eval/ROC.java throws likewise)")
        labels = labels.reshape(-1)
        predictions = predictions.reshape(-1)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[keep], predictions[keep]
        if self.is_exact:
            self._probs.append(predictions)
            self._labels.append(labels)
        else:
            pos = labels > 0.5
            for i, t in enumerate(self.thresholds):
                pred_pos = predictions >= t
                self.tp[i] += np.sum(pred_pos & pos)
                self.fp[i] += np.sum(pred_pos & ~pos)
                self.fn[i] += np.sum(~pred_pos & pos)
                self.tn[i] += np.sum(~pred_pos & ~pos)

    def merge(self, other: "ROC"):
        if self.is_exact:
            self._probs.extend(other._probs)
            self._labels.extend(other._labels)
        else:
            self.tp += other.tp
            self.fp += other.fp
            self.fn += other.fn
            self.tn += other.tn

    def _exact_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        return np.concatenate(self._probs), np.concatenate(self._labels)

    def get_roc_curve(self) -> RocCurve:
        if self.is_exact:
            p, y = self._exact_arrays()
            order = np.argsort(-p, kind="stable")
            y = y[order] > 0.5
            tps = np.cumsum(y)
            fps = np.cumsum(~y)
            P, N = max(tps[-1], 1), max(fps[-1], 1)
            thr = p[order]
            tpr = np.concatenate([[0.0], tps / P])
            fpr = np.concatenate([[0.0], fps / N])
            return RocCurve(np.concatenate([[1.0], thr]), fpr, tpr)
        tpr = self.tp / np.maximum(self.tp + self.fn, 1)
        fpr = self.fp / np.maximum(self.fp + self.tn, 1)
        return RocCurve(self.thresholds, fpr, tpr)

    def get_precision_recall_curve(self) -> PrecisionRecallCurve:
        if self.is_exact:
            p, y = self._exact_arrays()
            order = np.argsort(-p, kind="stable")
            y = y[order] > 0.5
            tps = np.cumsum(y)
            fps = np.cumsum(~y)
            P = max(tps[-1], 1)
            prec = tps / np.maximum(tps + fps, 1)
            rec = tps / P
            return PrecisionRecallCurve(p[order], prec, rec)
        prec = self.tp / np.maximum(self.tp + self.fp, 1)
        rec = self.tp / np.maximum(self.tp + self.fn, 1)
        return PrecisionRecallCurve(self.thresholds, prec, rec)

    def calculate_auc(self) -> float:
        return self.get_roc_curve().calculate_auc()

    def calculate_auprc(self) -> float:
        return self.get_precision_recall_curve().calculate_auprc()

    def stats(self) -> str:
        return (f"AUC (Area under ROC curve): {self.calculate_auc():.6f}\n"
                f"AUPRC (Area under PR curve): {self.calculate_auprc():.6f}")


class ROCBinary:
    """Per-output-column binary ROC (reference eval/ROCBinary.java) for
    multi-label sigmoid outputs."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._rocs: List[ROC] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        n = labels.shape[-1]
        if not self._rocs:
            self._rocs = [ROC(self.threshold_steps) for _ in range(n)]
        for c in range(n):
            self._rocs[c].eval(labels[..., c], predictions[..., c], mask)

    def calculate_auc(self, col: int) -> float:
        return self._rocs[col].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))

    def num_labels(self) -> int:
        return len(self._rocs)


class ROCMultiClass:
    """One-vs-all ROC per class (reference eval/ROCMultiClass.java)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._rocs: List[ROC] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        n = labels.shape[-1]
        if not self._rocs:
            self._rocs = [ROC(self.threshold_steps) for _ in range(n)]
        for c in range(n):
            self._rocs[c].eval(labels[:, c], predictions[:, c], mask)

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))
