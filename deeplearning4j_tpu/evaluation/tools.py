"""HTML export of evaluation artifacts (reference
``deeplearning4j-core/.../evaluation/EvaluationTools.java`` — ROC/calibration
chart export).  Self-contained inline-SVG pages, no external assets."""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["export_roc_charts_to_html", "export_calibration_to_html",
           "rocs_to_html", "calibration_to_html"]

_W, _H, _PAD = 420, 320, 45


def _polyline(xs, ys, color: str, width: int = 2) -> str:
    pts = " ".join(
        f"{_PAD + x * (_W - 2 * _PAD):.1f},"
        f"{_H - _PAD - y * (_H - 2 * _PAD):.1f}"
        for x, y in zip(xs, ys) if np.isfinite(x) and np.isfinite(y))
    return (f'<polyline fill="none" stroke="{color}" '
            f'stroke-width="{width}" points="{pts}"/>')


def _axes(title: str, xlabel: str, ylabel: str) -> str:
    return (
        f'<rect x="{_PAD}" y="{_PAD}" width="{_W-2*_PAD}" height="{_H-2*_PAD}"'
        f' fill="none" stroke="#999"/>'
        f'<text x="{_W/2}" y="18" text-anchor="middle" font-size="13">{title}</text>'
        f'<text x="{_W/2}" y="{_H-8}" text-anchor="middle" font-size="11">{xlabel}</text>'
        f'<text x="12" y="{_H/2}" text-anchor="middle" font-size="11" '
        f'transform="rotate(-90 12 {_H/2})">{ylabel}</text>'
        + "".join(
            f'<text x="{_PAD + f * (_W - 2*_PAD)}" y="{_H-_PAD+14}" '
            f'text-anchor="middle" font-size="9">{f:.1f}</text>'
            f'<text x="{_PAD-6}" y="{_H-_PAD - f*(_H-2*_PAD)+3}" '
            f'text-anchor="end" font-size="9">{f:.1f}</text>'
            for f in (0.0, 0.5, 1.0)))


def _svg(body: str) -> str:
    return (f'<svg width="{_W}" height="{_H}" '
            f'xmlns="http://www.w3.org/2000/svg">{body}</svg>')


def rocs_to_html(rocs, names: Optional[Sequence[str]] = None) -> str:
    """ROC curves (one chart per ROC with AUC in the title)."""
    charts = []
    if not isinstance(rocs, (list, tuple)):
        rocs = [rocs]
    for i, roc in enumerate(rocs):
        curve = roc.get_roc_curve()
        name = names[i] if names else f"output {i}"
        body = _axes(f"ROC {name} (AUC={curve.calculate_auc():.4f})",
                     "false positive rate", "true positive rate")
        body += _polyline([0, 1], [0, 1], "#bbb", 1)
        body += _polyline(curve.fpr, curve.tpr, "#1565c0")
        charts.append(_svg(body))
        pr = roc.get_precision_recall_curve()
        body = _axes(f"P-R {name} (AUPRC={pr.calculate_auprc():.4f})",
                     "recall", "precision")
        body += _polyline(pr.recall, pr.precision, "#c62828")
        charts.append(_svg(body))
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            "<title>ROC report</title></head><body>"
            + "".join(charts) + "</body></html>")


def calibration_to_html(cal, class_indices: Optional[Sequence[int]] = None
                        ) -> str:
    """Reliability diagrams + probability histograms."""
    classes = list(class_indices
                   if class_indices is not None else range(cal._n_classes))
    charts = []
    for c in classes:
        d = cal.reliability_diagram(c)
        body = _axes(f"Reliability class {c} "
                     f"(ECE={cal.expected_calibration_error(c):.4f})",
                     "mean predicted", "fraction positive")
        body += _polyline([0, 1], [0, 1], "#bbb", 1)
        ok = np.isfinite(d.fraction_positives)
        body += _polyline(d.mean_predicted_value[ok], d.fraction_positives[ok],
                          "#2e7d32")
        charts.append(_svg(body))
        h = cal.probability_histogram(c)
        mx = max(int(h.bin_counts.max()), 1)
        bw = (_W - 2 * _PAD) / h.n_bins
        bars = "".join(
            f'<rect x="{_PAD + j * bw:.1f}" '
            f'y="{_H - _PAD - (v / mx) * (_H - 2 * _PAD):.1f}" '
            f'width="{bw:.1f}" height="{(v / mx) * (_H - 2 * _PAD):.1f}" '
            f'fill="#1565c0"/>' for j, v in enumerate(h.bin_counts))
        charts.append(_svg(_axes(h.title, "p", "count") + bars))
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            "<title>Calibration report</title></head><body>"
            + "".join(charts) + "</body></html>")


def export_roc_charts_to_html(rocs, path: str,
                              names: Optional[Sequence[str]] = None) -> None:
    with open(path, "w") as fh:
        fh.write(rocs_to_html(rocs, names))


def export_calibration_to_html(cal, path: str,
                               class_indices: Optional[Sequence[int]] = None
                               ) -> None:
    with open(path, "w") as fh:
        fh.write(calibration_to_html(cal, class_indices))
