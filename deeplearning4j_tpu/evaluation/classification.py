"""Classification evaluation.

Analogue of ``eval/Evaluation.java:72`` + ``eval/ConfusionMatrix.java`` and
``eval/EvaluationBinary.java``: accuracy, precision, recall, F-beta, Matthews
correlation, confusion matrix, top-N accuracy, per-class reports.  Accumulation
is streaming (eval batch by batch), matching the reference's merge semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class ConfusionMatrix:
    """Integer confusion-count matrix (reference eval/ConfusionMatrix.java)."""

    def __init__(self, n_classes: int):
        self.n = n_classes
        self.matrix = np.zeros((n_classes, n_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def add_batch(self, actual: np.ndarray, predicted: np.ndarray):
        np.add.at(self.matrix, (actual, predicted), 1)

    def actual_total(self, cls: int) -> int:
        return int(self.matrix[cls].sum())

    def predicted_total(self, cls: int) -> int:
        return int(self.matrix[:, cls].sum())

    def count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def total(self) -> int:
        return int(self.matrix.sum())


class Prediction:
    """One recorded (actual, predicted, metadata) triple (reference
    ``eval/meta/Prediction.java``)."""

    __slots__ = ("actual", "predicted", "metadata")

    def __init__(self, actual: int, predicted: int, metadata=None):
        self.actual = actual
        self.predicted = predicted
        self.metadata = metadata

    def __repr__(self):
        return (f"Prediction(actual={self.actual}, "
                f"predicted={self.predicted}, metadata={self.metadata!r})")


class Evaluation:
    """Multi-class classification metrics (reference eval/Evaluation.java)."""

    def __init__(self, n_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None, top_n: int = 1):
        self.n_classes = n_classes
        self.label_names = labels
        self.top_n = top_n
        self.confusion: Optional[ConfusionMatrix] = None
        self.top_n_correct = 0
        self.top_n_total = 0
        self._predictions: List[Prediction] = []

    # ------------------------------------------------------------------ eval
    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None, record_metadata=None):
        """labels/predictions: [batch, n_classes] probabilities or one-hot;
        time series [batch, time, n_classes] are flattened (reference
        evalTimeSeries).  record_metadata: optional per-example objects
        (reference ``eval/meta/``) enabling get_prediction_errors()."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                labels, predictions = labels[keep], predictions[keep]
        elif mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[keep], predictions[keep]

        if labels.ndim == 1 or labels.shape[-1] == 1:
            # binary 0/1 labels in a single column
            labels = labels.reshape(-1)
            n = 2
            actual = (labels > 0.5).astype(np.int64)
            p = predictions.reshape(-1)
            predicted = (p > 0.5).astype(np.int64)
        else:
            n = labels.shape[-1]
            actual = labels.argmax(-1)
            predicted = predictions.argmax(-1)

        if self.confusion is None:
            self.n_classes = self.n_classes or n
            self.confusion = ConfusionMatrix(self.n_classes)
        self.confusion.add_batch(actual, predicted)

        if record_metadata is not None:
            if len(record_metadata) != len(actual):
                raise ValueError(
                    f"{len(record_metadata)} metadata entries for "
                    f"{len(actual)} (post-mask) examples")
            for a, p, md in zip(actual, predicted, record_metadata):
                self._predictions.append(
                    Prediction(int(a), int(p), md))

        if self.top_n > 1 and predictions.ndim == 2:
            topn = np.argsort(-predictions, axis=-1)[:, :self.top_n]
            self.top_n_correct += int((topn == actual[:, None]).any(axis=1).sum())
            self.top_n_total += len(actual)

    def merge(self, other: "Evaluation"):
        if other.confusion is None:
            return
        if self.confusion is None:
            self.n_classes = other.n_classes
            self.confusion = ConfusionMatrix(self.n_classes)
        self.confusion.matrix += other.confusion.matrix
        self.top_n_correct += other.top_n_correct
        self.top_n_total += other.top_n_total
        self._predictions.extend(other._predictions)

    # ------------------------------------------------------------- serde
    def to_json(self) -> str:
        """Reference ``eval/serde``: evaluations are serializable so
        workers can ship partial results for merge()."""
        import json
        return json.dumps({
            "type": "Evaluation",
            "n_classes": self.n_classes,
            "labels": self.label_names,
            "top_n": self.top_n,
            "top_n_correct": self.top_n_correct,
            "top_n_total": self.top_n_total,
            "confusion": (self.confusion.matrix.tolist()
                          if self.confusion is not None else None),
            "predictions": [
                {"a": p.actual, "p": p.predicted,
                 "m": p.metadata if isinstance(
                     p.metadata, (str, int, float, type(None)))
                 else str(p.metadata)}
                for p in self._predictions],
        })

    @staticmethod
    def from_json(s: str) -> "Evaluation":
        import json
        d = json.loads(s)
        ev = Evaluation(n_classes=d["n_classes"], labels=d["labels"],
                        top_n=d.get("top_n", 1))
        if d.get("confusion") is not None:
            ev.confusion = ConfusionMatrix(d["n_classes"])
            ev.confusion.matrix = np.asarray(d["confusion"], np.int64)
        ev.top_n_correct = d.get("top_n_correct", 0)
        ev.top_n_total = d.get("top_n_total", 0)
        ev._predictions = [Prediction(r["a"], r["p"], r.get("m"))
                           for r in d.get("predictions", [])]
        return ev

    # ----------------------------------------------------- prediction meta
    def get_prediction_errors(self) -> List["Prediction"]:
        """Misclassified examples with their metadata (reference
        ``getPredictionErrors``)."""
        return [p for p in self._predictions if p.actual != p.predicted]

    def get_predictions_by_actual_class(self, cls: int) -> List["Prediction"]:
        return [p for p in self._predictions if p.actual == cls]

    def get_predictions_by_predicted_class(self, cls: int
                                           ) -> List["Prediction"]:
        return [p for p in self._predictions if p.predicted == cls]

    # --------------------------------------------------------------- metrics
    def _tp(self, c):
        return self.confusion.count(c, c)

    def _fp(self, c):
        return self.confusion.predicted_total(c) - self._tp(c)

    def _fn(self, c):
        return self.confusion.actual_total(c) - self._tp(c)

    def accuracy(self) -> float:
        m = self.confusion.matrix
        tot = m.sum()
        return float(np.trace(m) / tot) if tot else 0.0

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.top_n_total if self.top_n_total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            d = self._tp(cls) + self._fp(cls)
            return self._tp(cls) / d if d else 0.0
        vals = [self.precision(c) for c in range(self.n_classes)
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            d = self._tp(cls) + self._fn(cls)
            return self._tp(cls) / d if d else 0.0
        vals = [self.recall(c) for c in range(self.n_classes)
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        return self.f_beta(1.0, cls)

    def f_beta(self, beta: float, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        d = beta * beta * p + r
        return float((1 + beta * beta) * p * r / d) if d else 0.0

    def matthews_correlation(self, cls: int) -> float:
        tp, fp, fn = self._tp(cls), self._fp(cls), self._fn(cls)
        tn = self.confusion.total() - tp - fp - fn
        num = tp * tn - fp * fn
        den = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float(num / den) if den else 0.0

    def false_positive_rate(self, cls: int) -> float:
        fp = self._fp(cls)
        tn = self.confusion.total() - self._tp(cls) - fp - self._fn(cls)
        return fp / (fp + tn) if (fp + tn) else 0.0

    def false_negative_rate(self, cls: int) -> float:
        fn = self._fn(cls)
        return fn / (fn + self._tp(cls)) if (fn + self._tp(cls)) else 0.0

    # ---------------------------------------------------------------- report
    def stats(self) -> str:
        if self.confusion is None:
            return "<no data>"
        lines = ["", "========================Evaluation Metrics========================"]
        lines.append(f" # of classes:    {self.n_classes}")
        lines.append(f" Accuracy:        {self.accuracy():.4f}")
        if self.top_n > 1:
            lines.append(f" Top {self.top_n} Accuracy:  {self.top_n_accuracy():.4f}")
        lines.append(f" Precision:       {self.precision():.4f}")
        lines.append(f" Recall:          {self.recall():.4f}")
        lines.append(f" F1 Score:        {self.f1():.4f}")
        lines.append("")
        lines.append("=========================Confusion Matrix=========================")
        lines.append(str(self.confusion.matrix))
        lines.append("==================================================================")
        return "\n".join(lines)

    def __str__(self):
        return self.stats()
