"""Per-output binary classification evaluation (reference
``eval/EvaluationBinary.java``: independent binary stats per output column,
with optional per-label decision thresholds and mask support)."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["EvaluationBinary"]


class EvaluationBinary:
    """Counts TP/FP/TN/FN independently for each of the n output columns
    (multi-label setting — each column is its own binary problem)."""

    def __init__(self, n_labels: Optional[int] = None,
                 decision_threshold: float = 0.5,
                 thresholds: Optional[Sequence[float]] = None,
                 label_names: Optional[List[str]] = None):
        self.n_labels = n_labels
        self.decision_threshold = decision_threshold
        self.thresholds = None if thresholds is None else np.asarray(thresholds)
        self.label_names = label_names
        self.tp = self.fp = self.tn = self.fn = None

    def _ensure(self, n: int):
        if self.tp is None:
            self.n_labels = n
            self.tp = np.zeros(n, np.int64)
            self.fp = np.zeros(n, np.int64)
            self.tn = np.zeros(n, np.int64)
            self.fn = np.zeros(n, np.int64)

    def eval(self, labels, predictions, mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        n_out = labels.shape[-1]
        if mask is not None:
            mask = np.asarray(mask)
        if labels.ndim == 3:  # time series: flatten [b,t,n] -> [b*t,n]
            labels = labels.reshape(-1, n_out)
            predictions = predictions.reshape(-1, n_out)
            if mask is not None:
                # per-step [b,t] -> [b*t]; per-output [b,t,n] -> [b*t,n]
                mask = (mask.reshape(-1, n_out) if mask.ndim == 3
                        else mask.reshape(-1))
        self._ensure(n_out)
        t = (self.thresholds if self.thresholds is not None
             else self.decision_threshold)
        pred = (predictions >= t).astype(np.int64)
        lab = (labels >= 0.5).astype(np.int64)
        if mask is None:
            w = np.ones((len(lab), 1), np.int64)
        elif mask.ndim == 1:   # per-example weight, broadcast over outputs
            w = (mask > 0).astype(np.int64)[:, None]
        else:                  # per-output weight [N, n]
            w = (mask > 0).astype(np.int64)
        # weighted per-label counts: never index-flatten, so per-output masks
        # keep the label axis intact
        self.tp += (((pred == 1) & (lab == 1)) * w).sum(0)
        self.fp += (((pred == 1) & (lab == 0)) * w).sum(0)
        self.tn += (((pred == 0) & (lab == 0)) * w).sum(0)
        self.fn += (((pred == 0) & (lab == 1)) * w).sum(0)
        return self

    def merge(self, other: "EvaluationBinary") -> "EvaluationBinary":
        if other.tp is None:
            return self
        self._ensure(len(other.tp))
        self.tp += other.tp
        self.fp += other.fp
        self.tn += other.tn
        self.fn += other.fn
        return self

    # ---- per-label metrics -------------------------------------------------
    def _div(self, a, b):
        return np.divide(a, b, out=np.zeros_like(a, dtype=float),
                         where=b > 0)

    def accuracy(self, label: Optional[int] = None):
        acc = self._div(self.tp + self.tn, self.tp + self.tn + self.fp + self.fn)
        return float(acc[label]) if label is not None else acc

    def precision(self, label: Optional[int] = None):
        p = self._div(self.tp, self.tp + self.fp)
        return float(p[label]) if label is not None else p

    def recall(self, label: Optional[int] = None):
        r = self._div(self.tp, self.tp + self.fn)
        return float(r[label]) if label is not None else r

    def f1(self, label: Optional[int] = None):
        p, r = self.precision(), self.recall()
        f = self._div(2 * p * r, p + r)
        return float(f[label]) if label is not None else f

    def average_accuracy(self) -> float:
        return float(np.mean(self.accuracy()))

    def average_f1(self) -> float:
        return float(np.mean(self.f1()))

    def false_alarm_rate(self, label: Optional[int] = None):
        fa = self._div(self.fp, self.fp + self.tn)
        return float(fa[label]) if label is not None else fa

    def stats(self) -> str:
        names = (self.label_names
                 or [f"label_{i}" for i in range(self.n_labels or 0)])
        lines = [f"{'label':<16}{'acc':>8}{'prec':>8}{'rec':>8}{'f1':>8}"
                 f"{'tp':>8}{'fp':>8}{'tn':>8}{'fn':>8}"]
        for i, nm in enumerate(names):
            lines.append(
                f"{nm:<16}{self.accuracy(i):>8.4f}{self.precision(i):>8.4f}"
                f"{self.recall(i):>8.4f}{self.f1(i):>8.4f}"
                f"{self.tp[i]:>8}{self.fp[i]:>8}{self.tn[i]:>8}{self.fn[i]:>8}")
        lines.append(f"average accuracy: {self.average_accuracy():.4f}  "
                     f"average f1: {self.average_f1():.4f}")
        return "\n".join(lines)
