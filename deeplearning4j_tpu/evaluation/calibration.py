"""Probability-calibration evaluation (reference
``eval/EvaluationCalibration.java`` + curves ``eval/curves/ReliabilityDiagram``,
``Histogram``): reliability diagrams, residual histograms, and predicted-
probability histograms per class, plus expected calibration error (ECE)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

__all__ = ["EvaluationCalibration", "ReliabilityDiagram", "Histogram"]


@dataclass
class Histogram:
    title: str
    lower: float
    upper: float
    bin_counts: np.ndarray

    @property
    def n_bins(self) -> int:
        return len(self.bin_counts)


@dataclass
class ReliabilityDiagram:
    title: str
    mean_predicted_value: np.ndarray  # per bin
    fraction_positives: np.ndarray    # per bin (NaN where bin empty)
    bin_counts: np.ndarray


class EvaluationCalibration:
    """Accumulates (label, predicted prob) pairs binned by confidence."""

    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 50):
        self.reliability_bins = reliability_bins
        self.histogram_bins = histogram_bins
        self._n_classes: Optional[int] = None
        # per class: sum of probs, count of positives, count, per bin
        self._prob_sum = None
        self._pos_count = None
        self._count = None
        self._residual_counts = None
        self._prob_counts = None

    def _ensure(self, n_classes: int):
        if self._n_classes is None:
            self._n_classes = n_classes
            rb, hb = self.reliability_bins, self.histogram_bins
            self._prob_sum = np.zeros((n_classes, rb))
            self._pos_count = np.zeros((n_classes, rb), np.int64)
            self._count = np.zeros((n_classes, rb), np.int64)
            self._residual_counts = np.zeros((n_classes, hb), np.int64)
            self._prob_counts = np.zeros((n_classes, hb), np.int64)

    def eval(self, labels, predictions):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        self._ensure(labels.shape[-1])
        rb, hb = self.reliability_bins, self.histogram_bins
        bins = np.clip((predictions * rb).astype(int), 0, rb - 1)
        resid = np.abs(labels - predictions)
        rbins = np.clip((resid * hb).astype(int), 0, hb - 1)
        pbins = np.clip((predictions * hb).astype(int), 0, hb - 1)
        for c in range(self._n_classes):
            np.add.at(self._prob_sum[c], bins[:, c], predictions[:, c])
            np.add.at(self._pos_count[c], bins[:, c],
                      (labels[:, c] >= 0.5).astype(np.int64))
            np.add.at(self._count[c], bins[:, c], 1)
            np.add.at(self._residual_counts[c], rbins[:, c], 1)
            np.add.at(self._prob_counts[c], pbins[:, c], 1)
        return self

    # ---- outputs -----------------------------------------------------------
    def reliability_diagram(self, class_idx: int) -> ReliabilityDiagram:
        cnt = self._count[class_idx]
        with np.errstate(invalid="ignore", divide="ignore"):
            mean_pred = np.where(cnt > 0, self._prob_sum[class_idx]
                                 / np.maximum(cnt, 1), np.nan)
            frac_pos = np.where(cnt > 0, self._pos_count[class_idx]
                                / np.maximum(cnt, 1), np.nan)
        return ReliabilityDiagram(f"class {class_idx}", mean_pred, frac_pos,
                                  cnt.copy())

    def residual_histogram(self, class_idx: int) -> Histogram:
        return Histogram(f"|label - p| class {class_idx}", 0.0, 1.0,
                         self._residual_counts[class_idx].copy())

    def probability_histogram(self, class_idx: int) -> Histogram:
        return Histogram(f"P(class {class_idx})", 0.0, 1.0,
                         self._prob_counts[class_idx].copy())

    def expected_calibration_error(self, class_idx: Optional[int] = None
                                   ) -> float:
        """ECE: count-weighted mean |confidence - accuracy| over bins."""
        classes = ([class_idx] if class_idx is not None
                   else range(self._n_classes))
        total_err = total_cnt = 0.0
        for c in classes:
            d = self.reliability_diagram(c)
            ok = d.bin_counts > 0
            total_err += np.sum(np.abs(d.mean_predicted_value[ok]
                                       - d.fraction_positives[ok])
                                * d.bin_counts[ok])
            total_cnt += d.bin_counts[ok].sum()
        return float(total_err / max(total_cnt, 1.0))
