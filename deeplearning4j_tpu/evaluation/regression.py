"""Regression evaluation (reference ``eval/RegressionEvaluation.java``).

Streaming accumulation of MSE, MAE, RMSE, RSE, PC (Pearson correlation), R².
Per-column statistics, merged across batches exactly as the reference does.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None,
                 column_names: Optional[List[str]] = None):
        self.n_columns = n_columns
        self.column_names = column_names
        self._initialized = False

    def _init_stats(self, n):
        self.n_columns = n
        z = lambda: np.zeros(n, dtype=np.float64)
        self.sum_abs_err = z()
        self.sum_sq_err = z()
        self.sum_label = z()
        self.sum_sq_label = z()
        self.sum_pred = z()
        self.sum_sq_pred = z()
        self.sum_label_pred = z()
        self.count = z()
        self._initialized = True

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[keep], predictions[keep]
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        if not self._initialized:
            self._init_stats(labels.shape[1])
        err = predictions - labels
        self.sum_abs_err += np.abs(err).sum(0)
        self.sum_sq_err += (err ** 2).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_sq_label += (labels ** 2).sum(0)
        self.sum_pred += predictions.sum(0)
        self.sum_sq_pred += (predictions ** 2).sum(0)
        self.sum_label_pred += (labels * predictions).sum(0)
        self.count += labels.shape[0]

    def merge(self, other: "RegressionEvaluation"):
        if not other._initialized:
            return
        if not self._initialized:
            self._init_stats(other.n_columns)
        for f in ("sum_abs_err", "sum_sq_err", "sum_label", "sum_sq_label",
                  "sum_pred", "sum_sq_pred", "sum_label_pred", "count"):
            setattr(self, f, getattr(self, f) + getattr(other, f))

    # ---- metrics ------------------------------------------------------------
    def mean_squared_error(self, col: int) -> float:
        return float(self.sum_sq_err[col] / self.count[col])

    def mean_absolute_error(self, col: int) -> float:
        return float(self.sum_abs_err[col] / self.count[col])

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.sum_sq_err[col] / self.count[col]))

    def relative_squared_error(self, col: int) -> float:
        n = self.count[col]
        mean_label = self.sum_label[col] / n
        ss_tot = self.sum_sq_label[col] - n * mean_label ** 2
        return float(self.sum_sq_err[col] / ss_tot) if ss_tot else float("nan")

    def pearson_correlation(self, col: int) -> float:
        n = self.count[col]
        cov = self.sum_label_pred[col] - self.sum_label[col] * self.sum_pred[col] / n
        vl = self.sum_sq_label[col] - self.sum_label[col] ** 2 / n
        vp = self.sum_sq_pred[col] - self.sum_pred[col] ** 2 / n
        den = np.sqrt(vl * vp)
        return float(cov / den) if den else float("nan")

    def r_squared(self, col: int) -> float:
        rse = self.relative_squared_error(col)
        return 1.0 - rse

    def average_mean_squared_error(self) -> float:
        return float(np.mean([self.mean_squared_error(c) for c in range(self.n_columns)]))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean([self.mean_absolute_error(c) for c in range(self.n_columns)]))

    def average_root_mean_squared_error(self) -> float:
        return float(np.mean([self.root_mean_squared_error(c) for c in range(self.n_columns)]))

    def average_pearson_correlation(self) -> float:
        return float(np.mean([self.pearson_correlation(c) for c in range(self.n_columns)]))

    def average_r_squared(self) -> float:
        return float(np.mean([self.r_squared(c) for c in range(self.n_columns)]))

    def stats(self) -> str:
        lines = ["Column    MSE            MAE            RMSE           RSE            PC             R^2"]
        for c in range(self.n_columns):
            name = (self.column_names[c] if self.column_names and c < len(self.column_names)
                    else f"col_{c}")
            lines.append(
                f"{name:<10}{self.mean_squared_error(c):<15.6e}"
                f"{self.mean_absolute_error(c):<15.6e}"
                f"{self.root_mean_squared_error(c):<15.6e}"
                f"{self.relative_squared_error(c):<15.6e}"
                f"{self.pearson_correlation(c):<15.6e}"
                f"{self.r_squared(c):<.6e}")
        return "\n".join(lines)

    def __str__(self):
        return self.stats()
