"""Training listeners (callbacks).

Analogue of ``optimize/api/IterationListener.java`` / ``TrainingListener.java``
and the impls in ``optimize/listeners/``: ScoreIterationListener,
PerformanceListener, EvaluativeListener, CollectScoresIterationListener,
TimeIterationListener, SleepyTrainingListener, ComposableIterationListener.
"""
from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

from ..observability.clock import monotonic_s

log = logging.getLogger("deeplearning4j_tpu.train")


def boundary_score(model):
    """The latest host-visible score WITHOUT forcing a device sync.

    Returns ``(score, drained_at)``.  A plain loop materializes
    ``_score`` per step (host float — use it, ``drained_at`` None).
    The pipelined fit loops keep ``_score`` a device scalar and publish
    the most recently DRAINED step's value at the window boundary
    (``last_drained_score`` / ``last_drained_iteration``, written by
    ``nn.dispatch.DispatchWindow``): read that — stale by at most the
    dispatch depth, never a host sync.  Only when neither exists (a
    custom loop before anything drained) fall back to a real
    ``get_score()`` sync."""
    raw = getattr(model, "_score", None)
    if isinstance(raw, float):
        return raw, None
    drained_at = getattr(model, "last_drained_iteration", -1)
    if isinstance(drained_at, int) and drained_at >= 0:
        return getattr(model, "last_drained_score", float("nan")), drained_at
    return float(model.get_score()), None


class TrainingListener:
    """Base callback; all hooks optional (reference TrainingListener.java)."""

    def iteration_done(self, model, iteration: int, epoch: int) -> None:
        pass

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass

    def on_forward_pass(self, model, activations) -> None:
        pass

    def on_gradient_calculation(self, model) -> None:
        pass

    def on_backward_pass(self, model) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.print_iterations == 0:
            score, drained_at = boundary_score(model)
            if drained_at is not None and drained_at != iteration:
                log.info("Score at iteration %d is %s (drained @ %d)",
                         iteration, score, drained_at)
            else:
                log.info("Score at iteration %d is %s", iteration, score)


class PerformanceListener(TrainingListener):
    """Throughput: samples/sec, batches/sec
    (reference ``optimize/listeners/PerformanceListener.java:19,48-96``).

    Steady-state semantics: reported rates NEVER include the first
    observed iteration — it is compile-dominated (XLA traces + compiles
    the whole step program there), so a window containing it under-reads
    throughput by orders of magnitude.  The baseline clock starts at the
    first hook call (after that iteration completed) and every window is
    measured from there on the shared monotonic clock helpers
    (``observability.clock``), immune to wall-clock steps.
    """

    def __init__(self, frequency: int = 1, report_score: bool = False,
                 batch_size_fn: Optional[Callable] = None):
        self.frequency = max(1, frequency)
        self.report_score = report_score
        self.batch_size_fn = batch_size_fn
        self._last_time = None
        self._last_iter = 0
        self.samples_per_sec = float("nan")
        self.batches_per_sec = float("nan")
        self.last_batch_size = 0

    def iteration_done(self, model, iteration, epoch):
        now = monotonic_s()
        if self.batch_size_fn is not None:
            self.last_batch_size = self.batch_size_fn(model)
        else:
            self.last_batch_size = getattr(model, "last_batch_size", 0)
        if self._last_time is None:
            # first observation closes the compile-dominated iteration:
            # start the steady-state clock here, report nothing yet
            self._last_time = now
            self._last_iter = iteration
            return
        if iteration % self.frequency == 0:
            dt = max(now - self._last_time, 1e-9)
            iters = max(iteration - self._last_iter, 1)
            self.batches_per_sec = iters / dt
            if self.last_batch_size:
                self.samples_per_sec = self.last_batch_size * iters / dt
            msg = (f"iteration {iteration}; iterations/sec: "
                   f"{self.batches_per_sec:.3f}; samples/sec: {self.samples_per_sec:.3f}")
            etl = getattr(model, "last_etl_ms", None)
            if etl is not None:
                msg += f"; ETL: {etl:.1f} ms"
            if self.report_score:
                # window-drain boundary read: rate reporting must not
                # re-serialize the pipeline it is measuring
                msg += f"; score: {boundary_score(model)[0]}"
            log.info(msg)
            self._last_time = now
            self._last_iter = iteration


class CollectScoresIterationListener(TrainingListener):
    """Collect (iteration, score) pairs (reference CollectScoresIterationListener)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.get_score()))


class TimeIterationListener(TrainingListener):
    """Estimate remaining time (reference TimeIterationListener)."""

    def __init__(self, iteration_count: int, frequency: int = 50):
        self.iteration_count = iteration_count
        self.frequency = max(1, frequency)
        self.start = monotonic_s()

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = monotonic_s() - self.start
            remaining = elapsed / iteration * (self.iteration_count - iteration)
            log.info("Remaining time: %d min %d sec", remaining // 60, remaining % 60)


class SleepyTrainingListener(TrainingListener):
    """Throttle training (reference SleepyTrainingListener) — debugging aid."""

    def __init__(self, timer_iteration_ms: float = 0.0, timer_epoch_ms: float = 0.0):
        self.timer_iteration_ms = timer_iteration_ms
        self.timer_epoch_ms = timer_epoch_ms

    def iteration_done(self, model, iteration, epoch):
        if self.timer_iteration_ms > 0:
            time.sleep(self.timer_iteration_ms / 1000.0)

    def on_epoch_end(self, model):
        if self.timer_epoch_ms > 0:
            time.sleep(self.timer_epoch_ms / 1000.0)


class EvaluativeListener(TrainingListener):
    """Periodically evaluate on a held-out iterator (reference EvaluativeListener)."""

    def __init__(self, iterator, frequency: int = 100, print_report: bool = True):
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.print_report = print_report
        self.last_evaluation = None

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            self.last_evaluation = model.evaluate(self.iterator)
            if self.print_report:
                log.info("Evaluation at iteration %d:\n%s", iteration,
                         self.last_evaluation.stats())


class ComposableIterationListener(TrainingListener):
    def __init__(self, *listeners):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration, epoch):
        for l in self.listeners:
            l.iteration_done(model, iteration, epoch)


class ParamAndGradientIterationListener(TrainingListener):
    """Per-iteration parameter/update statistics to a log or file
    (reference ``optimize/listeners/ParamAndGradientIterationListener.java``).
    Gradient norms come from the jitted train step's fused stats
    (``model._last_grad_stats``); parameter norms are computed host-side."""

    def __init__(self, iterations: int = 1, print_mean: bool = True,
                 print_norms: bool = True, output_file=None,
                 delimiter: str = "\t"):
        self.iterations = max(1, iterations)
        self.print_mean = print_mean
        self.print_norms = print_norms
        self.output_file = output_file
        self.delimiter = delimiter
        self.rows: List[dict] = []

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.iterations != 0:
            return
        import numpy as np
        row = {"iteration": iteration, "score": model.get_score()}
        gstats = getattr(model, "_last_grad_stats", None)
        if gstats is not None:
            row["grad_norm"] = float(gstats["global_norm"])
            for k, v in gstats.get("layer_norms", {}).items():
                row[f"grad_norm_{k}"] = float(v)
        if self.print_norms or self.print_mean:
            for lname, lp in getattr(model, "params", {}).items():
                for pname, arr in (lp or {}).items():
                    a = np.asarray(arr)
                    if self.print_norms:
                        row[f"l2_{lname}.{pname}"] = float(
                            np.linalg.norm(a.reshape(-1)))
                    if self.print_mean:
                        row[f"mean_{lname}.{pname}"] = float(a.mean())
        self.rows.append(row)
        if self.output_file:
            import json as _json
            with open(self.output_file, "a", encoding="utf-8") as f:
                f.write(_json.dumps(row) + "\n")
        else:
            log.info("paramStats %s", row)


class CheckpointListener(TrainingListener):
    """Periodic model checkpoints (reference
    ``optimize/listeners/checkpoint/CheckpointListener.java``): save every
    N iterations and/or every N epochs, keep the last K.

    Re-based on ``faulttolerance.CheckpointManager``: every save is a
    crash-consistent checkpoint DIRECTORY (atomic temp-then-rename commit,
    manifest with per-file checksums) instead of an in-place zip write —
    a kill mid-save can no longer leave a truncated artifact — and
    ``background=True`` rides the manager's double-buffered writer with an
    RNG-neutral snapshot (the old clone()-based snapshot silently split
    the model's RNG stream, making checkpointed runs diverge from
    uncheckpointed ones).  The iteration trigger no longer fires at
    iteration 0 (an empty save before any step).  Saved entries restore
    with ``model_serializer.restore_*`` (which accepts checkpoint dirs) or
    ``CheckpointManager.restore``.
    """

    def __init__(self, directory, save_every_n_iterations: Optional[int] = None,
                 save_every_n_epochs: Optional[int] = None, keep_last: int = 3,
                 background: bool = False):
        from ..faulttolerance.checkpoint import CheckpointManager
        self.directory = directory
        self.save_every_n_iterations = save_every_n_iterations
        self.save_every_n_epochs = save_every_n_epochs
        self.keep_last = keep_last
        self.background = background
        self.manager = CheckpointManager(directory, keep_last=keep_last,
                                         background=background)
        self.saved: List[str] = []

    def _save(self, model, tag: str):
        del tag   # directories are keyed by step now
        self.manager.save(model)
        self._refresh_saved()

    def _refresh_saved(self) -> None:
        self.saved = [p for _, p, _ in self.manager.checkpoints()]

    def wait(self) -> None:
        """Block until any in-flight background checkpoint completes."""
        self.manager.wait()
        self._refresh_saved()

    def iteration_done(self, model, iteration, epoch):
        if self.save_every_n_iterations and iteration > 0 and \
                iteration % self.save_every_n_iterations == 0:
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model):
        if self.save_every_n_epochs and \
                (model.epoch + 1) % self.save_every_n_epochs == 0:
            self._save(model, f"epoch_{model.epoch}")


class ConvolutionalIterationListener(TrainingListener):
    """Render conv-layer activation grids to HTML every N iterations
    (reference ``RemoteConvolutionalIterationListener`` / ``WebReporter``:
    the reference posts rendered activations to the UI; here they land as
    standalone HTML files, or are POSTed to a UIServer's /activations page
    when ``url`` is given, e.g. ``url=f"http://127.0.0.1:{ui.port}/activations"``)."""

    def __init__(self, probe_batch, frequency: int = 50, output_dir=None,
                 layer_index: int = 0, url: Optional[str] = None):
        import os as _os
        self.probe = probe_batch
        self.frequency = max(1, frequency)
        self.output_dir = output_dir
        self.layer_index = layer_index
        self.url = url
        self.rendered: List[str] = []
        if output_dir:
            _os.makedirs(output_dir, exist_ok=True)

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency != 0:
            return
        import numpy as np
        from ..ui.components import activation_grid_svg, render_page
        acts = model.feed_forward(self.probe)
        a = np.asarray(acts[self.layer_index])
        if a.ndim != 4:
            return  # not a conv activation
        svg = activation_grid_svg(a)
        page = (f"<h3>iteration {iteration}, layer {self.layer_index}, "
                f"shape {a.shape}</h3>{svg}")
        self.rendered.append(page)
        if self.output_dir:
            import os as _os
            path = _os.path.join(self.output_dir,
                                 f"activations_{iteration:06d}.html")
            with open(path, "w", encoding="utf-8") as f:
                f.write(f"<!DOCTYPE html><html><body>{page}</body></html>")
        if self.url:
            import json as _json
            import urllib.request
            req = urllib.request.Request(
                self.url, data=_json.dumps(
                    {"iteration": iteration, "svg": svg}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=5).read()
            except OSError:
                log.warning("activation POST to %s failed", self.url)
