"""Legacy full-batch solvers — LBFGS / ConjugateGradient /
LineGradientDescent with backtracking line search.

Reference: ``optimize/Solver.java:43``, ``optimize/solvers/LBFGS.java``,
``ConjugateGradient.java``, ``LineGradientDescent.java``,
``BackTrackLineSearch.java``, ``optimize/stepfunctions/``,
``optimize/terminations/``.

TPU-native re-design: the reference mutates a flat param view from Java
loops; here each solver iteration (direction + Armijo backtracking line
search) is ONE jitted XLA program over the raveled param vector
(`jax.flatten_util.ravel_pytree`).  The line search runs as a
``lax.while_loop`` (no host round-trips per trial step); the L-BFGS
two-loop recursion runs as ``lax.fori_loop`` over fixed circular (S, Y)
memory buffers so the program has static shapes.  Loss is evaluated
deterministically (train=False) — these are deterministic full-batch
methods; stochastic regularization stays with the SGD path.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.flatten_util import ravel_pytree

__all__ = ["Solver", "LineGradientDescent", "ConjugateGradient", "LBFGS",
           "BackTrackLineSearch", "DefaultStepFunction",
           "NegativeDefaultStepFunction", "EpsTermination",
           "Norm2Termination", "ZeroDirectionTermination"]


# --------------------------------------------------------- step functions
class DefaultStepFunction:
    """x_new = x + alpha * direction (reference DefaultStepFunction)."""
    sign = 1.0


class NegativeDefaultStepFunction:
    """x_new = x - alpha * direction (reference NegativeDefaultStepFunction)."""
    sign = -1.0


# ---------------------------------------------------- termination conditions
class EpsTermination:
    """Stop when the score improvement falls below eps * tolerance
    (reference ``optimize/terminations/EpsTermination.java``)."""

    def __init__(self, eps: float = 1e-4, tolerance: float = 1.0):
        self.eps = eps
        self.tolerance = tolerance

    def terminate(self, cost_old: float, cost_new: float, g_norm: float
                  ) -> bool:
        return abs(cost_old - cost_new) < self.eps * self.tolerance


class Norm2Termination:
    """Stop when ||grad||_2 < gradient_norm threshold (reference
    ``Norm2Termination.java``)."""

    def __init__(self, gradient_norm: float = 1e-6):
        self.gradient_norm = gradient_norm

    def terminate(self, cost_old: float, cost_new: float, g_norm: float
                  ) -> bool:
        return g_norm < self.gradient_norm


class ZeroDirectionTermination:
    """Stop when the search direction is numerically zero (reference
    ``ZeroDirection.java``)."""

    def terminate(self, cost_old: float, cost_new: float, g_norm: float
                  ) -> bool:
        return g_norm == 0.0


# --------------------------------------------------------- line search
class BackTrackLineSearch:
    """Armijo backtracking (reference ``BackTrackLineSearch.java``): shrink
    alpha by ``rho`` until f(x + a·d) <= f(x) + c1·a·(g·d), as a
    ``lax.while_loop`` inside the caller's jitted step."""

    def __init__(self, c1: float = 1e-4, rho: float = 0.5,
                 max_iterations: int = 20, min_step: float = 1e-12,
                 initial_step: float = 1.0):
        self.c1 = c1
        self.rho = rho
        self.max_iterations = max_iterations
        self.min_step = min_step
        self.initial_step = initial_step

    def search(self, value_fn: Callable[[jax.Array], jax.Array],
               x: jax.Array, f0: jax.Array, g: jax.Array,
               direction: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Returns (alpha, f_new); traced (jit-safe)."""
        gd = jnp.vdot(g, direction)
        a0 = jnp.asarray(self.initial_step, x.dtype)
        f_try = value_fn(x + a0 * direction)

        def cond(carry):
            alpha, f_new, n = carry
            armijo_fail = ~(f_new <= f0 + self.c1 * alpha * gd)
            finite_fail = ~jnp.isfinite(f_new)
            return ((armijo_fail | finite_fail)
                    & (n < self.max_iterations) & (alpha > self.min_step))

        def body(carry):
            alpha, _, n = carry
            alpha = alpha * self.rho
            return alpha, value_fn(x + alpha * direction), n + 1

        alpha, f_new, _ = lax.while_loop(cond, body, (a0, f_try, 0))
        # if even the smallest step failed, take no step at all
        ok = (f_new <= f0) & jnp.isfinite(f_new)
        return jnp.where(ok, alpha, 0.0), jnp.where(ok, f_new, f0)


# ------------------------------------------------------------- solvers
class _BaseFullBatchOptimizer:
    """Shared driver: build flat loss/grad, run jitted iterations, write
    params back (reference ``BaseOptimizer.gradientAndScore`` :171-187 +
    per-algorithm ``optimize()``)."""

    name = "base"

    def __init__(self, max_iterations: int = 100,
                 terminations: Optional[Sequence[Any]] = None,
                 line_search: Optional[BackTrackLineSearch] = None,
                 step_function: Any = None):
        self.max_iterations = max_iterations
        self.terminations = list(terminations) if terminations is not None \
            else [EpsTermination(1e-10), Norm2Termination(1e-8)]
        self.line_search = line_search or BackTrackLineSearch()
        self.step_function = step_function or DefaultStepFunction()
        self.score_history: List[float] = []

    # subclass contract ----------------------------------------------------
    def init_state(self, flat: jax.Array, g: jax.Array):
        return ()

    def direction(self, g: jax.Array, state) -> Tuple[jax.Array, Any]:
        raise NotImplementedError

    def post_step(self, state, x_old, x_new, g_old, g_new):
        return state

    # driver ---------------------------------------------------------------
    def optimize(self, model, data, labels=None, mask=None,
                 label_mask=None) -> float:
        """Run up to max_iterations full-batch iterations on (x, y).
        Returns the final score and updates ``model.params`` in place."""
        x, y, m, lm = _normalize(model, data, labels, mask, label_mask)
        flat0, unravel = ravel_pytree(model.params)
        state_tree = model.state

        def loss_flat(flat):
            p = unravel(flat)
            loss, _ = model._loss(p, state_tree, x, y, train=False, key=None,
                                  mask=m, label_mask=lm)
            return loss

        value_and_grad = jax.value_and_grad(loss_flat)
        sign = self.step_function.sign

        # legacy full-batch solver: the step dispatches on solver-subclass
        # methods and bakes the (single, full) batch in as a constant, so a
        # per-optimize() trace is the program — there is no steady-state
        # step to share across instances
        @jax.jit  # graftlint: disable=JX028  (cold per-optimize() program — see the JX013 note below)
        def step(flat, f, g, opt_state):  # graftlint: disable=JX013  (cold path, per-call program)
            d, opt_state = self.direction(g, opt_state)
            d = sign * d
            alpha, f_new = self.line_search.search(loss_flat, flat, f, g, d)
            flat_new = flat + alpha * d
            f2, g_new = value_and_grad(flat_new)
            opt_state = self.post_step(opt_state, flat, flat_new, g, g_new)
            return flat_new, f2, g_new, opt_state

        # called exactly once per optimize(): jit-wrapping the fresh
        # closure would XLA-compile a program that never runs again
        f, g = value_and_grad(flat0)
        flat = flat0
        opt_state = self.init_state(flat0, g)
        self.score_history = [float(f)]
        for _ in range(self.max_iterations):
            f_old = float(f)
            flat, f, g, opt_state = step(flat, f, g, opt_state)
            f_cur = float(f)
            self.score_history.append(f_cur)
            g_norm = float(jnp.linalg.norm(g))
            if any(t.terminate(f_old, f_cur, g_norm)
                   for t in self.terminations):
                break
        model.params = unravel(flat)
        model._score = float(f)
        for lst in getattr(model, "listeners", []):
            model.iteration += 1
            lst.iteration_done(model, model.iteration, model.epoch)
        return float(f)


class LineGradientDescent(_BaseFullBatchOptimizer):
    """Steepest descent + line search (reference
    ``optimize/solvers/LineGradientDescent.java``)."""

    name = "line_gradient_descent"

    def direction(self, g, state):
        return -g, state


class ConjugateGradient(_BaseFullBatchOptimizer):
    """Nonlinear Polak-Ribiere(+) conjugate gradient with automatic restart
    (reference ``optimize/solvers/ConjugateGradient.java``)."""

    name = "conjugate_gradient"

    def init_state(self, flat, g):
        return (-g, g)  # (previous direction, previous gradient)

    def direction(self, g, state):
        d_prev, g_prev = state
        beta = jnp.vdot(g, g - g_prev) / (jnp.vdot(g_prev, g_prev) + 1e-30)
        beta = jnp.maximum(beta, 0.0)   # PR+ restart
        d = -g + beta * d_prev
        # restart to steepest descent if d is not a descent direction
        d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
        return d, (d, g)

    def post_step(self, state, x_old, x_new, g_old, g_new):
        d, _ = state
        return (d, g_old)


class LBFGS(_BaseFullBatchOptimizer):
    """Limited-memory BFGS (reference ``optimize/solvers/LBFGS.java``,
    default memory m=10).  The two-loop recursion runs as ``lax.fori_loop``
    over circular [m, n] S/Y buffers so the jitted program has static
    shapes; unfilled slots are masked out."""

    name = "lbfgs"

    def __init__(self, max_iterations: int = 100, memory: int = 10, **kw):
        super().__init__(max_iterations=max_iterations, **kw)
        self.m = memory

    def init_state(self, flat, g):
        n = flat.shape[0]
        m = self.m
        z = jnp.zeros((m, n), flat.dtype)
        return (z, z, jnp.zeros((m,), flat.dtype), jnp.zeros((), jnp.int32))

    def direction(self, g, state):
        S, Y, rho, count = state
        m = self.m
        valid_n = jnp.minimum(count, m)

        def bwd(i, carry):
            q, alphas = carry
            idx = (count - 1 - i) % m
            valid = i < valid_n
            a = jnp.where(valid, rho[idx] * jnp.vdot(S[idx], q), 0.0)
            q = q - a * Y[idx]
            return q, alphas.at[idx].set(a)

        q, alphas = lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), g.dtype)))
        latest = (count - 1) % m
        yy = jnp.vdot(Y[latest], Y[latest])
        gamma = jnp.where(count > 0,
                          jnp.vdot(S[latest], Y[latest]) / (yy + 1e-30), 1.0)
        r = gamma * q

        def fwd(i, r):
            idx = (count - valid_n + i) % m
            valid = i < valid_n
            b = rho[idx] * jnp.vdot(Y[idx], r)
            return r + jnp.where(valid, alphas[idx] - b, 0.0) * S[idx]

        r = lax.fori_loop(0, m, fwd, r)
        d = -r
        # safeguard: fall back to steepest descent on a non-descent direction
        d = jnp.where(jnp.vdot(d, g) < 0, d, -g)
        return d, state

    def post_step(self, state, x_old, x_new, g_old, g_new):
        S, Y, rho, count = state
        s = x_new - x_old
        yv = g_new - g_old
        sy = jnp.vdot(s, yv)
        slot = count % self.m
        ok = sy > 1e-10       # curvature condition; skip the pair otherwise
        S = jnp.where(ok, S.at[slot].set(s), S)
        Y = jnp.where(ok, Y.at[slot].set(yv), Y)
        rho = jnp.where(ok, rho.at[slot].set(1.0 / jnp.maximum(sy, 1e-30)),
                        rho)
        count = count + jnp.where(ok, 1, 0).astype(count.dtype)
        return (S, Y, rho, count)


_ALGOS = {
    "line_gradient_descent": LineGradientDescent,
    "conjugate_gradient": ConjugateGradient,
    "lbfgs": LBFGS,
}


class Solver:
    """Facade mirroring ``optimize/Solver.java:43``: pick the optimizer from
    the algorithm name and drive it.  ``sgd``/``stochastic_gradient_descent``
    delegates to the network's own jitted minibatch path."""

    def __init__(self, model, algorithm: str = "lbfgs",
                 max_iterations: int = 100, **kw):
        self.model = model
        self.algorithm = algorithm.lower()
        if self.algorithm in ("sgd", "stochastic_gradient_descent"):
            self.optimizer = None
        elif self.algorithm in _ALGOS:
            self.optimizer = _ALGOS[self.algorithm](
                max_iterations=max_iterations, **kw)
        else:
            raise ValueError(
                f"unknown optimization algorithm '{algorithm}'; available: "
                f"sgd, {', '.join(sorted(_ALGOS))}")

    def optimize(self, data, labels=None, **kw) -> float:
        if self.optimizer is None:
            self.model.fit(data, labels)
            return self.model.score()
        return self.optimizer.optimize(self.model, data, labels, **kw)


def _normalize(model, data, labels, mask, label_mask):
    if labels is not None:
        x, y, m, lm = data, labels, mask, label_mask
    else:
        x, y, m, lm = model._normalize_batch(data)
        m = mask if mask is not None else m
        lm = label_mask if label_mask is not None else lm
    to = lambda a: None if a is None else jnp.asarray(a)
    return to(x), to(y), to(m), to(lm)
