"""Reusable benchmark configs mirroring BASELINE.md's table (LeNet-MNIST
step time, GravesLSTM char-RNN step time, Word2Vec words/sec).  The driver's
headline ResNet50 metric lives in ``bench.py``; these side metrics are
invoked from there (DL4J_TPU_BENCH_SIDE=1) and from ``tools/``.

All timings are steady-state — the compile-dominated first iteration is
always excluded (warm-up fit before any clock starts; ``_cold_steady_fit``
reports the compile-inclusive number separately as ``cold``) — and close on
a forced device→host fetch — block_until_ready alone can return early
through buffer-proxying transports (BENCH_NOTES round 1).  Training rows
time the device-resident epoch scan (``_scan_step_ms``), the path the
framework actually trains through.  Clocks come from the same monotonic
helpers the tracer/metrics tier uses (``observability.clock``), so bench
rows and span histograms are directly comparable.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

from ..observability.clock import monotonic_s

_ENV_FINGERPRINT: Optional[Dict] = None


def env_fingerprint(refresh: bool = False) -> Dict:
    """Host/runtime provenance block stamped onto every bench JSON row
    (ISSUE 17 satellite): round-over-round comparisons keep mis-blaming
    the framework for environment drift (tunnel latency, host load,
    jaxlib bumps — BENCH_NOTES passim), so every row carries the facts
    needed to rule that out.  Captured ONCE per process (load average is
    the *at-start* reading — a capture's own load must not pollute the
    rows it stamps); ``refresh=True`` re-reads for tests."""
    global _ENV_FINGERPRINT
    if _ENV_FINGERPRINT is not None and not refresh:
        return _ENV_FINGERPRINT
    import sys
    env: Dict = {
        "cpus": os.cpu_count(),
        "python": sys.version.split()[0],
    }
    try:
        env["loadavg_1m"] = round(os.getloadavg()[0], 2)
    except OSError:
        env["loadavg_1m"] = None
    try:
        import jax
        import jaxlib
        env["jax"] = jax.__version__
        env["jaxlib"] = jaxlib.__version__
        env["x64"] = bool(jax.config.jax_enable_x64)
    except Exception:
        env["jax"] = env["jaxlib"] = None
        env["x64"] = None
    # the knobs that change what a row measures: every DL4J_TPU_* override
    # in effect (values are short flags/paths, never secrets)
    env["overrides"] = {k: os.environ[k] for k in sorted(os.environ)
                        if k.startswith("DL4J_TPU_")}
    _ENV_FINGERPRINT = env
    return env


def _scan_step_ms(model, x, y, batch: int, nbatch: int, epochs: int = 2,
                  blocks: int = 3) -> float:
    """Per-step ms through the device-resident epoch scan (fit_on_device:
    one dispatch per epoch).  The per-step-dispatch path measures the
    tunnel as much as the chip — its trivial-dispatch latency drifted
    24 -> 90+ ms between rounds (BENCH_NOTES "tunnel health"), which is
    environment, not framework."""
    model.fit_on_device(x, y, batch_size=batch, epochs=1)   # compile+warm
    steps = nbatch * epochs
    times = []
    for _ in range(blocks):
        t0 = monotonic_s()
        model.fit_on_device(x, y, batch_size=batch, epochs=epochs)
        times.append((monotonic_s() - t0) / steps * 1e3)
    return float(np.median(times))


def lenet_step_time(batch: int = 128, nbatch: int = 50) -> Dict:
    """LeNet-MNIST training step time (zoo ``model/LeNet.java:35``)."""
    import jax.numpy as jnp

    from ..models import LeNet
    model = LeNet().init()
    rng = np.random.default_rng(0)
    n = batch * nbatch
    x = jnp.asarray(rng.standard_normal((n, 28, 28, 1), dtype=np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)])
    ms = _scan_step_ms(model, x, y, batch, nbatch)
    return {"metric": "lenet_mnist_step_ms", "value": round(ms, 3),
            "unit": "ms/step", "batch": batch,
            "examples_per_sec": round(batch / ms * 1e3, 1)}


def char_lstm_step_time(batch: int = 128, timesteps: int = 64,
                        nbatch: int = 30) -> Dict:
    """Char-RNN step time (zoo ``model/TextGenerationLSTM.java:34``; the
    reference's cuDNN LSTM path, ``GravesLSTM.java:46``)."""
    import jax.numpy as jnp

    from ..models import TextGenerationLSTM
    model = TextGenerationLSTM(timesteps=timesteps).init()
    rng = np.random.default_rng(0)
    vocab = 26
    n = batch * nbatch
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (n, timesteps))])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (n, timesteps))])
    ms = _scan_step_ms(model, x, y, batch, nbatch)
    return {"metric": "char_lstm_step_ms", "value": round(ms, 3),
            "unit": "ms/step", "batch": batch, "timesteps": timesteps,
            "tokens_per_sec": round(batch * timesteps / ms * 1e3, 1)}


def _zipf_sentences(vocab: int, n_sent: int, sent_len: int):
    """Zipf(1.3)-distributed synthetic corpus shared by the embedding
    benchmarks, so word2vec and PV rows measure the same token stream."""
    rng = np.random.default_rng(0)
    ids = np.clip(rng.zipf(1.3, size=n_sent * sent_len), 1, vocab) - 1
    toks = ["w%d" % i for i in ids]
    return [" ".join(toks[i * sent_len:(i + 1) * sent_len])
            for i in range(n_sent)]


def _cold_steady_fit(model, total_words: int, runs: int = 3):
    """(cold, steady) words/sec: first fit compiles; steady is the MEDIAN
    of ``runs`` reset-weights re-fits — these benches are dispatch/host
    bound and swing ±40% run-to-run through the tunnel, so a single timed
    fit is not a stable artifact.

    Every clock here closes on a HOST FETCH of the trained table
    (``_sync_tables``), and the queue is drained before each clock starts.
    ``fit()`` itself only enqueues async dispatches; through the axon
    tunnel even ``block_until_ready`` returns early, so timing ``fit()``
    alone measures ENQUEUE rate, not training throughput — the rounds 1-3
    words/sec artifacts did exactly that and over-read by ~3x (BENCH_NOTES
    round 4 "words/sec correction")."""
    def _sync_tables():
        float(np.asarray(model.lookup_table.syn0[0, 0]))

    model.build_vocab()
    t0 = monotonic_s()
    model.fit()
    _sync_tables()
    cold = total_words / (monotonic_s() - t0)
    rates = []
    for _ in range(runs):
        model.lookup_table.reset_weights()
        _sync_tables()                    # drain before starting the clock
        t0 = monotonic_s()
        model.fit()
        _sync_tables()
        rates.append(total_words / (monotonic_s() - t0))
    return cold, float(np.median(rates))


def word2vec_words_per_sec(vocab: int = 5000, n_sent: int = 20000,
                           sent_len: int = 20, epochs: int = 1) -> Dict:
    """Skip-gram NS throughput (parity bar: the reference's native batched
    AggregateSkipGram hot loop, ``SkipGram.java:271-283``)."""
    from ..nlp.word2vec import Word2Vec

    sentences = _zipf_sentences(vocab, n_sent, sent_len)
    total = n_sent * sent_len * epochs
    w2v = Word2Vec(sentences=sentences, layer_size=128, window=5, negative=5,
                   epochs=epochs, seed=1, min_word_frequency=1)
    cold, steady = _cold_steady_fit(w2v, total)
    return {"metric": "word2vec_words_per_sec", "value": round(steady, 1),
            "unit": "words/sec", "cold_words_per_sec": round(cold, 1),
            "vocab": vocab, "corpus_words": total}


def paragraph_vectors_words_per_sec(vocab: int = 5000, n_docs: int = 20000,
                                    doc_len: int = 20, epochs: int = 1,
                                    seq_algo: str = "dbow") -> Dict:
    """Labeled-sequence (doc2vec) throughput — the bulk-path analogue of
    ``word2vec_words_per_sec`` with one unique label per document
    (reference: PV rides the same native aggregates,
    ``SkipGram.java:271-283``)."""
    from ..nlp.paragraph_vectors import ParagraphVectors
    from ..nlp.sentence_iterator import LabelledDocument

    docs = [LabelledDocument(s, ["DOC_%d" % i]) for i, s in
            enumerate(_zipf_sentences(vocab, n_docs, doc_len))]
    total = n_docs * doc_len * epochs
    pv = ParagraphVectors(documents=docs, sequence_algorithm=seq_algo,
                          layer_size=128, window=5, negative=5,
                          epochs=epochs, seed=1, min_word_frequency=1)
    cold, steady = _cold_steady_fit(pv, total)
    return {"metric": f"paragraph_vectors_{seq_algo}_words_per_sec",
            "value": round(steady, 1), "unit": "words/sec",
            "cold_words_per_sec": round(cold, 1), "vocab": vocab,
            "n_docs": n_docs, "corpus_words": total}


def transformer_lm_step_time(batch: int = 16, seq: int = 512,
                             embed: int = 512, n_layers: int = 8,
                             n_heads: int = 8, vocab: int = 8192,
                             impls=("auto", "flash", "reference"),
                             nbatch: int = 5, epochs: int = 2,
                             blocks: int = 3) -> List[Dict]:
    """TransformerLM train throughput + achieved TFLOP/s per attention impl
    (VERDICT r2 item 6 / r3 item 1: the beyond-reference tier measured like
    the parity tier).  Flops use the causal PaLM-style estimate
    6·T·(12·L·E² + E·V) matmul + 6·L·B·S²·E attention (fwd+bwd).

    Round-4 campaign form (BENCH_NOTES "transformer campaign"): sparse
    integer labels (the LM-natural target — one-hot reads an extra ~268 MB
    HBM/step at V=8192) and the device-resident epoch scan
    (``fit_on_device``, one dispatch per epoch) so the row measures the
    chip, not the tunnel's ~24-90 ms per-dispatch latency."""
    import jax.numpy as jnp

    from ..models import TransformerLM

    from ..observability.profiler import resolve_card_flops

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch * nbatch, seq + 1))
    x = jnp.asarray(ids[:, :-1])
    y = jnp.asarray(ids[:, 1:])
    tokens = batch * seq
    # analytic fallback only: when a committed graftaudit card exists for
    # the program, its COUNTED flops are authoritative (same source the
    # profiler's training_mfu uses) and the estimate below is unused
    analytic_flops = (
        6 * tokens * (12 * n_layers * embed * embed + embed * vocab)
        + 6 * n_layers * batch * seq * seq * embed)
    out = []
    for impl in impls:
        program = f"transformer_lm[{impl},s={seq}]"
        card_flops = resolve_card_flops(program)
        flops = card_flops if card_flops is not None else analytic_flops
        model = TransformerLM(vocab_size=vocab, seq_len=seq, embed=embed,
                              n_layers=n_layers, n_heads=n_heads,
                              attn_impl=impl, sparse_labels=True,
                              compute_dtype="bfloat16").init()
        ms = _scan_step_ms(model, x, y, batch, nbatch, epochs=epochs,
                           blocks=blocks)
        out.append({
            "metric": f"transformer_lm_step_ms[{impl},s={seq}]",
            "value": round(ms, 3), "unit": "ms/step",
            "batch": batch, "seq": seq, "embed": embed,
            "n_layers": n_layers, "sparse_labels": True,
            "tokens_per_sec": round(tokens / ms * 1e3, 1),
            "achieved_tflops": round(flops / ms / 1e9, 2),
            "flops_source": "card" if card_flops is not None else "analytic",
        })
    return out


def step_time_ms(seqs=(128, 512, 2048), dtypes=("float32", "bfloat16"),
                 batch: int = 16, big_mult: int = 4, embed: int = 256,
                 n_layers: int = 4, n_heads: int = 8, vocab: int = 2048,
                 steps: int = 20, adapt_cap: int = 2000,
                 compile_cost_s=None, step_cost_s=None) -> List[Dict]:
    """Per-step train time through the PER-STEP fit path under a
    mixed-size workload, auto shape policy vs off (ISSUE 6 acceptance:
    the s=128 bucketing regression must stay within 10% of the
    off-policy reference).

    Each row reproduces the regression scenario directly: one batch at
    ``batch x big_mult`` compiles a large bucket, then the workload
    settles on ``batch``-sized steps.  The pre-cost-model auto policy
    padded EVERY small step onto the big bucket (paying ``big_mult``x
    the flops forever); the ski-rental cost model pads only until the
    cumulative waste rivals one compile, then gives the recurring size
    its own executable — ``adapt_steps`` reports how many padded steps
    that took.  The timed window starts after adaptation, so ``value``
    is the steady per-step cost a long-running job pays.  The f32/bf16
    sweep makes the PrecisionPolicy step-time win visible on the same
    trajectory (``DL4J_TPU_BENCH_DTYPE``-independent: both always run).
    """
    import jax.numpy as jnp

    from ..data.shapes import ShapePolicy
    from ..models import TransformerLM

    rng = np.random.default_rng(0)
    out = []
    for seq in seqs:
        ids_big = rng.integers(0, vocab, (batch * big_mult, seq + 1))
        ids = rng.integers(0, vocab, (batch, seq + 1))
        xb, yb = jnp.asarray(ids_big[:, :-1]), jnp.asarray(ids_big[:, 1:])
        x, y = jnp.asarray(ids[:, :-1]), jnp.asarray(ids[:, 1:])
        for dt in dtypes:
            per_policy = {}
            for mode in ("auto", "off"):
                model = TransformerLM(
                    vocab_size=vocab, seq_len=seq, embed=embed,
                    n_layers=n_layers, n_heads=n_heads, sparse_labels=True,
                    compute_dtype=None if dt == "float32" else dt).init()
                model.shape_policy = ShapePolicy(
                    mode, compile_cost_s=compile_cost_s,
                    step_cost_s=step_cost_s)
                model.fit_batch((xb, yb))   # the large compiled bucket
                # adaptation: drive small steps (through fit, so the
                # steady step-seconds histogram feeds the cost model)
                # until the policy stops padding onto the big bucket
                adapted = mode == "off"
                n_adapt = 0
                while not adapted and n_adapt < adapt_cap:
                    chunk = min(25, adapt_cap - n_adapt)
                    model.fit(iter([(x, y, None, None)] * chunk))
                    n_adapt += chunk
                    seen = {tuple(e[:2]): e[2] for e in
                            model.shape_policy.snapshot()["seen"]}
                    adapted = batch in (seen.get(("train", "batch")) or [])
                model.fit_batch((x, y))     # warm the steady executable
                t0 = monotonic_s()
                model.fit(iter([(x, y, None, None)] * steps))
                # _fit_one syncs the loss per step: the clock closes on
                # device completion, not enqueue
                ms = (monotonic_s() - t0) / steps * 1e3
                per_policy[mode] = (ms, n_adapt)
            auto_ms, n_adapt = per_policy["auto"]
            off_ms, _ = per_policy["off"]
            tag = "f32" if dt == "float32" else dt
            out.append({
                "metric": f"step_time_ms[s={seq},{tag}]",
                "value": round(auto_ms, 3), "unit": "ms/step (auto policy)",
                "off_policy_ms": round(off_ms, 3),
                "vs_off": round(auto_ms / off_ms, 3) if off_ms else None,
                "adapt_steps": n_adapt,
                "batch": batch, "seq": seq, "dtype": dt,
                "big_bucket": batch * big_mult,
                "tokens_per_sec": round(batch * seq / auto_ms * 1e3, 1),
            })
    return out


class _PipelineBenchSource:
    """Picklable source factory for the input-pipeline benchmark: every ETL
    worker regenerates the same synthetic image set (cheaper and more
    deterministic than shipping arrays through pickle) and batches it."""

    def __init__(self, n: int, image: int = 32, channels: int = 3,
                 batch: int = 64, n_classes: int = 10, seed: int = 0):
        self.n, self.image, self.channels = n, image, channels
        self.batch, self.n_classes, self.seed = batch, n_classes, seed

    def __call__(self):
        from ..data.dataset import INDArrayDataSetIterator
        rng = np.random.default_rng(self.seed)
        x = rng.standard_normal(
            (self.n, self.image, self.image, self.channels),
            dtype=np.float32)
        y = np.zeros((self.n, self.n_classes), np.float32)
        y[np.arange(self.n), rng.integers(0, self.n_classes, self.n)] = 1.0
        return INDArrayDataSetIterator(x, y, self.batch)


class _PipelineBenchTransform:
    """Deliberately CPU-heavy augmentation (CIFAR-style crop/flip/cutout
    plus repeated per-image standardization) so host ETL, not the tiny
    dense step, is the bound — the workload the overlapped pipeline exists
    for.  Module-level (picklable) so ETL worker processes can receive it;
    exposes both the ``ImageTransform.transform`` protocol (for
    ``TransformingDataSetIterator``) and plain ``__call__``."""

    def __init__(self, repeats: int = 40):
        from ..data.transforms import (ComposeTransform, CutoutTransform,
                                       RandomCropTransform,
                                       RandomFlipTransform)
        self.repeats = repeats
        self.aug = ComposeTransform([RandomCropTransform(4),
                                     RandomFlipTransform(),
                                     CutoutTransform(8)])

    def transform(self, feats, rng):
        out = self.aug.transform(feats, rng)
        for _ in range(self.repeats):
            # 5-point smoothing + per-image standardization: ~5 ms per
            # repeat at (64, 64, 64, 3) — repeats=40 puts batch ETL around
            # 200 ms, far above the tiny dense step, so the pipeline (not
            # the chip) is what this benchmark exercises
            out = (out + np.roll(out, 1, axis=1) + np.roll(out, -1, axis=1)
                   + np.roll(out, 1, axis=2)
                   + np.roll(out, -1, axis=2)) * 0.2
            mu = out.mean(axis=(1, 2, 3), keepdims=True)
            sd = out.std(axis=(1, 2, 3), keepdims=True) + 1e-6
            out = (out - mu) / sd
        return out.astype(np.float32)

    __call__ = transform


def input_pipeline_examples_per_sec(batch: int = 64, image: int = 64,
                                    channels: int = 3, nbatch: int = 120,
                                    workers: int = 0, depth: int = 3,
                                    runs: int = 2) -> Dict:
    """Input-bound training throughput: single-thread async prefetch
    (``AsyncDataSetIterator``, the pre-pipeline path) vs the overlapped
    pipeline (``MultiprocessETLIterator`` workers + ``DevicePrefetchIterator``
    H2D-ahead).  The model is a deliberately tiny dense net so ETL >= step;
    ``overlap_speedup`` is the headline ratio (ISSUE 3 acceptance: >= 1.5x
    on hardware with spare host cores — worker *spawn* time is inside the
    clock, as a real user would pay it each epoch).  ``workers=0`` picks
    ``min(4, cpu_count - 1)``."""
    import os as _os

    from ..data.dataset import AsyncDataSetIterator
    from ..data.pipeline import build_input_pipeline
    from ..data.transforms import TransformingDataSetIterator
    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork

    if workers <= 0:
        workers = max(1, min(4, (_os.cpu_count() or 2) - 1))
    n = batch * nbatch
    source = _PipelineBenchSource(n, image, channels, batch)
    tf = _PipelineBenchTransform()

    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(Adam(learning_rate=1e-3)).list()
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(image, image, channels))
            .build())
    model = MultiLayerNetwork(conf).init()

    # compile warm-up + raw per-batch costs (ETL vs step) for the
    # input-boundedness sanity flag
    probe = next(iter(source()))
    feats = tf.transform(probe.features, np.random.default_rng(0))
    model.fit((feats, probe.labels))
    t0 = monotonic_s()
    model.fit((feats, probe.labels))
    step_ms = (monotonic_s() - t0) * 1e3
    t0 = monotonic_s()
    tf.transform(probe.features, np.random.default_rng(1))
    etl_ms = (monotonic_s() - t0) * 1e3

    def timed_fit(iterator) -> float:
        t0 = monotonic_s()
        model.fit(iterator)
        model.get_score()          # _fit_one already synced the final loss
        return n / (monotonic_s() - t0)

    async_rates, pipe_rates = [], []
    for _ in range(runs):
        async_rates.append(timed_fit(AsyncDataSetIterator(
            TransformingDataSetIterator(source(), tf, seed=1),
            queue_size=depth)))
        pipe_rates.append(timed_fit(build_input_pipeline(
            source, tf, num_workers=workers, depth=depth, seed=1)))
    async_rate = float(np.median(async_rates))
    pipe_rate = float(np.median(pipe_rates))
    return {"metric": "input_pipeline_examples_per_sec",
            "value": round(pipe_rate, 1), "unit": "examples/sec",
            "async_examples_per_sec": round(async_rate, 1),
            "overlap_speedup": round(pipe_rate / async_rate, 2),
            "batch": batch, "nbatch": nbatch, "workers": workers,
            "depth": depth, "host_cpus": _os.cpu_count(),
            "etl_ms_per_batch": round(etl_ms, 1),
            "step_ms_per_batch": round(step_ms, 1),
            "input_bound": bool(etl_ms > step_ms)}


def serving_latency(concurrency: int = 16,
                    n_requests: int = 400, model=None) -> List[Dict]:
    """Serving under load (VERDICT r3 item 8; mirror
    ``ParallelInference.java:32`` + ``InferenceMode.BATCHED``): p50/p99
    single-request latency and delivered throughput at a stated
    concurrency, batched (dynamic coalescing) vs unbatched (INPLACE
    synchronous).  Requests are singleton feature rows fired from
    ``concurrency`` client threads against one LeNet-sized model."""
    from ..models import LeNet
    from ..parallel.inference import InferenceMode, ParallelInference

    if model is None:
        model = LeNet().init()
    rng = np.random.default_rng(0)
    probe = rng.standard_normal((784,)).astype(np.float32)  # LeNet takes
    out = []                 # flat MNIST rows (feed-forward input + reshape)
    for mode in (InferenceMode.BATCHED, InferenceMode.INPLACE):
        pi = ParallelInference(model, inference_mode=mode,
                               max_batch_size=32)
        # warm every coalescing bucket so no compile lands in a timed
        # request (XLA compiles per padded shape)
        for b in (1, 2, 4, 8, 16, 32):
            pi.output(np.stack([probe] * b))
        lats, wall, _ = _closed_loop(
            lambda: np.asarray(pi.output(probe)),  # host-synced result
            concurrency, n_requests)
        pi.shutdown()
        lats_ms = np.asarray(lats) * 1e3
        out.append({
            "metric": f"serving_latency_ms[{mode.lower()},c={concurrency}]",
            "value": round(float(np.percentile(lats_ms, 50)), 2),
            "unit": "ms p50", "concurrency": concurrency,
            "p99_ms": round(float(np.percentile(lats_ms, 99)), 2),
            "requests": len(lats),
            "requests_per_sec": round(len(lats) / wall, 1),
        })
    return out


def _closed_loop(call, concurrency: int, n_requests: int):
    """Closed-loop load: ``concurrency`` client threads each issue
    ``n_requests // concurrency`` back-to-back requests.  Returns
    (sorted latencies in seconds, wall seconds, error count)."""
    import threading

    lats: List[float] = []
    errors = [0]
    lock = threading.Lock()
    per_worker = max(1, n_requests // concurrency)

    def client():
        mine = []
        errs = 0
        for _ in range(per_worker):
            t0 = monotonic_s()
            try:
                call()
            except Exception:
                errs += 1
                continue
            mine.append(monotonic_s() - t0)
        with lock:
            lats.extend(mine)
            errors[0] += errs

    threads = [threading.Thread(target=client)
               for _ in range(concurrency)]
    t0 = monotonic_s()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = monotonic_s() - t0
    return sorted(lats), wall, errors[0]


def serve_latency_ms(concurrencies=(1, 16, 64), n_requests: int = 384,
                     model=None, max_batch: int = 32,
                     queue_limit: int = 1024) -> List[Dict]:
    """Serving-engine bench (ISSUE 8): p50/p99 single-request latency and
    delivered req/s from closed-loop clients, the continuous-batching
    :class:`serving.ServingEngine` vs the per-request baseline (one
    synchronous forward per request — the pre-engine serving path), at
    each stated concurrency.  Engine rows carry ``vs_per_request``
    (req/s ratio — the acceptance gate at c=16) and
    ``steady_recompiles`` (XLA traces after warmup, which the warmed
    bucket ladder must keep at 0)."""
    from ..models import LeNet
    from ..parallel.inference import InferenceMode, ParallelInference
    from ..serving.engine import ServingEngine

    if model is None:
        model = LeNet().init()
    try:
        feat = tuple(model.conf.input_type.shape(-1)[1:])
    except Exception:
        feat = (784,)
    probe = np.random.default_rng(0).standard_normal(feat).astype(np.float32)

    def rows_for(impl: str, call, concurrency: int, extra: Dict) -> Dict:
        lats, wall, errs = _closed_loop(call, concurrency, n_requests)
        lats_ms = np.asarray(lats) * 1e3
        return {
            "metric": f"serve_latency_ms[{impl},c={concurrency}]",
            "value": round(float(np.percentile(lats_ms, 50)), 2),
            "unit": "ms p50", "impl": impl, "concurrency": concurrency,
            "p99_ms": round(float(np.percentile(lats_ms, 99)), 2),
            "requests": len(lats), "errors": errs,
            "requests_per_sec": round(len(lats) / wall, 1),
            **extra,
        }

    out: List[Dict] = []
    baseline_rps: Dict[int, float] = {}
    # per-request baseline: every request pays its own synchronous forward
    pi = ParallelInference(model, InferenceMode.INPLACE)
    pi.output(probe)                       # compile the singleton shape
    for c in concurrencies:
        row = rows_for("per_request", lambda: pi.output(probe), c, {})
        baseline_rps[c] = row["requests_per_sec"]
        out.append(row)
    pi.shutdown()

    engine = ServingEngine(model, max_batch_size=max_batch,
                           queue_limit=queue_limit)
    try:
        engine.warmup()                    # compile the bucket ladder
        for c in concurrencies:
            row = rows_for("engine", lambda: engine.predict(probe), c, {})
            if baseline_rps.get(c):
                row["vs_per_request"] = round(
                    row["requests_per_sec"] / baseline_rps[c], 2)
            # read AFTER the loop: these count the timed window's work
            row["steady_recompiles"] = engine.steady_recompiles
            row["batches_dispatched"] = engine.batches_dispatched
            out.append(row)
    finally:
        engine.shutdown()
    return out


def decode_tokens_per_sec(model=None, max_slots: int = 8,
                          max_seq: int = 128,
                          mixes=(("decode_heavy", 12, 8, 48),
                                 ("prefill_heavy", 12, 96, 8)),
                          ) -> List[Dict]:
    """Generation-engine bench (ISSUE 11): delivered tokens/sec from the
    slot-batched continuous-batching :class:`generation.GenerationEngine`
    vs the naive pre-subsystem baseline — one FULL re-forward per token,
    one request at a time — on a prefill-heavy mix (long prompts, short
    continuations: the prefill ladder dominates) and a decode-heavy mix
    (short prompts, long continuations: the fixed-shape decode step
    dominates).  Engine rows carry ``vs_naive`` (the acceptance gate:
    batching `max_slots` sequences through ONE decode program per step
    must beat re-running the stack per token) and ``steady_recompiles``,
    which the warmed two-program set must keep at 0.

    The naive baseline runs at a FIXED padded shape (history padded to
    ``max_seq``) so it pays one compile, not one per history length —
    the comparison is engine-vs-dispatch-pattern, not engine-vs-
    recompile-storm.  Greedy sampling on both sides keeps the token
    streams comparable (the bench asserts throughput, the test suite
    asserts the streams match)."""
    from ..generation import GenerationConfig, GenerationEngine
    from ..models import TransformerLM

    if model is None:
        model = TransformerLM(vocab_size=64, seq_len=max_seq, embed=64,
                              n_layers=2, n_heads=4).init()
    rng = np.random.default_rng(0)
    vocab = model.conf.layers[-1].n_out

    def naive_tokens(prompt, n) -> list:
        """Per-token full re-forward at one padded shape."""
        hist = list(prompt)
        out = []
        for _ in range(n):
            padded = np.zeros((1, max_seq), np.int32)
            padded[0, :len(hist)] = hist
            probs = np.asarray(model.output(padded))
            tok = int(probs[0, len(hist) - 1].argmax())
            out.append(tok)
            hist.append(tok)
        return out

    rows: List[Dict] = []
    engine = GenerationEngine.for_model(
        model, GenerationConfig(max_slots=max_slots, max_seq=max_seq,
                                queue_limit=4096))
    try:
        engine.warmup()
        cache_bytes = engine.ring.cache_bytes
        slots_per_gb = round(max_slots / (cache_bytes / 2**30), 1)
        naive_tokens([1], 1)                 # compile the naive shape too
        for mix, n_requests, prompt_len, new_tokens in mixes:
            prompts = [rng.integers(0, vocab, prompt_len).tolist()
                       for _ in range(n_requests)]
            t0 = monotonic_s()
            total_naive = sum(len(naive_tokens(p, new_tokens))
                              for p in prompts)
            naive_wall = monotonic_s() - t0
            t0 = monotonic_s()
            reqs = [engine.submit(p, max_new_tokens=new_tokens)
                    for p in prompts]
            results = [r.future.result(timeout=600) for r in reqs]
            engine_wall = monotonic_s() - t0
            total = sum(len(r.tokens) for r in results)
            tps = total / engine_wall
            naive_tps = total_naive / naive_wall
            rows.append({
                "metric": f"decode_tokens_per_sec[{mix}]",
                "value": round(tps, 1),
                "unit": "tokens/sec", "mix": mix,
                "requests": n_requests, "prompt_len": prompt_len,
                "new_tokens": new_tokens, "max_slots": max_slots,
                "tokens": total,
                "naive_tokens_per_sec": round(naive_tps, 1),
                "vs_naive": round(tps / naive_tps, 2) if naive_tps else None,
                "steady_recompiles": engine.steady_recompiles,
                "decode_steps": engine.decode_steps,
                "cache_bytes": cache_bytes,
                "slots_per_gb": slots_per_gb,
            })
    finally:
        engine.shutdown()
    rows.append(_slot_capacity_row(model, max_slots, max_seq))
    return rows


def _dense_cache_bytes(model, max_slots: int, max_seq: int) -> int:
    """Byte cost of the REMOVED dense slot ring at this geometry — the
    baseline the capacity row is measured against, computed analytically
    (``jax.eval_shape`` of exactly the per-layer carries the ring used
    to allocate: K/V ``[max_slots, heads, max_seq, head_dim]`` plus
    validity/position rows), so the comparison survives the ring's
    deletion without a dense engine to measure."""
    import jax
    import jax.numpy as jnp

    from ..generation.programs import _fresh_carry, carried_layers

    total = 0
    for lc in carried_layers(model.conf).values():
        shapes = jax.eval_shape(
            lambda lc=lc: _fresh_carry(lc, max_slots, max_seq))
        if isinstance(shapes, dict) and "pos" in shapes and \
                getattr(shapes["pos"], "ndim", 0) == 0:
            # the ring vectorized scalar stream positions per slot
            shapes = dict(shapes, pos=jax.ShapeDtypeStruct(
                (max_slots,), jnp.int32))
        total += sum(int(np.prod(s.shape)) * s.dtype.itemsize
                     for s in jax.tree_util.tree_leaves(shapes))
    return total


def _slot_capacity_row(model, max_slots: int, max_seq: int) -> Dict:
    """The paged-KV memory claim as a pinned number: at the dense ring's
    cache-byte budget (computed analytically — the ring itself is gone),
    how many slots can decode CONCURRENTLY on a short-actual-length
    workload (each sequence fits ONE block — at the bench default,
    prompt 8 + 8 generated = 16 tokens vs a dense slot priced at
    ``max_seq=128``)?  The paged pool is sized to the dense-equivalent
    block count (trash block included), the paged engine to 4x the
    slots, and the row verifies the whole fleet was simultaneously
    resident (``peak_active``) with zero steady recompiles."""
    from ..generation import GenerationConfig, GenerationEngine

    rng = np.random.default_rng(7)
    vocab = model.conf.layers[-1].n_out
    # one block per sequence, 8 blocks per dense-slot-equivalent: the
    # short-actual-length geometry scales with max_seq so toy configs
    # exercise the same row contract the real bench scale pins
    block = max(2, max_seq // 8)
    dense_bytes = _dense_cache_bytes(model, max_slots, max_seq)
    paged_slots = 4 * max_slots
    # the dense ring's K/V byte budget expressed in blocks (trash block
    # INCLUDED — the pool must not exceed the dense bytes it replaces)
    n_blocks = max_slots * (max_seq // block)
    paged = GenerationEngine.for_model(
        model, GenerationConfig(max_slots=paged_slots, max_seq=max_seq,
                                block_size=block,
                                n_blocks=n_blocks, queue_limit=4096))
    try:
        paged.warmup()
        paged_bytes = paged.ring.cache_bytes
        # queue the whole fleet before a tick can admit any of it: ticks
        # serialize on the engine step lock, so holding it across the
        # submits makes admission one batch and the simultaneous-
        # residency claim deterministic (short requests would otherwise
        # finish before the submit loop does)
        with paged._step_lock:
            reqs = [paged.submit(
                        rng.integers(0, vocab, block // 2).tolist(),
                        max_new_tokens=block - block // 2)
                    for _ in range(paged_slots)]
        results = [r.future.result(timeout=600) for r in reqs]
        assert all(r.finish == "length" for r in results)
        peak = paged.ring.peak_active
        return {
            "metric": "decode_tokens_per_sec[slot_capacity]",
            "value": round(paged_slots / max_slots, 2),
            "unit": "x_dense_slots",
            "dense_slots": max_slots, "paged_slots": paged_slots,
            "peak_active": peak, "block_size": block,
            "n_blocks": n_blocks, "max_seq": max_seq,
            "cache_bytes": paged_bytes, "dense_cache_bytes": dense_bytes,
            "bytes_vs_dense": round(paged_bytes / dense_bytes, 3),
            "slots_per_gb": round(paged_slots / (paged_bytes / 2**30), 1),
            "dense_slots_per_gb": round(
                max_slots / (dense_bytes / 2**30), 1),
            "steady_recompiles": paged.steady_recompiles,
        }
    finally:
        paged.shutdown()


def ttft_ms(model=None, max_slots: int = 4, max_seq: int = 128,
            n_requests: int = 16, prefix_len: int = 64,
            suffix_len: int = 8, new_tokens: int = 4) -> List[Dict]:
    """Time-to-first-token under a shared-prefix-heavy admission mix
    (ISSUE 19): every request carries the same ``prefix_len``-token
    system/few-shot header plus a unique ``suffix_len`` tail — the
    workload prefix sharing exists for.  Two arms, identical requests:

    - ``paged_cold``: paged cache, sharing disabled — every admission
      prefills its full prompt;
    - ``paged_shared``: paged cache with the content-hash prefix
      registry — after the first request registers the header blocks,
      every later admission adopts them and prefills only its suffix.

    Requests run SEQUENTIALLY (TTFT here isolates the prefill path, not
    queueing).  Rows carry p50/p99 TTFT, prefill tokens saved, the
    shared-vs-cold ratio on the shared arm (the >= 2x acceptance gate),
    and the steady-recompile counter (the suffix ladder must absorb
    every suffix shape at warmup)."""
    from ..generation import GenerationConfig, GenerationEngine
    from ..models import TransformerLM

    if model is None:
        model = TransformerLM(vocab_size=64, seq_len=max_seq, embed=64,
                              n_layers=2, n_heads=4).init()
    rng = np.random.default_rng(3)
    vocab = model.conf.layers[-1].n_out
    prefix = rng.integers(0, vocab, prefix_len).tolist()
    prompts = [prefix + rng.integers(0, vocab, suffix_len).tolist()
               for _ in range(n_requests)]

    arms = (("paged_cold", dict(prefix_sharing=False)),
            ("paged_shared", dict(prefix_sharing=True)))
    rows: List[Dict] = []
    cold_p50 = None
    for arm, cfg_kw in arms:
        engine = GenerationEngine.for_model(
            model, GenerationConfig(max_slots=max_slots, max_seq=max_seq,
                                    **cfg_kw))
        try:
            engine.warmup()
            ttfts = []
            for p in prompts:
                req = engine.submit(p, max_new_tokens=new_tokens)
                req.future.result(timeout=600)
                ttfts.append((req.t_first - req.t_submit) * 1e3)
            stats = engine.status().get("kv") or {}
            p50 = float(np.percentile(ttfts, 50))
            if arm == "paged_cold":
                cold_p50 = p50
            row = {
                "metric": f"ttft_ms[{arm}]",
                "value": round(p50, 3), "unit": "ms", "arm": arm,
                "p50_ms": round(p50, 3),
                "p99_ms": round(float(np.percentile(ttfts, 99)), 3),
                "requests": n_requests, "prefix_len": prefix_len,
                "suffix_len": suffix_len, "new_tokens": new_tokens,
                "prefill_tokens_saved": stats.get("prefix_tokens_saved",
                                                  0),
                "prefix_hits": stats.get("prefix_hits", 0),
                "steady_recompiles": engine.steady_recompiles,
            }
            if arm == "paged_shared" and cold_p50:
                row["vs_cold"] = round(cold_p50 / p50, 2)
            rows.append(row)
        finally:
            engine.shutdown()
    return rows


# Calibration (BENCH_NOTES "tunnel health"): round-2 measured ~24 ms
# trivial-dispatch; this round measured ~90 ms on an otherwise-working
# tunnel, and the round-3 degraded window showed 3-5x metric inflation.
# Thresholds are deliberately loose — they flag "sick window", not drift.
PROBE_ROUNDTRIP_HEALTHY_MS = 200.0
PROBE_SPREAD_HEALTHY = 0.6
# v5e bf16 peak ≈ 197 TF/s; the 2048^3 scan chain delivers ~80-120 TF/s in
# a healthy window (tanh + non-pipelined chain).  Below this the chip is
# contended/degraded and throughput rows are not comparable across windows.
# Chip-generation-specific — override on smaller TPUs (a v2/v3 can never
# reach the v5e floor and would read permanently unhealthy).
PROBE_COMPUTE_HEALTHY_TFLOPS = float(
    os.environ.get("DL4J_TPU_PROBE_HEALTHY_TFLOPS", "40"))


def tunnel_probe(n: int = 5) -> Dict:
    """Tunnel-health probe recorded beside every BENCH_SIDE row (VERDICT r3
    item 2): (a) trivial-dispatch roundtrip latency — a tiny jitted op plus
    a 512-byte host fetch; (b) a fixed 20-matmul device block timed ``n``
    times — its spread separates device/tunnel instability from honest
    load.  Rows carrying a probe let the next round distinguish a real
    regression from a degraded capture window without re-reading prose
    (the ``PerformanceListener.java:19`` role: measurements you can trust
    round-over-round)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)  # graftlint: disable=JX028  (microbenchmark probe; measures raw dispatch, deliberately bypasses the cache)
    x = jnp.zeros((1, 128), jnp.float32)
    float(np.asarray(f(x))[0, 0])                    # compile + settle
    lats = []
    for _ in range(n):
        t0 = monotonic_s()
        float(np.asarray(f(x))[0, 0])
        lats.append(monotonic_s() - t0)
    g = jax.jit(lambda a: a @ a)  # graftlint: disable=JX028  (microbenchmark probe; measures raw dispatch, deliberately bypasses the cache)
    a = jnp.eye(1024, dtype=jnp.bfloat16)            # stable under chaining
    float(np.asarray(g(a)[0, 0]))                    # compile + settle
    blocks = []
    for _ in range(n):
        t0 = monotonic_s()
        r = a
        for _ in range(20):
            r = g(r)
        float(np.asarray(r[0, 0]))                   # sync the whole chain
        blocks.append(monotonic_s() - t0)
    med = float(np.median(blocks))

    # (c) device-COMPUTE throughput: one big dispatch (1000 scanned 2048^3
    # bf16 matmuls ≈ 17.2 TFLOP), fetch-closed.  The roundtrip/block probes
    # above are dispatch-latency-bound and stay "healthy" through windows
    # where the chip itself delivers 3x less (observed this round: same
    # code, 703k -> 233k words/s while roundtrip read 110 ms both times) —
    # only a completion-timed compute block exposes that.  TPU-only: on
    # CPU/interpret backends the 17.2-TFLOP chain takes minutes and the
    # v5e-calibrated floor would read permanently unhealthy, so the leg is
    # skipped and `healthy` gates on the dispatch probes alone.
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        h = jax.jit(lambda a: jax.lax.scan(  # graftlint: disable=JX028  (microbenchmark probe; measures raw dispatch, deliberately bypasses the cache)
            lambda c, _: (jnp.tanh(c @ c), None), a, None, length=1000)[0])
        c = (jnp.eye(2048, dtype=jnp.bfloat16) * 0.99
             + jnp.full((2048, 2048), 1e-3, jnp.bfloat16))
        float(np.asarray(h(c)[0, 0]))                # compile + settle
        t0 = monotonic_s()
        float(np.asarray(h(c)[0, 0]))
        compute_s = monotonic_s() - t0
        flops = 1000 * 2 * 2048 ** 3
        compute_tflops = round(flops / compute_s / 1e12, 1)
    else:
        compute_tflops = None

    probe = {
        "roundtrip_ms": round(float(np.median(lats)) * 1e3, 1),
        "block_ms": round(med * 1e3, 1),
        "block_spread": round((max(blocks) - min(blocks)) / med, 3),
        "compute_tflops": compute_tflops,
    }
    probe["healthy"] = bool(
        probe["roundtrip_ms"] < PROBE_ROUNDTRIP_HEALTHY_MS
        and probe["block_spread"] < PROBE_SPREAD_HEALTHY
        and (compute_tflops is None
             or compute_tflops > PROBE_COMPUTE_HEALTHY_TFLOPS))
    return probe


def compile_reuse(hidden: int = 64, features: int = 16, classes: int = 5,
                  batch: int = 32) -> Dict:
    """Compilation-reuse benchmark (ISSUE 4): cold first-step compile vs a
    ``clone()``'s first step through the shared trace cache, plus the
    compile count of a ragged-last-batch ``fit`` under shape bucketing.

    The headline ``value`` is the clone-reuse speedup (cold first-step
    wall time / clone first-step wall time): >> 1 means replica K's
    time-to-first-step is dispatch, not an XLA compile.  ``_fit_one``
    host-syncs the loss, so both step timings close on device completion.
    """
    import jax.numpy as jnp

    from .. import (InputType, MultiLayerNetwork, NeuralNetConfiguration)
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..observability.registry import default_registry

    def build():
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(learning_rate=0.01)).list()
                .layer(DenseLayer(n_out=hidden, activation="relu"))
                .layer(OutputLayer(n_out=classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(features))
                .build())
        return MultiLayerNetwork(conf).init()

    reg = default_registry()

    def train_step_compiles() -> float:
        c = reg.get("training_compile_total")
        return 0.0 if c is None else c.labels("train_step").value

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, features),
                                        dtype=np.float32))
    y = jnp.asarray(np.eye(classes, dtype=np.float32)[
        rng.integers(0, classes, batch)])

    model = build()
    t0 = monotonic_s()
    model.fit_batch((x, y))                     # cold: trace + compile
    cold_s = monotonic_s() - t0

    replica = model.clone()
    before = train_step_compiles()
    t0 = monotonic_s()
    replica.fit_batch((x, y))                   # shared-cache reuse
    clone_s = monotonic_s() - t0
    clone_compiles = train_step_compiles() - before

    # ragged last batch: the tail pads onto the steady bucket, so the
    # whole fit costs at most one extra (label-masked) compile
    tail = max(1, batch // 3)
    before = train_step_compiles()
    model.fit(iter([(x, y, None, None),
                    (x[:tail], y[:tail], None, None)]))
    ragged_compiles = train_step_compiles() - before

    speedup = cold_s / max(clone_s, 1e-9)
    return {"metric": "compile_reuse", "value": round(speedup, 1),
            "unit": "x cold/clone first-step",
            "cold_first_step_ms": round(cold_s * 1e3, 1),
            "clone_first_step_ms": round(clone_s * 1e3, 1),
            "clone_extra_compiles": clone_compiles,
            "ragged_fit_compiles": ragged_compiles}


def checkpoint_overhead(hidden: int = 128, features: int = 64,
                        classes: int = 10, batch: int = 64,
                        steps: int = 16, save_every: int = 4) -> Dict:
    """Checkpointing-overhead benchmark (ISSUE 5): training stall per
    checkpoint from a sync (blocking) save vs an async (background,
    double-buffered) save, plus the committed-bytes write rate.

    ``value`` is the ASYNC stall in ms/save — what production training
    actually pays per checkpoint: the host snapshot only, with the write
    overlapped on the manager's worker thread across the following
    ``save_every - 1`` uncheckpointed steps (saving EVERY step would
    drain the double buffer at disk speed — real cadences leave the
    writer headroom).  ``sync_stall_ms`` is the full in-line write cost
    the async path hides.  Baseline and checkpointed loops run the same
    compiled step (warm-up excluded); ``_fit_one`` host-syncs the loss,
    so timings close on device completion.
    """
    import shutil
    import tempfile

    import jax.numpy as jnp

    from .. import (InputType, MultiLayerNetwork, NeuralNetConfiguration)
    from ..faulttolerance.checkpoint import CheckpointManager
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer

    def build():
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(Adam(learning_rate=0.01)).list()
                .layer(DenseLayer(n_out=hidden, activation="relu"))
                .layer(DenseLayer(n_out=hidden, activation="relu"))
                .layer(OutputLayer(n_out=classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(features))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((batch, features),
                                        dtype=np.float32))
    y = jnp.asarray(np.eye(classes, dtype=np.float32)[
        rng.integers(0, classes, batch)])
    model = build()
    model.fit_batch((x, y))                     # compile + warm

    n_saves = max(1, steps // save_every)

    def loop_s(save=None):
        t0 = monotonic_s()
        for i in range(steps):
            model.fit_batch((x, y))
            if save is not None and (i + 1) % save_every == 0:
                save()
        return monotonic_s() - t0

    base_s = loop_s()
    workdir = tempfile.mkdtemp(prefix="dl4j_ckpt_bench_")
    try:
        sync_mgr = CheckpointManager(os.path.join(workdir, "sync"),
                                     keep_last=2, background=False)
        sync_s = loop_s(lambda: sync_mgr.save(model))
        ckpt_path = sync_mgr.latest()
        nbytes = sum(
            os.path.getsize(os.path.join(ckpt_path, f))
            for f in os.listdir(ckpt_path)) if ckpt_path else 0
        async_mgr = CheckpointManager(os.path.join(workdir, "async"),
                                      keep_last=2, background=True)
        async_s = loop_s(lambda: async_mgr.save(model))
        async_mgr.wait()
        # steady-state async stall: save() with the writer idle (the
        # production regime — checkpoint cadence >> write time) pays only
        # the host snapshot + thread handoff.  The loop numbers above
        # additionally capture double-buffer drain when this toy step
        # outruns the disk.
        t0 = monotonic_s()
        async_mgr.save(model)
        idle_stall_s = monotonic_s() - t0
        async_mgr.wait()
        # isolate the write itself for the bytes/sec figure
        t0 = monotonic_s()
        sync_mgr.save(model, blocking=True)
        write_s = monotonic_s() - t0
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    sync_stall = (sync_s - base_s) / n_saves * 1e3
    async_stall = (async_s - base_s) / n_saves * 1e3
    return {"metric": "checkpoint_overhead",
            "value": round(idle_stall_s * 1e3, 3),
            "unit": "ms/save async stall (idle writer)",
            "sync_stall_ms": round(sync_stall, 3),
            "async_loop_stall_ms": round(async_stall, 3),
            "base_step_ms": round(base_s / steps * 1e3, 3),
            "save_every": save_every,
            "checkpoint_bytes": int(nbytes),
            "write_mb_per_sec": round(nbytes / max(write_s, 1e-9) / 1e6, 1)}


def recovery_time_ms(hidden: int = 24, features: int = 8, classes: int = 3,
                     n_batches: int = 12, batch: int = 16) -> Dict:
    """Recovery-time benchmark (ISSUE 7): wall time from an injected
    worker kill to the FIRST post-recovery training step, on both
    recovery paths of the parameter-averaging master:

    - **sync retry** — a transient failure: the master restores the
      round-start snapshot, sleeps the seeded backoff, and re-executes
      the same worker's chunk.  Recovery = backoff + snapshot restore.
    - **elastic degradation** — a permanent loss: the retry budget
      exhausts and the survivors re-chunk the dead worker's round NOW.
      Recovery = loss verdict (the last failed attempt) to the first
      replayed batch on a survivor.

    ``value`` is the sync-retry figure (the common transient case); the
    elastic figure rides along.  Timestamps come from the
    ``FaultInjector``'s per-worker fault/recovery bookkeeping, so the
    measurement is the master's actual reaction time, not a loop-level
    subtraction.
    """
    from ..faulttolerance.faults import FaultInjector
    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from ..parallel.master import ParameterAveragingTrainingMaster

    def build():
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(Adam(learning_rate=0.02)).list()
                .layer(DenseLayer(n_out=hidden, activation="tanh"))
                .layer(OutputLayer(n_out=classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(features))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(5)
    batches = []
    for _ in range(n_batches):
        x = rng.standard_normal((batch, features)).astype(np.float32)
        y = np.eye(classes, dtype=np.float32)[
            rng.integers(0, classes, batch)]
        batches.append((x, y))
    build().fit_batch(batches[0])               # compile + warm the cache

    def run(injector, max_retries):
        master = ParameterAveragingTrainingMaster(
            2, averaging_frequency=2, max_retries=max_retries,
            retry_backoff_s=0.02, fault_injector=injector)
        master.fit(build(), iter(batches))
        return injector.recoveries_s

    retry_rec = run(FaultInjector(seed=0).fail(worker=1, rnd=1, times=1),
                    max_retries=2)
    elastic_rec = run(FaultInjector(seed=0).fail(worker=1, rnd=1, times=-1),
                      max_retries=1)
    retry_ms = retry_rec[0] * 1e3 if retry_rec else None
    elastic_ms = elastic_rec[0] * 1e3 if elastic_rec else None
    return {"metric": "recovery_time_ms",
            "value": None if retry_ms is None else round(retry_ms, 2),
            "unit": "ms kill -> first post-recovery step (sync retry)",
            "elastic_ms": None if elastic_ms is None
            else round(elastic_ms, 2),
            "workers": 2, "retry_backoff_s": 0.02}


def lint_time_ms(paths=None, runs: int = 2) -> Dict:
    """graftlint wall-time benchmark (ISSUE 9): one full-package run
    through the public ``lint_paths`` API — 24 module rules off the
    shared per-file parse plus the whole-program concurrency pass
    (JX018–JX021).  The linter gates tier-1 and the developer loop, so a
    rule addition that blows up its wall time is a latency regression
    exactly like a slow train step; this row makes it round-over-round
    visible.  ``value`` is the MEDIAN of ``runs`` runs (process-cache
    effects make the first run the slowest)."""
    import sys
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # import under a TEMPORARY path entry: leaving the repo root on
    # sys.path would let its top-level packages (tools, tests, bench)
    # shadow a host application's same-named modules forever after
    added = repo_root not in sys.path
    if added:
        sys.path.insert(0, repo_root)
    try:
        from tools.graftlint import PROGRAM_RULES, RULES, \
            iter_python_files, lint_paths
    finally:
        if added:
            sys.path.remove(repo_root)
    if paths is None:
        paths = [os.path.join(repo_root, "deeplearning4j_tpu")]
    n_files = len(list(iter_python_files(paths)))
    times = []
    findings = []
    for _ in range(max(1, runs)):
        t0 = monotonic_s()
        findings = lint_paths(paths)
        times.append((monotonic_s() - t0) * 1e3)
    return {
        "metric": "lint_time_ms",
        "value": round(float(np.median(times)), 1),
        "unit": "ms full-package graftlint",
        "files": n_files,
        "rules": len(RULES) + len(PROGRAM_RULES),
        "findings": len(findings),
        "runs": len(times),
        "spread_ms": round(max(times) - min(times), 1),
    }


def audit_time_ms(include=None) -> Dict:
    """graftaudit wall-time benchmark (ISSUE 14; diff slice ISSUE 16):
    build the canonical program set through its production entry
    points, then run the full IR audit — jaxpr phase plus the
    partitioned-HLO compiles of every program — then the differential
    gate's budgets.json ceiling checks.  The audit gates tier-1
    (tests/test_audit.py, test_audit_diff.py) exactly like
    graftlint does, so rule/program additions that blow up its wall
    time are a CI-latency regression this row keeps round-over-round
    visible; the acceptance budget is the full run (build + audit)
    under 60s on the CPU rig.  One run — the dominant cost is XLA
    compiles, which the persistent jit caches would make a second run
    under-report.  Coverage is EXPLICIT: canonical programs the host
    couldn't build (a sharded dp on a single-device backend) land in
    ``skipped`` — a row claiming the full set while silently covering
    6 of 8 programs would hide exactly the layout regressions the
    audit exists to catch."""
    import sys
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # temporary path entry, same hygiene as lint_time_ms
    added = repo_root not in sys.path
    if added:
        sys.path.insert(0, repo_root)
    try:
        from tools.graftaudit import AUDIT_RULES, audit_programs
        from tools.graftaudit.canonical import (BUDGETS_PATH,
                                                CANONICAL_CONFIG,
                                                build_canonical)
        from tools.graftaudit.diff import check_budgets, load_budgets
    finally:
        if added:
            sys.path.remove(repo_root)
    t0 = monotonic_s()
    cs = build_canonical(include=include)
    build_ms = (monotonic_s() - t0) * 1e3
    t1 = monotonic_s()
    result = audit_programs(cs.programs, cs.suppressions,
                            CANONICAL_CONFIG)
    audit_ms = (monotonic_s() - t1) * 1e3
    # the differential-gate slice (ISSUE 16): the budgets.json ceiling
    # checks --diff-cards adds on top of the audit (AX010 card drift is
    # already inside audit_ms — CANONICAL_CONFIG arms it)
    t2 = monotonic_s()
    budgets = load_budgets(BUDGETS_PATH)
    # an include subset leaves non-matching budgeted programs
    # un-audited, not stale (same rule as the CLI's --programs)
    skipped_for_diff = dict(cs.skipped)
    if include is not None:
        audited = {ir_prog.name for ir_prog in result.irs}
        for name in budgets.get("programs", {}):
            if name not in audited and \
                    not any(s in name for s in include):
                skipped_for_diff.setdefault(name, "include subset")
    diff_findings, stale = check_budgets(
        result.irs, budgets, skipped_for_diff)
    diff_ms = (monotonic_s() - t2) * 1e3
    return {
        "metric": "audit_time_ms",
        "value": round(build_ms + audit_ms + diff_ms, 1),
        "unit": "ms full canonical-set IR audit (build + audit + diff)",
        "build_ms": round(build_ms, 1),
        "audit_ms": round(audit_ms, 1),
        "diff_ms": round(diff_ms, 1),
        "programs": len(result.irs),
        "skipped": sorted(cs.skipped),
        "rules": len(AUDIT_RULES),
        "findings": len(result.findings) + len(diff_findings),
        "stale_budgets": sorted(stale),
        "suppressed": sum(result.suppressed.values()),
        "budget_ms": 60000.0,
    }


def obs_overhead_ms(hidden: int = 256, features: int = 128,
                    classes: int = 10, batch: int = 128,
                    n_batches: int = 10,
                    runs: int = 21, isolate: bool = False) -> Dict:
    """Observability-overhead benchmark (ISSUE 10): steady-state per-step
    train time with the runtime-forensics layer (flight recorder + health
    monitor) installed vs absent.  The fit loop's forensics feed
    (``_StepForensics``) captures one raw tuple per step and drains the
    buffer through the recorder ring and the monitor's EWMA detectors in
    warm batches — ~10us/step flat — so the target is <2%; this row
    keeps that claim measured instead of asserted, round over round.
    The workload is sized so the step does real compute (~3 ms on the
    1-core CPU test host, MLP 128->256->256->10 at batch 128): a
    dispatch-dominated sub-ms toy step would bill the flat microsecond
    cost against a denominator no real training run has.
    Shared-host noise between whole fits dwarfs the ~10us/step effect,
    so the design is PAIRED over SHORT fits: each round runs both arms
    back to back (order alternating to cancel cache-warmth bias) and
    the overhead is the median of the per-round deltas.  Chunks are kept
    to tens of milliseconds so both arms of a pair land inside one host
    drift window (~100 ms scheduler/freq timescale on the test host) —
    longer fits let drift straddle a pair and leak into the deltas;
    independent medians would report the drift, not the overhead.  ``isolate=True`` (bench.py uses it) reruns the
    measurement in a fresh interpreter: by the 9th JSON line the bench
    process carries the headline run's multi-MB heap, and LLC pressure
    from that unrelated residue inflates the cache-cold Python deltas
    ~2-3x — a microbenchmark of the forensics layer must not bill it
    for another benchmark's memory."""
    if isolate:
        import subprocess
        import sys
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        code = (
            "import json\n"
            "from deeplearning4j_tpu.utils.benchmarks import "
            "obs_overhead_ms\n"
            f"print(json.dumps(obs_overhead_ms(hidden={hidden}, "
            f"features={features}, classes={classes}, batch={batch}, "
            f"n_batches={n_batches}, runs={runs})))\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, env=env,
                              timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                "isolated obs_overhead_ms run failed: "
                + proc.stderr.strip()[-300:])
        import json as _json
        row = _json.loads(proc.stdout.strip().splitlines()[-1])
        row["isolated"] = True
        return row
    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from ..observability.health import HealthMonitor, set_health_monitor
    from ..observability.recorder import FlightRecorder, set_flight_recorder

    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=0.01)).list()
            .layer(DenseLayer(n_out=hidden, activation="tanh"))
            .layer(DenseLayer(n_out=hidden, activation="tanh"))
            .layer(OutputLayer(n_out=classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(features)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(13)
    batches = [(rng.standard_normal((batch, features)).astype(np.float32),
                np.eye(classes, dtype=np.float32)[
                    rng.integers(0, classes, batch)])
               for _ in range(n_batches)]
    net.fit(iter(batches[:2]), epochs=1)          # compile + warm

    def timed(enabled: bool) -> float:
        prev_rec = set_flight_recorder(
            FlightRecorder(capacity=256) if enabled else None)
        prev_mon = set_health_monitor(HealthMonitor() if enabled else None)
        try:
            t0 = monotonic_s()
            net.fit(iter(batches), epochs=1)
            return (monotonic_s() - t0) / n_batches * 1e3
        finally:
            set_flight_recorder(prev_rec)
            set_health_monitor(prev_mon)

    off_t, on_t, deltas = [], [], []
    for i in range(max(1, runs)):
        # alternate arm order: the second fit of a pair runs cache-warmer,
        # so a fixed order would systematically bias the deltas
        if i % 2 == 0:
            off = timed(False)
            on = timed(True)
        else:
            on = timed(True)
            off = timed(False)
        off_t.append(off)
        on_t.append(on)
        deltas.append(on - off)
    off_ms = float(np.median(off_t))
    on_ms = float(np.median(on_t))
    overhead_ms = float(np.median(deltas))
    overhead_pct = overhead_ms / off_ms * 100.0 if off_ms > 0 else None
    return {
        "metric": "obs_overhead_ms",
        "value": round(on_ms, 3),
        "unit": "ms/step recorder+monitor enabled",
        "off_ms": round(off_ms, 3),
        "overhead_ms": round(overhead_ms, 3),
        "overhead_pct": None if overhead_pct is None
        else round(overhead_pct, 2),
        "target_pct": 2.0,
        "steps": n_batches,
        "runs": max(1, runs),
    }


def profiler_overhead_ms(hidden: int = 256, features: int = 128,
                         classes: int = 10, batch: int = 128,
                         n_batches: int = 10,
                         runs: int = 21, isolate: bool = False) -> Dict:
    """Step-profiler overhead benchmark (ISSUE 17 acceptance): steady
    per-step train time with the :class:`StepProfiler` armed (default-on
    config — sampled fence every 16 steps) vs ``DL4J_TPU_STEPPROF=0``.
    The per-step cost is a handful of ``perf_counter`` reads plus one
    buffered tuple append; the sampled fence amortizes its sync across
    the window — the target is <2%, measured here round over round.

    Same paired-short-fit design as :func:`obs_overhead_ms` (which see
    for the sizing/pairing/isolation rationale): both arms run back to
    back per round with alternating order, overhead is the median of
    per-round deltas, and ``isolate=True`` reruns in a fresh interpreter.

    The row also carries the attribution honesty check: one extra fit at
    ``sample_every=1`` (every step fenced) whose ``phase_share``
    breakdown and ``phase_coverage`` (phase sum over step wall on
    sampled steps, from :func:`~..observability.profiler.phase_summary`)
    must cover the wall within 5%."""
    if isolate:
        import subprocess
        import sys
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        code = (
            "import json\n"
            "from deeplearning4j_tpu.utils.benchmarks import "
            "profiler_overhead_ms\n"
            f"print(json.dumps(profiler_overhead_ms(hidden={hidden}, "
            f"features={features}, classes={classes}, batch={batch}, "
            f"n_batches={n_batches}, runs={runs})))\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, env=env,
                              timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                "isolated profiler_overhead_ms run failed: "
                + proc.stderr.strip()[-300:])
        import json as _json
        row = _json.loads(proc.stdout.strip().splitlines()[-1])
        row["isolated"] = True
        return row
    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from ..observability.profiler import CHANNEL, phase_summary
    from ..observability.recorder import FlightRecorder, set_flight_recorder

    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=0.01)).list()
            .layer(DenseLayer(n_out=hidden, activation="tanh"))
            .layer(DenseLayer(n_out=hidden, activation="tanh"))
            .layer(OutputLayer(n_out=classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(features)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(13)
    batches = [(rng.standard_normal((batch, features)).astype(np.float32),
                np.eye(classes, dtype=np.float32)[
                    rng.integers(0, classes, batch)])
               for _ in range(n_batches)]
    net.fit(iter(batches[:2]), epochs=1)          # compile + warm

    def timed(enabled: bool) -> float:
        # both arms keep the recorder installed so the delta isolates the
        # profiler itself, not the ring the records land in
        prev_rec = set_flight_recorder(FlightRecorder(capacity=256))
        prev_env = os.environ.get("DL4J_TPU_STEPPROF")
        os.environ["DL4J_TPU_STEPPROF"] = "1" if enabled else "0"
        try:
            t0 = monotonic_s()
            net.fit(iter(batches), epochs=1)
            return (monotonic_s() - t0) / n_batches * 1e3
        finally:
            set_flight_recorder(prev_rec)
            if prev_env is None:
                os.environ.pop("DL4J_TPU_STEPPROF", None)
            else:
                os.environ["DL4J_TPU_STEPPROF"] = prev_env

    off_t, on_t, deltas = [], [], []
    for i in range(max(1, runs)):
        if i % 2 == 0:
            off = timed(False)
            on = timed(True)
        else:
            on = timed(True)
            off = timed(False)
        off_t.append(off)
        on_t.append(on)
        deltas.append(on - off)
    off_ms = float(np.median(off_t))
    on_ms = float(np.median(on_t))
    overhead_ms = float(np.median(deltas))
    overhead_pct = overhead_ms / off_ms * 100.0 if off_ms > 0 else None

    # attribution honesty: one fully-fenced fit, phase sums vs step wall
    rec = FlightRecorder(capacity=256)
    prev_rec = set_flight_recorder(rec)
    prev_env = {k: os.environ.get(k)
                for k in ("DL4J_TPU_STEPPROF", "DL4J_TPU_STEPPROF_SAMPLE")}
    os.environ["DL4J_TPU_STEPPROF"] = "1"
    os.environ["DL4J_TPU_STEPPROF_SAMPLE"] = "1"
    try:
        net.fit(iter(batches), epochs=1)
    finally:
        set_flight_recorder(prev_rec)
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    summary = phase_summary(rec.channel(CHANNEL).items())
    coverage = summary.get("sampled_coverage")
    return {
        "metric": "profiler_overhead_ms",
        "value": round(on_ms, 3),
        "unit": "ms/step stepprof enabled",
        "off_ms": round(off_ms, 3),
        "overhead_ms": round(overhead_ms, 3),
        "overhead_pct": None if overhead_pct is None
        else round(overhead_pct, 2),
        "target_pct": 2.0,
        "phase_coverage": None if coverage is None else round(coverage, 4),
        "phase_share": {k: round(v, 4) for k, v in
                        (summary.get("phase_share") or {}).items()},
        "steps": n_batches,
        "runs": max(1, runs),
    }


def sharded_step_time_ms(hidden: int = 512, features: int = 256,
                         classes: int = 32, batch: int = 64,
                         steps: int = 12, warm: int = 2,
                         dp: Optional[int] = None,
                         min_shard_size: Optional[int] = None) -> Dict:
    """ZeRO-3 sharded-training benchmark (ISSUE 12): steady per-step
    train time through ``parallel.ShardedTrainer`` (params + updater
    state row-sharded over the data axis; reduce-scatter gradients,
    shard-local update, XLA-inserted forward all-gather) vs the
    replicated ``ParallelWrapper`` (full params per device, dense
    all-reduce) at a FIXED global batch on the same mesh — plus the
    memory side of the trade: per-device parameter bytes, which the
    sharded layout holds at ~1/dp of replicated (``param_bytes_ratio``).

    ``train_step_traces`` carries the compile-counter delta across BOTH
    runs: the sharded and replicated paths execute the same jitted
    program from the process-global trace cache (sharding lives in the
    arguments, not the trace), so the whole bench traces ONCE.  On the
    1-core CPU rig the collectives are memcpy loops and sharding is pure
    overhead (``vs_replicated`` > 1 is expected there); the row exists
    to track the trajectory and the memory win, which is
    backend-independent."""
    import jax

    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from ..observability.registry import default_registry
    from ..parallel import (ParallelWrapper, ShardedTrainer, make_mesh,
                            param_bytes, per_device_param_bytes)

    from ..parallel.mesh import DEFAULT_MIN_SHARD_SIZE
    if min_shard_size is None:
        # track the trainer's default so the row always measures the
        # layout ShardedTrainer actually ships
        min_shard_size = DEFAULT_MIN_SHARD_SIZE
    if dp is None:
        dp = len(jax.devices())

    def build():
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(Adam(learning_rate=0.01)).list()
                .layer(DenseLayer(n_out=hidden, activation="tanh"))
                .layer(DenseLayer(n_out=hidden, activation="tanh"))
                .layer(DenseLayer(n_out=hidden, activation="tanh"))
                .layer(OutputLayer(n_out=classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(features)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(3)
    x = rng.standard_normal((batch, features)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, batch)]

    def traces() -> float:
        c = default_registry().get("training_compile_total")
        return 0.0 if c is None else c.labels("train_step").value

    t_before = traces()
    mesh = make_mesh(dp=dp, tp=1, sp=1)
    results = {}
    nets = []   # keep both nets alive: the shared trace-cache entry is
    # weak-valued, so dropping the first net would free the jitted step
    # and bill the second run a spurious retrace
    for impl in ("replicated", "sharded"):
        net = build()
        nets.append(net)
        tr = ParallelWrapper(net, mesh) if impl == "replicated" else \
            ShardedTrainer(net, mesh, min_shard_size=min_shard_size)
        tr.fit(iter([(x, y, None, None)] * max(1, warm)))   # compile+warm
        t0 = monotonic_s()
        # wrapper.fit closes on a final host sync of the score, so the
        # clock reads device completion, not enqueue
        tr.fit(iter([(x, y, None, None)] * steps))
        ms = (monotonic_s() - t0) / steps * 1e3
        results[impl] = (ms, per_device_param_bytes(net.params),
                         param_bytes(net.params))
    sh_ms, sh_dev_bytes, global_bytes = results["sharded"]
    rep_ms, rep_dev_bytes, _ = results["replicated"]
    return {
        "metric": "sharded_step_time_ms",
        "value": round(sh_ms, 3),
        "unit": f"ms/step (dp={dp} ZeRO-3 sharded)",
        "replicated_ms": round(rep_ms, 3),
        "vs_replicated": round(sh_ms / rep_ms, 3) if rep_ms else None,
        "dp": dp,
        "global_batch": batch,
        "param_bytes_per_device": int(sh_dev_bytes),
        "replicated_param_bytes": int(rep_dev_bytes),
        "param_bytes_ratio": round(sh_dev_bytes / rep_dev_bytes, 4)
        if rep_dev_bytes else None,
        "global_param_bytes": int(global_bytes),
        "min_shard_size": int(min_shard_size),
        "train_step_traces": int(traces() - t_before),
        "steps": steps,
    }


def embedding_grad_exchange_ms(vocabs=(50_000, 500_000),
                               touched_fracs=(0.01, 0.10),
                               dim: int = 16, batch: int = 1024,
                               classes: int = 4, steps: int = 8,
                               warm: int = 2,
                               dp: Optional[int] = None) -> List[Dict]:
    """Sparse-embedding gradient-exchange benchmark (ISSUE 15): steady
    per-step train time of the DENSIFIED index/value exchange (a
    ``sparse_grad=True`` table, ZeRO-3 row-sharded over the mesh
    through ``ShardedTrainer`` — coalesced touched rows, O(capacity·dim)
    collectives, lazy row-space updater) vs the DENSE baseline (the
    replicated ``ParallelWrapper``, whose every step all-reduces the
    full mostly-zero ``[vocab, dim]`` gradient), swept over
    vocab × touched-rows fraction.

    Ids are drawn from a pool of ``frac·vocab`` distinct rows, so the
    sparse path exchanges at most that many rows while the dense path
    always ships the whole table.  On the CPU rig the collectives are
    memcpy loops, which makes the O(vocab) dense volume directly
    visible in step time; the acceptance claim (ISSUE 15: densified
    beats dense at vocab ≥ 50k with ≤10% touched) is ``vs_dense < 1``.
    ``steady_recompiles`` carries the compile-counter delta across the
    timed windows — the zero-steady-state-recompile half of the
    acceptance line (each path compiles its own program up front; the
    timed steps must add none).  SGD keeps the comparison about the
    gradient exchange, not updater-mirror traffic.
    """
    import jax

    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Sgd
    from ..nn.layers.feedforward import EmbeddingLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from ..observability.registry import default_registry
    from ..parallel import ParallelWrapper, ShardedTrainer, make_mesh

    if dp is None:
        dp = len(jax.devices())

    def build(vocab, sparse):
        lb = (NeuralNetConfiguration.builder().seed(13)
              .updater(Sgd(learning_rate=0.05)).list())
        lb.layer(EmbeddingLayer(n_in=vocab, n_out=dim,
                                sparse_grad=sparse))
        lb.layer(OutputLayer(n_out=classes, activation="softmax",
                             loss="mcxent"))
        return MultiLayerNetwork(lb.build()).init()

    def traces() -> float:
        c = default_registry().get("training_compile_total")
        return 0.0 if c is None else c.labels("train_step").value

    mesh = make_mesh(dp=dp)
    rng = np.random.default_rng(29)
    rows = []
    for vocab in vocabs:
        for frac in touched_fracs:
            pool = rng.choice(vocab, size=max(1, int(frac * vocab)),
                              replace=False)
            ids = pool[rng.integers(0, len(pool), batch)] \
                .reshape(batch, 1).astype(np.int32)
            y = np.eye(classes, dtype=np.float32)[
                rng.integers(0, classes, batch)]
            results = {}
            recompiles = 0.0
            nets = []   # both nets stay alive: the shared trace-cache
            # entries are weak-valued (see sharded_step_time_ms)
            for impl in ("dense", "sparse"):
                net = build(vocab, impl == "sparse")
                nets.append(net)
                tr = ParallelWrapper(net, mesh) if impl == "dense" else \
                    ShardedTrainer(net, mesh, min_shard_size=0)
                tr.fit(iter([(ids, y, None, None)] * max(1, warm)))
                t_steady = traces()
                t0 = monotonic_s()
                # wrapper.fit closes on a final host sync of the score,
                # so the clock reads device completion, not enqueue
                tr.fit(iter([(ids, y, None, None)] * steps))
                results[impl] = (monotonic_s() - t0) / steps * 1e3
                recompiles += traces() - t_steady
            sp_ms, de_ms = results["sparse"], results["dense"]
            rows.append({
                "metric": f"embedding_grad_exchange_ms"
                          f"[v={vocab},t={frac:g}]",
                "value": round(sp_ms, 3),
                "unit": "ms/step (densified index/value exchange, "
                        "row-sharded table)",
                "dense_all_reduce_ms": round(de_ms, 3),
                "vs_dense": round(sp_ms / de_ms, 3) if de_ms else None,
                "densified_wins": bool(sp_ms < de_ms),
                "vocab": int(vocab), "dim": dim,
                "touched_frac": float(frac),
                "touched_rows_max": int(len(pool)),
                "capacity": int(min(batch, vocab)),
                "table_mbytes": round(vocab * dim * 4 / 2**20, 2),
                "dp": dp, "global_batch": batch,
                "steady_recompiles": int(recompiles),
                "steps": steps,
            })
    return rows


def elastic_reshard_ms(hidden: int = 32, features: int = 8,
                       classes: int = 4, n_batches: int = 16,
                       batch: int = 8, save_freq: int = 2,
                       lease_ttl_s: float = 0.4,
                       step_sleep_s: float = 0.05) -> Dict:
    """Elastic-reshard benchmark (ISSUE 13): wall time from a MEMBER
    LOSS (its last heartbeat — the process is gone) to the FIRST clean
    sharded train step on the survivor mesh.  The run is the real
    elastic path end to end: a two-member view over a dp=4 ZeRO-3 mesh,
    the dead member's in-flight barrier round aborted (never a torn
    store), eviction at the next round boundary, the survivor mesh
    rebuilt through ``restore_sharded(mesh=survivors)`` (params +
    updater mirrors re-placed byte-exact at dp=2), then training
    continues — ``restore_ms`` carries the reshard-restore slice of
    that window, ``detect_ms`` the lease-expiry + boundary wait.  The
    train step itself keeps its single process-global trace across the
    topology change (re-LOWERING for the new mesh is part of the
    measured window, as it is in production)."""
    import tempfile

    import jax

    from ..faulttolerance.cluster import (ClusterCoordinator,
                                          ClusterMember, FileLeaseStore)
    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from ..parallel.distributed import ElasticTrainer
    from ..parallel.mesh import make_mesh
    from ..parallel.sharded import ShardedTrainer

    import time

    if len(jax.devices()) < 4:
        raise RuntimeError("elastic_reshard_ms needs >= 4 devices")

    def build():
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(Adam(learning_rate=0.02)).list()
                .layer(DenseLayer(n_out=hidden, activation="tanh"))
                .layer(OutputLayer(n_out=classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(features))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(5)
    batches = []
    for _ in range(n_batches):
        x = rng.standard_normal((batch, features)).astype(np.float32)
        y = np.eye(classes, dtype=np.float32)[
            rng.integers(0, classes, batch)]
        batches.append((x, y))

    # prewarm the TRACE (dp=4 executable): the member must die mid-run,
    # not during the first step's cold compile
    warm = build()
    ShardedTrainer(warm, make_mesh(dp=4), min_shard_size=0).fit_batch(
        batches[0])

    workdir = tempfile.mkdtemp(prefix="dl4j-reshard-bench-")
    try:
        store = FileLeaseStore(workdir)
        coord = ClusterCoordinator(store, lease_ttl_s=lease_ttl_s)
        m0 = ClusterMember(store, 0, lease_ttl_s=10.0)
        m0.renew_once()
        net = build()
        st = ShardedTrainer(net, make_mesh(dp=4), min_shard_size=0)
        trainer = ElasticTrainer(
            st, workdir, save_freq=save_freq, member=m0,
            coordinator=coord,
            mesh_factory=lambda w: make_mesh(dp=2 * w),
            barrier_timeout_s=10.0)
        # the doomed member: one lease, never renewed — its "death" is
        # the renew timestamp, its loss is DETECTED when the lease
        # expires under the survivor's barrier/boundary machinery
        store.renew(1, ttl_s=lease_ttl_s)
        t_loss = monotonic_s()
        coord.begin_round(0)

        step_done_s: list = []

        class _Clock:
            def iteration_done(self, model, iteration, epoch):
                step_done_s.append(monotonic_s())

        net.listeners.append(_Clock())

        def feed():
            for b in batches:
                time.sleep(step_sleep_s)
                yield b

        steps = trainer.fit(feed)
        m0.stop()
    finally:
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)

    ev = trainer.reshard_events[0] if trainer.reshard_events else None
    first_clean = None
    if ev is not None:
        after = [t for t in step_done_s if t > ev["t"]]
        first_clean = after[0] if after else None
    value = None if (ev is None or first_clean is None) \
        else (first_clean - t_loss) * 1e3
    return {
        "metric": "elastic_reshard_ms",
        "value": None if value is None else round(value, 2),
        "unit": "ms member loss -> first clean sharded step "
                "(survivor mesh)",
        "restore_ms": None if ev is None else round(ev["ms"], 2),
        "detect_ms": None if (ev is None or first_clean is None)
        else round(value - ev["ms"], 2),
        "dp_before": 4, "dp_after": None if ev is None else ev["dp"],
        "world_before": 2,
        "world_after": None if ev is None else ev["world_size"],
        "barrier_aborts": trainer.barrier_aborts,
        "lease_ttl_s": lease_ttl_s, "save_freq": save_freq,
        "steps": steps,
    }


def dispatch_pipeline_ms(depths=(2, 4), n_batches: int = 24,
                         runs: int = 7, isolate: bool = False) -> Dict:
    """Bounded-dispatch pipeline benchmark (ISSUE 18): steady per-step
    train time at ``DL4J_TPU_DISPATCH_DEPTH=1`` (the fully serial
    per-step-sync loop) vs the windowed depths, on two arms chosen to
    bracket the claim:

    - **dispatch-bound** — a model tiny enough that the compiled step is
      microseconds, so the step time IS the host-side dispatch work the
      window overlaps (the regime the pipeline exists for);
    - **compute-bound** — the :func:`profiler_overhead_ms` geometry,
      where the device math dominates and the honest expectation is a
      speedup near 1.0 (the window can only hide host time that exists).

    Same paired design as :func:`obs_overhead_ms`: both arms of a pair
    run back to back per round with alternating order, and the reported
    per-depth speedup is the median of per-round ``depth1/depthN``
    ratios.  The depth is read per fit (``configured_depth``), and it
    lives entirely host-side — flipping it must not retrace, which
    ``train_step_traces`` (the compile-counter delta across every
    post-warm fit) proves on the row itself.  ``isolate=True`` reruns
    in a fresh interpreter like the other overhead rows."""
    if isolate:
        import subprocess
        import sys
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        code = (
            "import json\n"
            "from deeplearning4j_tpu.utils.benchmarks import "
            "dispatch_pipeline_ms\n"
            f"print(json.dumps(dispatch_pipeline_ms(depths={tuple(depths)}, "
            f"n_batches={n_batches}, runs={runs})))\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, env=env,
                              timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                "isolated dispatch_pipeline_ms run failed: "
                + proc.stderr.strip()[-300:])
        import json as _json
        row = _json.loads(proc.stdout.strip().splitlines()[-1])
        row["isolated"] = True
        return row
    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.dispatch import ENV_VAR
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from ..observability.registry import default_registry

    def traces() -> float:
        c = default_registry().get("training_compile_total")
        return 0.0 if c is None else c.labels("train_step").value

    def timed(net, batches, depth: int) -> float:
        prev = os.environ.get(ENV_VAR)
        os.environ[ENV_VAR] = str(depth)
        try:
            t0 = monotonic_s()
            net.fit(iter(batches), epochs=1)
            # fit's epoch-end drain syncs the last score, so the clock
            # reads device completion at every depth, not enqueue
            return (monotonic_s() - t0) / len(batches) * 1e3
        finally:
            if prev is None:
                os.environ.pop(ENV_VAR, None)
            else:
                os.environ[ENV_VAR] = prev

    def arm(hidden: int, features: int, classes: int, batch: int) -> Dict:
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(learning_rate=0.01)).list()
                .layer(DenseLayer(n_out=hidden, activation="tanh"))
                .layer(DenseLayer(n_out=hidden, activation="tanh"))
                .layer(OutputLayer(n_out=classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(features)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(13)
        batches = [(rng.standard_normal((batch, features))
                    .astype(np.float32),
                    np.eye(classes, dtype=np.float32)[
                        rng.integers(0, classes, batch)])
                   for _ in range(n_batches)]
        net.fit(iter(batches[:2]), epochs=1)      # compile + warm
        out = {}
        for depth in depths:
            serial, deep, ratios = [], [], []
            for i in range(max(1, runs)):
                # alternate arm order: the second fit of a pair runs
                # cache-warmer, so a fixed order would bias the ratios
                if i % 2 == 0:
                    s = timed(net, batches, 1)
                    d = timed(net, batches, depth)
                else:
                    d = timed(net, batches, depth)
                    s = timed(net, batches, 1)
                serial.append(s)
                deep.append(d)
                ratios.append(s / d if d > 0 else 1.0)
            out[f"depth1_ms_vs{depth}"] = round(float(np.median(serial)), 3)
            out[f"depth{depth}_ms"] = round(float(np.median(deep)), 3)
            out[f"speedup_depth{depth}"] = round(float(np.median(ratios)), 3)
        return out

    t_before = traces()   # post-warm counter is read inside arm(); the
    # delta therefore counts BOTH arms' one-time compiles and nothing
    # from the depth flips themselves
    dispatch_bound = arm(hidden=16, features=16, classes=4, batch=8)
    compute_bound = arm(hidden=256, features=128, classes=10, batch=128)
    trace_delta = int(traces() - t_before)
    lead = sorted(int(d) for d in depths)[0]
    return {
        "metric": "dispatch_pipeline_ms",
        "value": dispatch_bound[f"depth{lead}_ms"],
        "unit": f"ms/step dispatch-bound arm @ depth={lead}",
        "dispatch_bound": dispatch_bound,
        "compute_bound": compute_bound,
        "depths": [int(d) for d in depths],
        # 2 arms x (warm + paired fits); every fit past the two warmups
        # reuses the warm executable — the depth knob is host-only
        "train_step_traces_total": trace_delta,
        "steady_recompiles": max(0, trace_delta - 2),
        "steps": n_batches,
        "runs": max(1, runs),
    }


# ------------------------------------------------------------------ fleet
class _DevicePacedFn:
    """One compiled program with a fixed per-call pace appended.

    The sleep stands in for the device-step time of a real accelerator:
    on a TPU the host enqueues and goes idle while the device computes,
    so N replicas' steps overlap even on one host core.  On the 1-core
    CPU rig the XLA step occupies the host itself, which would make a
    fleet bench measure core contention instead of the routing tier —
    the pace (a GIL-releasing sleep, zero CPU) restores the
    host-async timing profile the fleet is designed for.  The wrapped
    program still runs for real (outputs stay bit-exact, traces still
    count), and attribute reads (``last_call_traced``) pass through."""

    def __init__(self, fn, pace_s: float):
        self._fn = fn
        self._pace_s = float(pace_s)

    def __call__(self, *args, **kw):
        out = self._fn(*args, **kw)
        time.sleep(self._pace_s)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


class _DevicePacedModel:
    """Model proxy whose compiled programs carry a fixed device pace.

    Intercepts ``_get_jitted`` (the single seam both the serving slot
    and the generation engine compile through) and returns cached
    :class:`_DevicePacedFn` wrappers — cached so program identity stays
    stable for the engines' trace accounting.  Everything else
    (``params``/``state``/``conf``/``output``/...) forwards to the real
    model."""

    def __init__(self, model, pace_s: float):
        self._model = model
        self._pace_s = float(pace_s)
        self._paced: Dict[str, _DevicePacedFn] = {}

    def _get_jitted(self, kind: str):
        fn = self._paced.get(kind)
        if fn is None:
            fn = _DevicePacedFn(self._model._get_jitted(kind),
                                self._pace_s)
            self._paced[kind] = fn
        return fn

    def __getattr__(self, name):
        return getattr(self._model, name)


def serve_fleet(replica_counts=(1, 2, 4), *, model=None, lm=None,
                pace_ms: float = 12.0, concurrency: int = 32,
                n_requests: int = 384, max_batch: int = 4,
                max_slots: int = 2, new_tokens: int = 24,
                kill_tokens: int = 48, max_seq: int = 64) -> List[Dict]:
    """Serving-fleet bench (ISSUE 20): closed-loop ``/predict`` req/s and
    ``/generate`` decode tokens/s through :class:`serving.ServingFleet`
    at each replica count, with ``vs_one_replica`` ratios (the
    acceptance gate: near-linear — >= 3x at 4 replicas), plus a
    kill-one-replica chaos row whose ``recovery_ms`` is the worst
    migrated session's gap from ``kill()`` to its first token on a
    survivor.  Every replica is device-paced (see
    :class:`_DevicePacedFn`): per-replica throughput is bounded by the
    paced step cadence, not host FLOPs, so the rows measure what the
    fleet tier adds — routing, affinity, migration — at the timing
    profile of real accelerator replicas.  ``steady_recompiles`` rides
    every row (warmed replicas + the process-shared trace cache must
    keep it 0 — including after the kill-phase rejoinless migration)."""
    import threading

    from ..generation import GenerationConfig
    from ..models import LeNet, TransformerLM
    from ..observability import MetricsRegistry
    from ..serving.fleet import ServingFleet

    pace_s = pace_ms / 1e3
    counts = sorted(int(r) for r in replica_counts)
    rows: List[Dict] = []

    # ---- stateless /predict: least-loaded routing over paced replicas
    if model is None:
        model = LeNet().init()
    probe = np.random.default_rng(0).standard_normal(
        _probe_shape(model)).astype(np.float32)
    paced = _DevicePacedModel(model, pace_s)
    base_rps = None
    for r in counts:
        fleet = ServingFleet(paced, n_replicas=r,
                             engine_kw=dict(max_batch_size=max_batch,
                                            queue_limit=1024),
                             registry=MetricsRegistry())
        try:
            fleet.warmup()
            lats, wall, errs = _closed_loop(
                lambda: fleet.predict(probe), concurrency, n_requests)
            lats_ms = np.asarray(lats) * 1e3
            rps = round(len(lats) / wall, 1)
            row = {
                "metric": f"serve_fleet[predict,r={r}]",
                "value": rps, "unit": "req/s", "replicas": r,
                "p50_ms": round(float(np.percentile(lats_ms, 50)), 2),
                "p99_ms": round(float(np.percentile(lats_ms, 99)), 2),
                "requests": len(lats), "errors": errs,
                "concurrency": concurrency, "max_batch": max_batch,
                "pace_ms": pace_ms,
                "steady_recompiles": fleet.stats()["steady_recompiles"],
            }
        finally:
            fleet.shutdown()
        if base_rps is None:
            base_rps = rps
        else:
            row["vs_one_replica"] = round(rps / base_rps, 2) \
                if base_rps else None
        rows.append(row)

    # ---- session-affine /generate: decode tokens/s + kill-one chaos
    if lm is None:
        lm = TransformerLM(vocab_size=64, seq_len=max_seq, embed=32,
                           n_layers=2, n_heads=2).init()
    paced_lm = _DevicePacedModel(lm, pace_s)
    vocab = lm.conf.layers[-1].n_out
    rng = np.random.default_rng(1)
    sessions = max_slots * counts[-1]     # fills every slot at max r
    prompts = [rng.integers(1, vocab, 6).tolist() for _ in range(sessions)]
    base_tps = None
    fleet = None
    for r in counts:
        fleet = ServingFleet(
            paced_lm, n_replicas=r,
            generation=GenerationConfig(max_slots=max_slots,
                                        max_seq=max_seq,
                                        queue_limit=4096),
            registry=MetricsRegistry())
        try:
            for rep in fleet.replicas:
                rep.engine.generation.warmup()
            results = [None] * sessions

            def _gen(i):
                results[i] = fleet.generate(
                    prompts[i], max_new_tokens=new_tokens,
                    temperature=0.0, timeout=300.0)

            threads = [threading.Thread(target=_gen, args=(i,))
                       for i in range(sessions)]
            t0 = monotonic_s()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = monotonic_s() - t0
            total = sum(len(res.tokens) for res in results
                        if res is not None)
            tps = round(total / wall, 1)
            row = {
                "metric": f"serve_fleet[decode,r={r}]",
                "value": tps, "unit": "tokens/sec", "replicas": r,
                "sessions": sessions, "new_tokens": new_tokens,
                "tokens": total, "max_slots": max_slots,
                "pace_ms": pace_ms,
                "steady_recompiles": fleet.stats()["steady_recompiles"],
            }
            if base_tps is None:
                base_tps = tps
            else:
                row["vs_one_replica"] = round(tps / base_tps, 2) \
                    if base_tps else None
            rows.append(row)
        finally:
            if r != counts[-1]:
                fleet.shutdown()

    # ---- chaos: kill one replica mid-decode on the widest fleet
    try:
        router = fleet.router
        handles = [router.open_session(p, max_new_tokens=kill_tokens,
                                       temperature=0.0)
                   for p in prompts]
        tok_times = [[] for _ in handles]
        stream_errs: List[str] = []

        def _consume(i, sess):
            for ev in router.events(sess, timeout=120.0):
                if "token" in ev:
                    tok_times[i].append(monotonic_s())
                if "error" in ev:
                    stream_errs.append(str(ev["error"]))

        threads = [threading.Thread(target=_consume, args=(i, s))
                   for i, s in enumerate(handles)]
        for t in threads:
            t.start()
        deadline = monotonic_s() + 60.0
        while monotonic_s() < deadline:
            if all(len(s.mirror["tokens"]) >= 1 for s in handles):
                break
            time.sleep(0.002)
        victim = handles[0].replica.id
        t_kill = monotonic_s()
        fleet.kill(victim)
        for t in threads:
            t.join(timeout=180)
        migrated = [i for i, s in enumerate(handles) if s.epoch > 0]
        recovery_ms = None
        if migrated:
            recovery_ms = round(max(
                next(t for t in tok_times[i] if t > t_kill) - t_kill
                for i in migrated
                if any(t > t_kill for t in tok_times[i])) * 1e3, 1)
        rows.append({
            "metric": "serve_fleet[recovery]",
            "value": recovery_ms, "unit": "ms kill->first survivor token",
            "replicas": counts[-1], "killed": victim,
            "migrated": len(migrated), "sessions": sessions,
            "completed": sum(len(ts) == kill_tokens for ts in tok_times),
            "errors": len(stream_errs),
            "steady_recompiles": fleet.stats()["steady_recompiles"],
        })
    finally:
        fleet.shutdown()
    return rows


def _probe_shape(model):
    try:
        return tuple(model.conf.input_type.shape(-1)[1:])
    except Exception:
        return (784,)
