"""Reusable benchmark configs mirroring BASELINE.md's table (LeNet-MNIST
step time, GravesLSTM char-RNN step time, Word2Vec words/sec).  The driver's
headline ResNet50 metric lives in ``bench.py``; these side metrics are
invoked from there (DL4J_TPU_BENCH_SIDE=1) and from ``tools/``.

All timings are steady-state: compile + warm step first, then ``n_iter``
timed steps closed with a forced device→host fetch (block_until_ready alone
can return early through buffer-proxying transports — BENCH_NOTES round 1).
"""
from __future__ import annotations

import time
from typing import Dict

import numpy as np


def _steady_step_ms(model, x, y, n_iter: int = 20) -> float:
    import jax
    import jax.numpy as jnp

    model.fit(x, y)           # compile + first step
    step = model._get_jitted("train_step")
    t0 = time.perf_counter()
    for _ in range(n_iter):
        model._rng, key = jax.random.split(model._rng)
        (model.params, model.state, model.opt_state, loss,
         model._last_grad_stats) = step(
            model.params, model.state, model.opt_state, key,
            x, y, None, None)
    float(jnp.asarray(loss))
    return (time.perf_counter() - t0) / n_iter * 1e3


def lenet_step_time(batch: int = 128, n_iter: int = 20) -> Dict:
    """LeNet-MNIST training step time (zoo ``model/LeNet.java:35``)."""
    import jax.numpy as jnp

    from ..models import LeNet
    model = LeNet().init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 28, 28, 1), dtype=np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, batch)])
    ms = _steady_step_ms(model, x, y, n_iter)
    return {"metric": "lenet_mnist_step_ms", "value": round(ms, 3),
            "unit": "ms/step", "batch": batch,
            "examples_per_sec": round(batch / ms * 1e3, 1)}


def char_lstm_step_time(batch: int = 128, timesteps: int = 64,
                        n_iter: int = 20) -> Dict:
    """Char-RNN step time (zoo ``model/TextGenerationLSTM.java:34``; the
    reference's cuDNN LSTM path, ``GravesLSTM.java:46``)."""
    import jax.numpy as jnp

    from ..models import TextGenerationLSTM
    model = TextGenerationLSTM(timesteps=timesteps).init()
    rng = np.random.default_rng(0)
    vocab = 26
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (batch, timesteps))])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (batch, timesteps))])
    ms = _steady_step_ms(model, x, y, n_iter)
    return {"metric": "char_lstm_step_ms", "value": round(ms, 3),
            "unit": "ms/step", "batch": batch, "timesteps": timesteps,
            "tokens_per_sec": round(batch * timesteps / ms * 1e3, 1)}


def word2vec_words_per_sec(vocab: int = 5000, n_sent: int = 20000,
                           sent_len: int = 20, epochs: int = 1) -> Dict:
    """Skip-gram NS throughput (parity bar: the reference's native batched
    AggregateSkipGram hot loop, ``SkipGram.java:271-283``).  Steady state:
    first fit compiles, second fit on reset weights is timed."""
    from ..nlp.word2vec import Word2Vec

    rng = np.random.default_rng(0)
    ids = np.clip(rng.zipf(1.3, size=n_sent * sent_len), 1, vocab) - 1
    toks = ["w%d" % i for i in ids]
    sentences = [" ".join(toks[i * sent_len:(i + 1) * sent_len])
                 for i in range(n_sent)]
    total = n_sent * sent_len * epochs
    w2v = Word2Vec(sentences=sentences, layer_size=128, window=5, negative=5,
                   epochs=epochs, seed=1, min_word_frequency=1)
    w2v.build_vocab()
    t0 = time.perf_counter()
    w2v.fit()
    cold = total / (time.perf_counter() - t0)
    w2v.lookup_table.reset_weights()
    t0 = time.perf_counter()
    w2v.fit()
    steady = total / (time.perf_counter() - t0)
    return {"metric": "word2vec_words_per_sec", "value": round(steady, 1),
            "unit": "words/sec", "cold_words_per_sec": round(cold, 1),
            "vocab": vocab, "corpus_words": total}
