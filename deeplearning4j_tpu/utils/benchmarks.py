"""Reusable benchmark configs mirroring BASELINE.md's table (LeNet-MNIST
step time, GravesLSTM char-RNN step time, Word2Vec words/sec).  The driver's
headline ResNet50 metric lives in ``bench.py``; these side metrics are
invoked from there (DL4J_TPU_BENCH_SIDE=1) and from ``tools/``.

All timings are steady-state: compile + warm step first, then ``n_iter``
timed steps closed with a forced device→host fetch (block_until_ready alone
can return early through buffer-proxying transports — BENCH_NOTES round 1).
"""
from __future__ import annotations

import time
from typing import Dict, List

import numpy as np


def _steady_step_ms(model, x, y, n_iter: int = 20, blocks: int = 3) -> float:
    """Median of ``blocks`` timed n_iter-step blocks — the tunnel's
    throughput drifts (observed 18-27 ms swings on identical LeNet steps),
    so a single block is not a stable artifact."""
    import jax
    import jax.numpy as jnp

    model.fit(x, y)           # compile + first step
    step = model._get_jitted("train_step")
    times = []
    for _ in range(blocks):
        t0 = time.perf_counter()
        for _ in range(n_iter):
            model._rng, key = jax.random.split(model._rng)
            (model.params, model.state, model.opt_state, loss,
             model._last_grad_stats) = step(
                model.params, model.state, model.opt_state, key,
                x, y, None, None)
        float(jnp.asarray(loss))
        times.append((time.perf_counter() - t0) / n_iter * 1e3)
    return float(np.median(times))


def lenet_step_time(batch: int = 128, n_iter: int = 20) -> Dict:
    """LeNet-MNIST training step time (zoo ``model/LeNet.java:35``)."""
    import jax.numpy as jnp

    from ..models import LeNet
    model = LeNet().init()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 28, 28, 1), dtype=np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, batch)])
    ms = _steady_step_ms(model, x, y, n_iter)
    return {"metric": "lenet_mnist_step_ms", "value": round(ms, 3),
            "unit": "ms/step", "batch": batch,
            "examples_per_sec": round(batch / ms * 1e3, 1)}


def char_lstm_step_time(batch: int = 128, timesteps: int = 64,
                        n_iter: int = 20) -> Dict:
    """Char-RNN step time (zoo ``model/TextGenerationLSTM.java:34``; the
    reference's cuDNN LSTM path, ``GravesLSTM.java:46``)."""
    import jax.numpy as jnp

    from ..models import TextGenerationLSTM
    model = TextGenerationLSTM(timesteps=timesteps).init()
    rng = np.random.default_rng(0)
    vocab = 26
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (batch, timesteps))])
    y = jnp.asarray(np.eye(vocab, dtype=np.float32)[
        rng.integers(0, vocab, (batch, timesteps))])
    ms = _steady_step_ms(model, x, y, n_iter)
    return {"metric": "char_lstm_step_ms", "value": round(ms, 3),
            "unit": "ms/step", "batch": batch, "timesteps": timesteps,
            "tokens_per_sec": round(batch * timesteps / ms * 1e3, 1)}


def _zipf_sentences(vocab: int, n_sent: int, sent_len: int):
    """Zipf(1.3)-distributed synthetic corpus shared by the embedding
    benchmarks, so word2vec and PV rows measure the same token stream."""
    rng = np.random.default_rng(0)
    ids = np.clip(rng.zipf(1.3, size=n_sent * sent_len), 1, vocab) - 1
    toks = ["w%d" % i for i in ids]
    return [" ".join(toks[i * sent_len:(i + 1) * sent_len])
            for i in range(n_sent)]


def _cold_steady_fit(model, total_words: int, runs: int = 3):
    """(cold, steady) words/sec: first fit compiles; steady is the MEDIAN
    of ``runs`` reset-weights re-fits — these benches are dispatch/host
    bound and swing ±40% run-to-run through the tunnel, so a single timed
    fit is not a stable artifact (all fits host-sync on the final tables)."""
    model.build_vocab()
    t0 = time.perf_counter()
    model.fit()
    cold = total_words / (time.perf_counter() - t0)
    rates = []
    for _ in range(runs):
        model.lookup_table.reset_weights()
        t0 = time.perf_counter()
        model.fit()
        rates.append(total_words / (time.perf_counter() - t0))
    return cold, float(np.median(rates))


def word2vec_words_per_sec(vocab: int = 5000, n_sent: int = 20000,
                           sent_len: int = 20, epochs: int = 1) -> Dict:
    """Skip-gram NS throughput (parity bar: the reference's native batched
    AggregateSkipGram hot loop, ``SkipGram.java:271-283``)."""
    from ..nlp.word2vec import Word2Vec

    sentences = _zipf_sentences(vocab, n_sent, sent_len)
    total = n_sent * sent_len * epochs
    w2v = Word2Vec(sentences=sentences, layer_size=128, window=5, negative=5,
                   epochs=epochs, seed=1, min_word_frequency=1)
    cold, steady = _cold_steady_fit(w2v, total)
    return {"metric": "word2vec_words_per_sec", "value": round(steady, 1),
            "unit": "words/sec", "cold_words_per_sec": round(cold, 1),
            "vocab": vocab, "corpus_words": total}


def paragraph_vectors_words_per_sec(vocab: int = 5000, n_docs: int = 20000,
                                    doc_len: int = 20, epochs: int = 1,
                                    seq_algo: str = "dbow") -> Dict:
    """Labeled-sequence (doc2vec) throughput — the bulk-path analogue of
    ``word2vec_words_per_sec`` with one unique label per document
    (reference: PV rides the same native aggregates,
    ``SkipGram.java:271-283``)."""
    from ..nlp.paragraph_vectors import ParagraphVectors
    from ..nlp.sentence_iterator import LabelledDocument

    docs = [LabelledDocument(s, ["DOC_%d" % i]) for i, s in
            enumerate(_zipf_sentences(vocab, n_docs, doc_len))]
    total = n_docs * doc_len * epochs
    pv = ParagraphVectors(documents=docs, sequence_algorithm=seq_algo,
                          layer_size=128, window=5, negative=5,
                          epochs=epochs, seed=1, min_word_frequency=1)
    cold, steady = _cold_steady_fit(pv, total)
    return {"metric": f"paragraph_vectors_{seq_algo}_words_per_sec",
            "value": round(steady, 1), "unit": "words/sec",
            "cold_words_per_sec": round(cold, 1), "vocab": vocab,
            "n_docs": n_docs, "corpus_words": total}


def transformer_lm_step_time(batch: int = 16, seq: int = 512,
                             embed: int = 512, n_layers: int = 8,
                             n_heads: int = 8, vocab: int = 8192,
                             impls=("auto", "flash", "reference"),
                             nbatch: int = 5, epochs: int = 2,
                             blocks: int = 3) -> List[Dict]:
    """TransformerLM train throughput + achieved TFLOP/s per attention impl
    (VERDICT r2 item 6 / r3 item 1: the beyond-reference tier measured like
    the parity tier).  Flops use the causal PaLM-style estimate
    6·T·(12·L·E² + E·V) matmul + 6·L·B·S²·E attention (fwd+bwd).

    Round-4 campaign form (BENCH_NOTES "transformer campaign"): sparse
    integer labels (the LM-natural target — one-hot reads an extra ~268 MB
    HBM/step at V=8192) and the device-resident epoch scan
    (``fit_on_device``, one dispatch per epoch) so the row measures the
    chip, not the tunnel's ~24-90 ms per-dispatch latency."""
    import jax.numpy as jnp

    from ..models import TransformerLM

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch * nbatch, seq + 1))
    x = jnp.asarray(ids[:, :-1])
    y = jnp.asarray(ids[:, 1:])
    tokens = batch * seq
    flops = (6 * tokens * (12 * n_layers * embed * embed + embed * vocab)
             + 6 * n_layers * batch * seq * seq * embed)
    steps = nbatch * epochs
    out = []
    for impl in impls:
        model = TransformerLM(vocab_size=vocab, seq_len=seq, embed=embed,
                              n_layers=n_layers, n_heads=n_heads,
                              attn_impl=impl, sparse_labels=True,
                              compute_dtype="bfloat16").init()
        model.fit_on_device(x, y, batch_size=batch, epochs=1)  # compile+warm
        times = []
        for _ in range(blocks):
            t0 = time.perf_counter()
            model.fit_on_device(x, y, batch_size=batch, epochs=epochs)
            times.append((time.perf_counter() - t0) / steps * 1e3)
        ms = float(np.median(times))
        out.append({
            "metric": f"transformer_lm_step_ms[{impl},s={seq}]",
            "value": round(ms, 3), "unit": "ms/step",
            "batch": batch, "seq": seq, "embed": embed,
            "n_layers": n_layers, "sparse_labels": True,
            "tokens_per_sec": round(tokens / ms * 1e3, 1),
            "achieved_tflops": round(flops / ms / 1e9, 2),
        })
    return out


# Calibration (BENCH_NOTES "tunnel health"): round-2 measured ~24 ms
# trivial-dispatch; this round measured ~90 ms on an otherwise-working
# tunnel, and the round-3 degraded window showed 3-5x metric inflation.
# Thresholds are deliberately loose — they flag "sick window", not drift.
PROBE_ROUNDTRIP_HEALTHY_MS = 200.0
PROBE_SPREAD_HEALTHY = 0.6


def tunnel_probe(n: int = 5) -> Dict:
    """Tunnel-health probe recorded beside every BENCH_SIDE row (VERDICT r3
    item 2): (a) trivial-dispatch roundtrip latency — a tiny jitted op plus
    a 512-byte host fetch; (b) a fixed 20-matmul device block timed ``n``
    times — its spread separates device/tunnel instability from honest
    load.  Rows carrying a probe let the next round distinguish a real
    regression from a degraded capture window without re-reading prose
    (the ``PerformanceListener.java:19`` role: measurements you can trust
    round-over-round)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros((1, 128), jnp.float32)
    float(np.asarray(f(x))[0, 0])                    # compile + settle
    lats = []
    for _ in range(n):
        t0 = time.perf_counter()
        float(np.asarray(f(x))[0, 0])
        lats.append(time.perf_counter() - t0)
    g = jax.jit(lambda a: a @ a)
    a = jnp.eye(1024, dtype=jnp.bfloat16)            # stable under chaining
    float(np.asarray(g(a)[0, 0]))                    # compile + settle
    blocks = []
    for _ in range(n):
        t0 = time.perf_counter()
        r = a
        for _ in range(20):
            r = g(r)
        float(np.asarray(r[0, 0]))                   # sync the whole chain
        blocks.append(time.perf_counter() - t0)
    med = float(np.median(blocks))
    probe = {
        "roundtrip_ms": round(float(np.median(lats)) * 1e3, 1),
        "block_ms": round(med * 1e3, 1),
        "block_spread": round((max(blocks) - min(blocks)) / med, 3),
    }
    probe["healthy"] = bool(
        probe["roundtrip_ms"] < PROBE_ROUNDTRIP_HEALTHY_MS
        and probe["block_spread"] < PROBE_SPREAD_HEALTHY)
    return probe
