"""Viterbi decoder — most-likely hidden-state path.

Reference ``deeplearning4j-nn/.../util/Viterbi.java`` (max-product decoding
over a label sequence).  TPU-native: the forward max-product recursion is a
``lax.scan`` over time with backpointers collected on-device; the backtrace
is a second (reversed) scan — one jitted program, no host loop.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Viterbi", "viterbi_decode"]


@jax.jit  # graftlint: disable=JX028  (viterbi decode kernel; NLP host path outside the audited program set)
def _decode(log_emissions: jax.Array, log_transitions: jax.Array,
            log_prior: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """log_emissions [t, s]; log_transitions [s, s] (row=from, col=to);
    log_prior [s].  Returns (path [t] int32, best_log_prob scalar)."""

    def step(alpha, emit):
        # alpha [s]: best log-prob ending in each state at t-1
        scores = alpha[:, None] + log_transitions  # [from, to]
        back = jnp.argmax(scores, axis=0)          # [to]
        alpha = jnp.max(scores, axis=0) + emit
        return alpha, back

    alpha0 = log_prior + log_emissions[0]
    alpha, backs = jax.lax.scan(step, alpha0, log_emissions[1:])
    last = jnp.argmax(alpha)

    def trace(state, back):
        return back[state], state

    first, rest = jax.lax.scan(trace, last, backs, reverse=True)
    path = jnp.concatenate([first[None], rest]).astype(jnp.int32)
    return path, alpha[last]


def viterbi_decode(emissions, transitions, prior=None, log_space: bool = False
                   ) -> Tuple[np.ndarray, float]:
    """Decode one sequence.  emissions [t, s] (probabilities, or log-probs
    with ``log_space=True``); transitions [s, s]; prior [s] (uniform when
    omitted).  Returns (state path [t], log-probability of the path)."""
    e = jnp.asarray(emissions, jnp.float32)
    tr = jnp.asarray(transitions, jnp.float32)
    s = e.shape[-1]
    p = (jnp.full((s,), 1.0 / s, jnp.float32) if prior is None
         else jnp.asarray(prior, jnp.float32))
    if not log_space:
        tiny = jnp.finfo(jnp.float32).tiny
        e, tr, p = (jnp.log(jnp.maximum(x, tiny)) for x in (e, tr, p))
    path, logp = _decode(e, tr, p)
    return np.asarray(path), float(logp)


class Viterbi:
    """Stateful facade (reference ``Viterbi.java``): fix the label set and
    transition structure once, decode many sequences (vmappable)."""

    def __init__(self, possible_labels, transitions=None, prior=None):
        self.labels = list(possible_labels)
        n = len(self.labels)
        if transitions is None:
            # reference default: strong self-transition bias
            transitions = np.full((n, n), 0.25 / max(n - 1, 1))
            np.fill_diagonal(transitions, 0.75)
        self.transitions = np.asarray(transitions, np.float32)
        self.prior = prior
        self._batched = jax.jit(jax.vmap(_decode, in_axes=(0, None, None)))  # graftlint: disable=JX028  (viterbi decode kernel; NLP host path outside the audited program set)

    def decode(self, emissions) -> Tuple[np.ndarray, float]:
        """[t, s] emissions → (labels [t], log-prob)."""
        path, logp = viterbi_decode(emissions, self.transitions, self.prior)
        return np.asarray([self.labels[i] for i in path]), logp

    def decode_batch(self, emissions) -> Tuple[np.ndarray, np.ndarray]:
        """[b, t, s] emissions → (paths [b, t] int32, log-probs [b])."""
        e = jnp.log(jnp.maximum(jnp.asarray(emissions, jnp.float32),
                                jnp.finfo(jnp.float32).tiny))
        tr = jnp.log(jnp.maximum(jnp.asarray(self.transitions),
                                 jnp.finfo(jnp.float32).tiny))
        n = len(self.labels)
        p = (jnp.full((n,), -np.log(n), jnp.float32) if self.prior is None
             else jnp.log(jnp.maximum(jnp.asarray(self.prior, jnp.float32),
                                      jnp.finfo(jnp.float32).tiny)))
        paths, logps = self._batched(e, tr, p)
        return np.asarray(paths), np.asarray(logps)
