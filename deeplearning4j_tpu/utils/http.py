"""Shared HTTP plumbing for the serving tier: JSON request/response handler
base with built-in observability (request count/latency/error-class metrics
per route and a ``/metrics`` exposition endpoint), background-thread server
lifecycle, and a JSON POST client."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.request import Request, urlopen

from ..observability import clock
from ..observability.exposition import CONTENT_TYPE, render_text
from ..observability.registry import default_registry

__all__ = ["JsonHandler", "MetricsEndpointMixin", "BackgroundHttpServer",
           "JsonClient"]

# request-latency buckets: local serving sits in the 1-100 ms band;
# keep a long tail for model (re)compiles hit by a first request
_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 10.0)


class MetricsEndpointMixin:
    """Serve the registry + observe per-route request metrics.

    Handlers bind ``metrics_registry`` (via ``BackgroundHttpServer``
    handler attrs) or fall back to the process-global default registry.
    ``GET /metrics`` renders Prometheus text format; ``GET
    /metrics?format=json`` returns the JSON snapshot.  Every response
    sent through ``_json``/``_serve_metrics`` records::

        http_requests_total{route,method,code}
        http_request_seconds{route}        (histogram)
        http_errors_total{route,class}     (class = client_error|server_error)

    Route labels are the matched path with query strings stripped; 404s
    collapse into one ``<unmatched>`` series so scrapes can't be
    cardinality-bombed by URL probing.
    """

    metrics_registry = None   # bound per-server; None -> default registry

    def _registry(self):
        return (self.metrics_registry if self.metrics_registry is not None
                else default_registry())

    def _route_label(self, code: int) -> str:
        if code == 404:
            return "<unmatched>"
        base = self.path.partition("?")[0].rstrip("/")
        return base or "/"

    def _observe_request(self, code: int) -> None:
        reg = self._registry()
        if not reg.enabled:
            return
        route = self._route_label(code)
        dur = clock.monotonic_s() - getattr(self, "_req_start_mono",
                                            clock.monotonic_s())
        reg.counter("http_requests_total", "HTTP requests served",
                    ("route", "method", "code")) \
           .labels(route, getattr(self, "command", "?") or "?",
                   str(code)).inc()
        reg.histogram("http_request_seconds", "HTTP request latency",
                      ("route",), buckets=_LATENCY_BUCKETS) \
           .labels(route).observe(dur)
        if code >= 400:
            cls = "server_error" if code >= 500 else "client_error"
            reg.counter("http_errors_total", "HTTP error responses",
                        ("route", "error_class")).labels(route, cls).inc()

    def _serve_metrics(self) -> bool:
        """Answer ``GET /metrics``; returns False when the path is not the
        metrics endpoint (caller continues its own routing)."""
        base, _, query = self.path.partition("?")
        if base.rstrip("/") != "/metrics":
            return False
        reg = self._registry()
        if "json" in query:
            self._json(reg.snapshot())
            return True
        payload = render_text(reg).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)
        self._observe_request(200)
        return True


class JsonHandler(MetricsEndpointMixin, BaseHTTPRequestHandler):
    """Quiet handler with JSON helpers; subclasses implement do_GET/do_POST."""

    def log_message(self, *a):
        pass

    def handle_one_request(self):
        # stamp BEFORE parsing so the latency histogram covers the whole
        # request (read + handle + write), not just the handler body
        self._req_start_mono = clock.monotonic_s()
        super().handle_one_request()

    def _json(self, obj, code: int = 200):
        payload = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)
        self._observe_request(code)

    def _read_json(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n))


class BackgroundHttpServer:
    """Owns a ThreadingHTTPServer on a daemon thread; binds the given handler
    class with extra attributes (the per-instance state the handler needs)."""

    def __init__(self, handler_base, port: int = 0, **handler_attrs):
        handler = type(f"Bound{handler_base.__name__}", (handler_base,),
                       dict(handler_attrs))
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "BackgroundHttpServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class JsonClient:
    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def post(self, route: str, body: dict) -> dict:
        req = Request(self.url + route, data=json.dumps(body).encode(),
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def get(self, route: str) -> dict:
        with urlopen(self.url + route, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def get_text(self, route: str) -> str:
        """Raw body fetch (the Prometheus /metrics exposition is not JSON)."""
        with urlopen(self.url + route, timeout=self.timeout) as resp:
            return resp.read().decode("utf-8")
