"""Shared HTTP plumbing for the serving tier: JSON request/response handler
base, background-thread server lifecycle, and a JSON POST client."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.request import Request, urlopen

__all__ = ["JsonHandler", "BackgroundHttpServer", "JsonClient"]


class JsonHandler(BaseHTTPRequestHandler):
    """Quiet handler with JSON helpers; subclasses implement do_GET/do_POST."""

    def log_message(self, *a):
        pass

    def _json(self, obj, code: int = 200):
        payload = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _read_json(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n))


class BackgroundHttpServer:
    """Owns a ThreadingHTTPServer on a daemon thread; binds the given handler
    class with extra attributes (the per-instance state the handler needs)."""

    def __init__(self, handler_base, port: int = 0, **handler_attrs):
        handler = type(f"Bound{handler_base.__name__}", (handler_base,),
                       dict(handler_attrs))
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "BackgroundHttpServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class JsonClient:
    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def post(self, route: str, body: dict) -> dict:
        req = Request(self.url + route, data=json.dumps(body).encode(),
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def get(self, route: str) -> dict:
        with urlopen(self.url + route, timeout=self.timeout) as resp:
            return json.loads(resp.read())
