"""Shared HTTP plumbing for the serving tier: JSON request/response handler
base with built-in observability (request count/latency/error-class metrics
per route and a ``/metrics`` exposition endpoint), background-thread server
lifecycle with bounded handler concurrency, and a keep-alive JSON client.

Concurrency model: ``ThreadingHTTPServer`` spawns one thread per
connection with no cap — under a connection flood that is an unbounded
thread (and memory) blowup.  ``BackgroundHttpServer`` bounds BOTH
resources, because keep-alive makes them distinct: ``max_concurrent``
caps requests being *handled* at once (an over-cap request gets a proper
``503 + Retry-After`` on its own connection, which stays open — an idle
pooled connection never holds a handling slot), while a higher
connection cap (default ``4 x max_concurrent``) bounds handler *threads*
against raw connection floods with a minimal socket-level 503 before any
thread spawns.  ``http_inflight_requests`` (requests mid-handler) and
``http_shed_total{scope=request|connection}`` make the pressure
scrape-visible.

``JsonClient`` holds one persistent ``http.client.HTTPConnection`` per
calling thread (keep-alive), with a single bounded reconnect when a
pooled connection turns out stale (server restarted, idle timeout) —
so a concurrency bench measures the server, not TCP handshakes."""
from __future__ import annotations

import http.client
import io
import json
import socket
import threading
import urllib.error
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from ..observability import clock
from ..observability.exposition import CONTENT_TYPE, render_text
from ..observability.registry import default_registry

__all__ = ["JsonHandler", "MetricsEndpointMixin", "PredictCircuitMixin",
           "BackgroundHttpServer", "JsonClient"]


class PredictCircuitMixin:
    """Consecutive-failure readiness circuit shared by the serving
    front-ends: a streak of model-side predict failures flips /health
    unready until one success.  ONE implementation — the two servers
    must never diverge on circuit semantics.  Handler threads report
    outcomes concurrently, so the lock keeps failure streaks lossless
    (N racing ``+=`` must reach the circuit threshold, not lose
    increments)."""

    def _init_predict_circuit(self) -> None:
        self.consecutive_failures = 0
        self.last_predict_mono: Optional[float] = None
        self._health_lock = threading.Lock()

    def note_predict_result(self, ok: bool) -> None:
        """Record one predict outcome from a handler thread."""
        with self._health_lock:
            if ok:
                self.consecutive_failures = 0
                self.last_predict_mono = clock.monotonic_s()
            else:
                self.consecutive_failures += 1

# request-latency buckets: local serving sits in the 1-100 ms band;
# keep a long tail for model (re)compiles hit by a first request
_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 10.0)


class MetricsEndpointMixin:
    """Serve the registry + observe per-route request metrics.

    Handlers bind ``metrics_registry`` (via ``BackgroundHttpServer``
    handler attrs) or fall back to the process-global default registry.
    ``GET /metrics`` renders Prometheus text format; ``GET
    /metrics?format=json`` returns the JSON snapshot.  Every response
    sent through ``_json``/``_serve_metrics`` records::

        http_requests_total{route,method,code}
        http_request_seconds{route}        (histogram)
        http_errors_total{route,class}     (class = client_error|server_error)

    Route labels are the matched path with query strings stripped; 404s
    collapse into one ``<unmatched>`` series so scrapes can't be
    cardinality-bombed by URL probing.
    """

    metrics_registry = None   # bound per-server; None -> default registry

    def _registry(self):
        return (self.metrics_registry if self.metrics_registry is not None
                else default_registry())

    def _route_label(self, code: int) -> str:
        if code == 404:
            return "<unmatched>"
        base = self.path.partition("?")[0].rstrip("/")
        return base or "/"

    def _observe_request(self, code: int) -> None:
        reg = self._registry()
        if not reg.enabled:
            return
        route = self._route_label(code)
        dur = clock.monotonic_s() - getattr(self, "_req_start_mono",
                                            clock.monotonic_s())
        reg.counter("http_requests_total", "HTTP requests served",
                    ("route", "method", "code")) \
           .labels(route, getattr(self, "command", "?") or "?",
                   str(code)).inc()
        reg.histogram("http_request_seconds", "HTTP request latency",
                      ("route",), buckets=_LATENCY_BUCKETS) \
           .labels(route).observe(dur)
        if code >= 400:
            cls = "server_error" if code >= 500 else "client_error"
            reg.counter("http_errors_total", "HTTP error responses",
                        ("route", "error_class")).labels(route, cls).inc()

    def _serve_flightrecorder(self) -> bool:
        """Answer ``GET /debug/flightrecorder``; returns False when the
        path is not the flight-recorder endpoint (caller continues its
        own routing).  Plain GET returns the live in-memory window
        (channels, spans, metric snapshots); ``?dump=1`` additionally
        commits it to an atomic checksummed artifact and returns the
        path — the manual trigger for "grab me the evidence NOW".
        ONE implementation on the mixin so every server that exposes
        ``/metrics`` exposes the same forensics route."""
        base, _, query = self.path.partition("?")
        if base.rstrip("/") != "/debug/flightrecorder":
            return False
        from ..observability.recorder import get_flight_recorder
        rec = get_flight_recorder()
        if rec is None or not rec.enabled:
            self._json({"enabled": False,
                        "error": "no flight recorder installed"}, 503)
            return True
        # dump only on an affirmative value: writing an artifact is a
        # side effect, so ?dump=0 / ?dump=false must stay the live view
        dump_vals = parse_qs(query).get("dump", [])
        if dump_vals and dump_vals[-1].lower() not in ("0", "false", "no", ""):
            try:
                path = rec.dump("manual")
            except Exception as e:
                self._json({"ok": False, "error": str(e)}, 500)
                return True
            self._json({"ok": True, "path": path})
            return True
        self._json(rec.view())
        return True

    def _serve_profile(self) -> bool:
        """Answer ``GET /debug/profile``; returns False when the path is
        not the step-profiler endpoint (caller continues its own
        routing).  Plain GET returns the live ``profile``-channel window
        (per-step phase records, serve/decode slices) plus the phase
        summary; ``?dump=1`` additionally commits a checksummed
        Chrome-trace artifact (``chrome://tracing`` / Perfetto loadable)
        and returns the path.  ONE implementation on the mixin — both
        servers expose identical profiling forensics."""
        base, _, query = self.path.partition("?")
        if base.rstrip("/") != "/debug/profile":
            return False
        from ..observability import profiler as stepprof
        from ..observability.recorder import get_flight_recorder
        rec = get_flight_recorder()
        if rec is None or not rec.enabled:
            self._json({"enabled": False,
                        "error": "no flight recorder installed"}, 503)
            return True
        # dump only on an affirmative value (side effect: writes a file)
        dump_vals = parse_qs(query).get("dump", [])
        if dump_vals and dump_vals[-1].lower() not in ("0", "false", "no", ""):
            try:
                path = stepprof.dump_chrome_trace(recorder=rec)
            except Exception as e:
                self._json({"ok": False, "error": str(e)}, 500)
                return True
            self._json({"ok": True, "path": path})
            return True
        records = rec.channel(stepprof.CHANNEL).items()
        self._json({"enabled": stepprof.stepprof_enabled(),
                    "records": records,
                    "summary": stepprof.phase_summary(records)})
        return True

    def _serve_metrics(self) -> bool:
        """Answer ``GET /metrics``; returns False when the path is not the
        metrics endpoint (caller continues its own routing)."""
        base, _, query = self.path.partition("?")
        if base.rstrip("/") != "/metrics":
            return False
        reg = self._registry()
        if "json" in query:
            self._json(reg.snapshot())
            return True
        payload = render_text(reg).encode("utf-8")
        try:
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True
            return True
        self._observe_request(200)
        return True


class JsonHandler(MetricsEndpointMixin, BaseHTTPRequestHandler):
    """Quiet handler with JSON helpers; subclasses implement do_GET/do_POST.

    HTTP/1.1 so keep-alive clients (``JsonClient``'s per-thread pooled
    connections) reuse one socket across requests; every response path
    here sends ``Content-Length``, which 1.1 persistence requires.  Idle
    connections are dropped after ``timeout`` so abandoned sockets can't
    pin handler threads (and concurrency-cap slots) forever."""

    protocol_version = "HTTP/1.1"
    timeout = 65

    def log_message(self, *a):
        pass

    def _request_gauge(self):
        return self._registry().gauge(
            "http_inflight_requests",
            "Requests currently being handled (capped at max_concurrent)")

    def parse_request(self):
        ok = super().parse_request()
        if not ok:
            return False
        # per-REQUEST concurrency slot: taken after a full request line
        # arrives (an idle keep-alive connection holds nothing), shed
        # in-protocol so the client's pooled connection survives the 503
        slots = getattr(self.server, "request_slots", None)
        if slots is not None:
            if not slots.acquire(blocking=False):
                self.server.count_shed("request")
                self._json({"error": "server at concurrency cap"}, 503,
                           headers={"Retry-After": "1"})
                return False
            self._slot_held = True
            if self._registry().enabled:
                self._request_gauge().inc()
        return True

    def handle_one_request(self):
        # stamp BEFORE parsing so the latency histogram covers the whole
        # request (read + handle + write), not just the handler body
        self._req_start_mono = clock.monotonic_s()
        self._slot_held = False
        self._body_read = False
        try:
            super().handle_one_request()
        except (ConnectionResetError, BrokenPipeError):
            # a client tearing down its socket between keep-alive
            # requests (an abandoned generation stream's dedicated
            # connection, a killed client) is routine under load — end
            # the handler quietly instead of stack-tracing per socket
            self.close_connection = True
        finally:
            if self._slot_held:
                self._slot_held = False
                self.server.request_slots.release()
                if self._registry().enabled:
                    self._request_gauge().dec()

    # largest request body worth draining to keep a connection alive; a
    # bigger one is cheaper to abandon than to read
    _DRAIN_CAP = 1 << 20

    def _drain_unread_body(self) -> None:
        """Consume an unread request body before responding.  HTTP/1.1
        keep-alive makes this mandatory: a response sent with body bytes
        still in the socket (shed 503s, 404 routes) would desync the
        client's pooled connection — the leftover body parses as the next
        request line.  Oversized bodies close the connection instead."""
        if getattr(self, "_body_read", False):
            return
        self._body_read = True
        try:
            n = int(self.headers.get("Content-Length", 0) or 0)
        except (TypeError, ValueError):
            n = 0
        if n <= 0:
            return
        if n > self._DRAIN_CAP:
            self.close_connection = True
            return
        try:
            self.rfile.read(n)
        except OSError:
            self.close_connection = True

    def _json(self, obj, code: int = 200, headers: Optional[dict] = None):
        self._drain_unread_body()     # keep-alive: never strand body bytes
        payload = json.dumps(obj).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            for k, v in (headers or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            # the client gave up (timeout under overload) — a dead socket
            # is routine there, not a handler error worth a stack trace
            self.close_connection = True
            return
        self._observe_request(code)

    def _read_body(self) -> bytes:
        """Read the request body.  ALWAYS consume the body through this
        (or ``_read_json``) rather than ``self.rfile`` directly — it
        marks the body consumed so the keep-alive drain in ``_json``
        doesn't block re-reading bytes that are already gone."""
        self._body_read = True
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n)

    def _read_json(self):
        return json.loads(self._read_body())

    def _stream_json_lines(self, events) -> bool:
        """Send a chunked HTTP/1.1 response of newline-delimited JSON
        objects, one chunk per event, flushed as produced — the
        token-streaming transport for ``POST /generate``.  Chunked
        framing keeps the connection keep-alive-clean (the client knows
        where the stream ends without a Content-Length).  Returns False
        when the client went away mid-stream (dead sockets are routine
        for an abandoned generation — the caller cancels the work, no
        stack trace)."""
        self._drain_unread_body()
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            for ev in events:
                data = (json.dumps(ev) + "\n").encode()
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True
            return False
        self._observe_request(200)
        return True


# connection-level shed response: written straight to the socket before
# any handler thread exists, so a flood can't allocate per-request state
_SHED_BODY = b'{"error": "server at concurrency cap"}'
_SHED_RESPONSE = (b"HTTP/1.1 503 Service Unavailable\r\n"
                  b"Retry-After: 1\r\n"
                  b"Content-Type: application/json\r\n"
                  b"Content-Length: " + str(len(_SHED_BODY)).encode() +
                  b"\r\nConnection: close\r\n\r\n" + _SHED_BODY)


class _BoundedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a request-handling cap and a connection
    (thread) cap.

    ``request_slots`` (``max_concurrent``) is taken per REQUEST by the
    handler (see ``JsonHandler.parse_request``) — keep-alive connections
    idling between requests hold no slot, and an over-cap request gets a
    proper in-protocol 503 + Retry-After.  The connection cap bounds
    handler threads themselves: past it, the accepted socket gets a raw
    503 and closes before any thread spawns (flood containment).
    """

    metrics_registry = None

    def __init__(self, addr, handler, max_concurrent: int,
                 max_connections: Optional[int] = None):
        self.max_concurrent = int(max_concurrent)
        self.max_connections = int(max_connections) if max_connections \
            else max(4 * self.max_concurrent, 64)
        self.request_slots = threading.BoundedSemaphore(self.max_concurrent)
        self._conn_slots = threading.BoundedSemaphore(self.max_connections)
        super().__init__(addr, handler)

    def _registry(self):
        reg = getattr(self, "metrics_registry", None)
        return reg if reg is not None else default_registry()

    def count_shed(self, scope: str) -> None:
        reg = self._registry()
        if reg.enabled:
            reg.counter("http_shed_total",
                        "Requests/connections shed at a concurrency cap "
                        "(503 + Retry-After)", ("scope",)
                        ).labels(scope).inc()

    def process_request(self, request, client_address):
        if not self._conn_slots.acquire(blocking=False):
            self.count_shed("connection")
            try:
                request.sendall(_SHED_RESPONSE)
            except OSError:
                pass
            self.shutdown_request(request)
            return
        super().process_request(request, client_address)

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            self._conn_slots.release()


class BackgroundHttpServer:
    """Owns a bounded ThreadingHTTPServer on a daemon thread; binds the
    given handler class with extra attributes (the per-instance state the
    handler needs).  ``max_concurrent`` caps requests being handled at
    once (in-protocol 503 + Retry-After past it); ``max_connections``
    (default 4x) caps handler threads against connection floods."""

    def __init__(self, handler_base, port: int = 0,
                 max_concurrent: int = 64,
                 max_connections: Optional[int] = None, **handler_attrs):
        handler = type(f"Bound{handler_base.__name__}", (handler_base,),
                       dict(handler_attrs))
        self.httpd = _BoundedThreadingHTTPServer(
            ("127.0.0.1", port), handler, max_concurrent=max_concurrent,
            max_connections=max_connections)
        # the shed path and the inflight gauge report into the same
        # registry the handlers bind
        self.httpd.metrics_registry = handler_attrs.get("metrics_registry")
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "BackgroundHttpServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        # BaseServer.shutdown() blocks on an event that only
        # serve_forever() sets on exit — calling it on a never-started
        # server would hang forever, so it only runs when the serve
        # thread exists.  Joining it stops new ACCEPTS; per-connection
        # handler threads are daemon and untracked, so a request already
        # executing may still be mid-flight after stop() returns —
        # teardown that mutates handler-visible state must tolerate that
        if self._thread is not None:
            self.httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self.httpd.server_close()


class JsonClient:
    """JSON-over-HTTP client with per-thread persistent connections.

    One ``http.client.HTTPConnection`` (or ``HTTPSConnection`` for
    ``https://`` URLs) per calling thread, reused across requests
    (keep-alive).  A stale pooled connection — the server restarted or
    closed the idle socket — gets ONE bounded reconnect, and only when a
    retry cannot double-execute: the failure happened while SENDING on a
    reused connection (nothing reached the server), or the method is an
    idempotent GET.  A POST whose bytes may have been delivered (send
    succeeded but the response failed, or any timeout) always propagates
    the error — serving requests are not assumed idempotent.  Error
    responses raise :class:`urllib.error.HTTPError` with
    ``.code``/``.headers``, matching the previous ``urlopen`` behavior
    callers already handle."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout
        parts = urlsplit(self.url if "//" in self.url
                         else "http://" + self.url)
        self._https = parts.scheme == "https"
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or (443 if self._https else 80)
        # base-URL path prefix (reverse proxy / mounted sub-path) rides
        # in front of every route, matching the old urlopen(url + route)
        self._base_path = parts.path.rstrip("/")
        self._tls = threading.local()

    # ------------------------------------------------------- connection pool
    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            cls = http.client.HTTPSConnection if self._https \
                else http.client.HTTPConnection
            conn = cls(self._host, self._port, timeout=self.timeout)
            self._tls.conn = conn
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._tls, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        self._tls.conn = None

    def close(self) -> None:
        """Close this thread's pooled connection (idle cleanup)."""
        self._drop_conn()

    # -------------------------------------------------------------- requests
    def _request(self, method: str, route: str,
                 body: Optional[bytes] = None) -> bytes:
        headers = {"Content-Type": "application/json"} if body else {}
        for attempt in (0, 1):
            reused = getattr(self._tls, "conn", None) is not None
            conn = self._conn()
            sent = False
            try:
                conn.request(method, self._base_path + route, body=body,
                             headers=headers)
                sent = True               # bytes may now be at the server
                resp = conn.getresponse()
                data = resp.read()        # drain fully: keeps the socket
            except socket.timeout:        # reusable for the next request
                self._drop_conn()
                raise                     # possibly delivered: never retried
            except (http.client.HTTPException, ConnectionError, OSError):
                self._drop_conn()
                # ONE reconnect, only when it cannot double-execute: a
                # send-phase failure on a REUSED (stale keep-alive) socket
                # never reached the server, and GETs are idempotent.  A
                # POST that failed after sending propagates — the server
                # may already be acting on it.
                retriable = reused and (not sent or method == "GET")
                if attempt or not retriable:
                    raise
                continue
            if resp.will_close:
                self._drop_conn()
            if resp.status >= 400:
                raise urllib.error.HTTPError(
                    self.url + route, resp.status, resp.reason,
                    resp.headers, io.BytesIO(data))
            return data
        raise RuntimeError("unreachable")  # pragma: no cover

    def post(self, route: str, body: dict) -> dict:
        return json.loads(self._request(
            "POST", route, json.dumps(body).encode()))

    def stream_lines(self, route: str, body: dict):
        """POST and yield newline-delimited JSON objects as they arrive
        (the chunked streaming responses ``_stream_json_lines`` sends).
        Uses a DEDICATED connection, not the keep-alive pool: a stream
        can outlive many pooled requests, and abandoning one mid-body
        must never leave a desynced socket behind for the next caller —
        closing the private connection also signals the server the
        client is gone (it cancels the work)."""
        cls = http.client.HTTPSConnection if self._https \
            else http.client.HTTPConnection
        conn = cls(self._host, self._port, timeout=self.timeout)
        try:
            conn.request("POST", self._base_path + route,
                         body=json.dumps(body).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status >= 400:
                data = resp.read()
                raise urllib.error.HTTPError(
                    self.url + route, resp.status, resp.reason,
                    resp.headers, io.BytesIO(data))
            for line in resp:
                line = line.strip()
                if line:
                    yield json.loads(line)
        finally:
            conn.close()

    def get(self, route: str) -> dict:
        return json.loads(self._request("GET", route))

    def get_text(self, route: str) -> str:
        """Raw body fetch (the Prometheus /metrics exposition is not JSON)."""
        return self._request("GET", route).decode("utf-8")
