"""Time-series utilities.

Reference ``deeplearning4j-nn/.../util/TimeSeriesUtils.java`` (mask
reshaping, last-time-step extraction, time-axis reversal) — array helpers
shared by the recurrent stack and evaluation.  All functions are
jit-friendly (pure jnp/numpy, static shapes).
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["reverse_time_series", "get_last_time_step",
           "moving_window_matrix", "reshape_time_series_mask"]


def reverse_time_series(x, mask=None):
    """Reverse [b, t, f] along time; with a mask, each sequence reverses
    within its own valid length (reference ``reverseTimeSeries``) so
    padding stays at the end."""
    x = jnp.asarray(x)
    if mask is None:
        return x[:, ::-1]
    mask = jnp.asarray(mask)
    t = x.shape[1]
    lengths = jnp.sum(mask > 0, axis=1).astype(jnp.int32)      # [b]
    idx = jnp.arange(t)[None, :]                               # [1, t]
    src = lengths[:, None] - 1 - idx                           # [b, t]
    src = jnp.where(src >= 0, src, idx)                        # padding stays
    return jnp.take_along_axis(x, src[:, :, None], axis=1)


def get_last_time_step(x, mask=None):
    """[b, t, f] -> [b, f] at each sequence's final VALID step (reference
    ``pullLastTimeSteps``)."""
    x = jnp.asarray(x)
    if mask is None:
        return x[:, -1]
    lengths = jnp.sum(jnp.asarray(mask) > 0, axis=1).astype(jnp.int32)
    idx = jnp.maximum(lengths - 1, 0)
    return jnp.take_along_axis(
        x, idx[:, None, None].repeat(x.shape[-1], -1), axis=1)[:, 0]


def moving_window_matrix(x, window: int, stride: int = 1) -> np.ndarray:
    """[t, f] -> [n_windows, window, f] sliding views (reference
    ``MovingWindowMatrix``)."""
    x = np.asarray(x)
    t = x.shape[0]
    if window > t:
        raise ValueError(f"window {window} exceeds length {t}")
    starts = range(0, t - window + 1, stride)
    return np.stack([x[s:s + window] for s in starts])


def reshape_time_series_mask(mask, n_features: int):
    """Per-timestep mask [b, t] -> flattened per-example mask
    [b*t, n_features] for 2-D losses (reference
    ``reshapeTimeSeriesMaskToVector``)."""
    m = jnp.asarray(mask).reshape(-1)
    return jnp.repeat(m[:, None], n_features, axis=1)
