"""One-time logging (reference ``util/OneTimeLogger.java``): emit a given
message at most once per process — for hot-loop warnings."""
from __future__ import annotations

import logging
import threading

__all__ = ["info_once", "warn_once", "reset_once"]

_seen = set()
_lock = threading.Lock()


def _once(level: int, logger: logging.Logger, msg: str, *args) -> bool:
    key = (logger.name, level, msg)
    with _lock:
        if key in _seen:
            return False
        _seen.add(key)
    logger.log(level, msg, *args)
    return True


def info_once(logger: logging.Logger, msg: str, *args) -> bool:
    return _once(logging.INFO, logger, msg, *args)


def warn_once(logger: logging.Logger, msg: str, *args) -> bool:
    return _once(logging.WARNING, logger, msg, *args)


def reset_once() -> None:
    with _lock:
        _seen.clear()
