"""Load any saved artifact by sniffing its format (reference
``deeplearning4j-core/.../util/ModelGuesser.java``): model zips (MLN or
ComputationGraph), word-vector files, and stats logs."""
from __future__ import annotations

import json
import os
import zipfile
from typing import Any

__all__ = ["guess_format", "load_model_guess"]


def guess_format(path: str) -> str:
    """Returns one of: 'multi_layer_network', 'computation_graph',
    'word_vectors', 'stats_log', 'unknown'."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path, "rb") as fh:
        head = fh.read(8)
    if head == b"DL4JTPU1":
        return "stats_log"
    if head[:2] == b"PK":  # zip container
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
            if "metadata.json" in names and "configuration.json" in names:
                try:
                    cls = json.loads(zf.read("metadata.json")).get(
                        "net_class", "")
                except Exception:
                    cls = ""
                if "Graph" in cls:
                    return "computation_graph"
                return "multi_layer_network"
        return "unknown"
    # word2vec text format: "<vocab> <dim>" header then "token floats..."
    try:
        with open(path, "r", errors="strict") as fh:
            first = fh.readline().split()
            if len(first) == 2 and first[0].isdigit() and first[1].isdigit():
                return "word_vectors"
            if len(first) > 2:
                float(first[1])
                return "word_vectors"
    except (UnicodeDecodeError, ValueError, IndexError):
        pass
    return "unknown"


def load_model_guess(path: str) -> Any:
    """Sniff + load (reference ``ModelGuesser.loadModelGuess``)."""
    kind = guess_format(path)
    if kind in ("multi_layer_network", "computation_graph"):
        from .model_serializer import restore_model
        return restore_model(path)
    if kind == "word_vectors":
        # full sniffing loader: txt/csv/binary/gzip variants
        from ..nlp.serializer import load_static_model
        return load_static_model(path)
    if kind == "stats_log":
        from ..ui.storage import FileStatsStorage
        return FileStatsStorage(path)
    raise ValueError(f"cannot determine artifact format of {path}")
