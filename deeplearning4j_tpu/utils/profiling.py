"""Profiling hooks: XLA trace capture + device memory reports.

Reference tracing (SURVEY §5): ``PerformanceListener`` wall-clock counters +
external ND4J ``OpProfiler``.  The TPU equivalents are the XLA profiler
(Xprof traces viewable in TensorBoard/Perfetto) and device memory
introspection — surfaced here as a listener that brackets a chosen
iteration window, plus small functional helpers.
"""
from __future__ import annotations

import contextlib
import logging
from typing import Optional

import jax

from ..train.listeners import TrainingListener

log = logging.getLogger("deeplearning4j_tpu.profiling")

__all__ = ["ProfilerListener", "trace_annotation", "device_memory_stats",
           "device_platform"]


def device_platform() -> str:
    """Backend platform of the default device ("cpu"/"gpu"/"tpu"), or
    "unknown" when no backend is reachable — the serving tier's /health
    readiness reports ride this."""
    try:
        return jax.devices()[0].platform
    except Exception:
        return "unknown"


class ProfilerListener(TrainingListener):
    """Capture an XLA trace for iterations [start, start+num) into
    ``log_dir`` (open with TensorBoard's profile plugin or Perfetto).
    The first iterations are compile-heavy, so ``start_iteration``
    defaults past them."""

    def __init__(self, log_dir: str, start_iteration: int = 3,
                 num_iterations: int = 3):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.end_iteration = start_iteration + num_iterations
        self._active = False
        self.captured = False

    def iteration_done(self, model, iteration, epoch):
        if not self._active and not self.captured and \
                iteration >= self.start_iteration:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            log.info("XLA trace started at iteration %d -> %s",
                     iteration, self.log_dir)
        elif self._active and iteration >= self.end_iteration:
            self.stop()

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self.captured = True
            log.info("XLA trace written to %s", self.log_dir)

    def on_epoch_end(self, model):
        # never leave a trace running across epochs
        self.stop()


@contextlib.contextmanager
def trace_annotation(name: str):
    """Label a host-side region so it shows up on the Xprof timeline
    (ETL, checkpointing, eval — the reference's StatsCalculationHelper
    phase-timing role).  For spans that should ALSO land in the metrics
    registry / event log, use ``observability.Tracer(bridge_xprof=True)``
    — its spans wrap the same TraceAnnotation."""
    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_stats(device=None) -> Optional[dict]:
    """Live HBM usage for one device: {bytes_in_use, peak_bytes_in_use,
    bytes_limit} — None when the backend doesn't expose it (CPU)."""
    d = device or jax.devices()[0]
    stats = getattr(d, "memory_stats", None)
    if stats is None:
        return None
    try:
        s = d.memory_stats()
    except Exception:
        return None
    if not s:
        return None
    return {k: s[k] for k in ("bytes_in_use", "peak_bytes_in_use",
                              "bytes_limit") if k in s}
