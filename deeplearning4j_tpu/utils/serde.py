"""JSON/YAML serialization for config dataclasses.

Plays the role Jackson plays in the reference (``nn/conf/serde/``,
``MultiLayerConfiguration.toJson/fromJson`` at
``nn/conf/MultiLayerConfiguration.java:120,138``): every config object
round-trips through plain JSON with an ``@class`` tag, and deserialization is
version-tolerant — unknown fields are dropped with a warning rather than
failing, mirroring the reference's legacy-format deserializers.
"""
from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any, Dict, Type

log = logging.getLogger(__name__)

_CLASS_REGISTRY: Dict[str, Type] = {}


def register_serde(cls):
    """Class decorator: make a dataclass JSON round-trippable by @class tag."""
    _CLASS_REGISTRY[cls.__name__] = cls
    return cls


def lookup_class(name: str):
    return _CLASS_REGISTRY.get(name)


def to_jsonable(obj: Any) -> Any:
    """Recursively convert registered dataclasses / containers to JSON-able."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (list, tuple)):
        return [to_jsonable(v) for v in obj]
    if isinstance(obj, dict):
        return {k: to_jsonable(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        d = {"@class": type(obj).__name__}
        for f in dataclasses.fields(obj):
            d[f.name] = to_jsonable(getattr(obj, f.name))
        return d
    # numpy / jax scalars
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    raise TypeError(f"cannot serialize {type(obj)}")


def from_jsonable(d: Any) -> Any:
    """Inverse of to_jsonable. Unknown fields are ignored (version tolerance)."""
    if isinstance(d, list):
        return [from_jsonable(v) for v in d]
    if isinstance(d, dict):
        if "@class" in d:
            name = d["@class"]
            cls = _CLASS_REGISTRY.get(name)
            if cls is None:
                raise ValueError(f"unknown @class '{name}' in config json")
            field_names = {f.name for f in dataclasses.fields(cls)}
            kwargs = {}
            for k, v in d.items():
                if k == "@class":
                    continue
                if k not in field_names:
                    log.warning("dropping unknown field %s.%s during deserialization",
                                name, k)
                    continue
                kwargs[k] = from_jsonable(v)
            obj = cls(**kwargs)
            return obj
        return {k: from_jsonable(v) for k, v in d.items()}
    return d


def to_json(obj: Any, indent: int = 2) -> str:
    return json.dumps(to_jsonable(obj), indent=indent)


def from_json(s: str) -> Any:
    return from_jsonable(json.loads(s))


def to_yaml(obj: Any) -> str:
    try:
        import yaml
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("pyyaml not available") from e
    return yaml.safe_dump(to_jsonable(obj), sort_keys=False)


def from_yaml(s: str) -> Any:
    try:
        import yaml
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("pyyaml not available") from e
    return from_jsonable(yaml.safe_load(s))
