"""Numerical gradient checking — the framework's primary correctness oracle.

Analogue of ``gradientcheck/GradientCheckUtil.java:112`` (central-difference
loop :207-222): compare analytic gradients (here ``jax.grad`` over the whole
network loss) against central differences in float64, with per-parameter
relative-error thresholds.  Used by the test suite exactly as the reference's
13 gradient-check suites use GradientCheckUtil.

Runs in float64 (enable via ``jax.config.update('jax_enable_x64', True)`` in
the test conftest) on small nets — same recipe as the reference (double
precision, exact thresholds).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(net, x, y, *, epsilon: float = 1e-6,
                    max_rel_error: float = 1e-3, min_abs_error: float = 1e-8,
                    mask=None, label_mask=None, print_results: bool = False,
                    subset: Optional[int] = None, seed: int = 12345,
                    exclude: tuple = ("centers",)) -> bool:
    """Check d(loss)/d(params) for a MultiLayerNetwork (or compatible).

    subset: if set, check only this many randomly-chosen parameters per layer
    (the reference checks all params of small nets; subset keeps big nets fast).
    """
    if not net.params:
        net.init()
    params = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float64), net.params)
    state = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float64) if jnp.issubdtype(a.dtype, jnp.floating) else a,
        net.state)
    x = jnp.asarray(x, jnp.float64)
    y = jnp.asarray(y, jnp.float64)

    @jax.jit  # graftlint: disable=JX028  (f64 finite-difference probe; cold diagnostic path, never steady-state)
    def loss_fn(p):
        # train=False: dropout/noise off; BN uses batch stats only if training,
        # reference gradient checks also disable stochastic regularization.
        loss, _ = net._loss(p, state, x, y, train=False, key=None,
                            mask=mask, label_mask=label_mask)
        return loss

    analytic = jax.grad(loss_fn)(params)
    return _check_gradients_impl(loss_fn, params, analytic, epsilon,
                                 max_rel_error, min_abs_error, print_results,
                                 subset, seed, exclude)


def _check_gradients_impl(loss_fn, params, analytic, epsilon, max_rel_error,
                          min_abs_error, print_results, subset, seed,
                          exclude: tuple = ()) -> bool:
    flat_params, treedef = jax.tree_util.tree_flatten(params)
    flat_grads = jax.tree_util.tree_leaves(analytic)
    paths = [jax.tree_util.keystr(kp)
             for kp, _ in jax.tree_util.tree_flatten_with_path(params)[0]]
    rng = np.random.default_rng(seed)
    fails = 0
    checked = 0
    max_err_seen = 0.0

    arrays = [np.asarray(p, np.float64) for p in flat_params]

    def loss_at(li, idx, delta):
        a = arrays[li].copy()
        a.reshape(-1)[idx] += delta
        leaves = list(flat_params)
        leaves[li] = jnp.asarray(a)
        return float(loss_fn(jax.tree_util.tree_unflatten(treedef, leaves)))

    for li, (pa, ga) in enumerate(zip(arrays, flat_grads)):
        if any(e in paths[li] for e in exclude):
            # statistics-like params (class centers ≙ reference "cL") are
            # intentionally updated with decoupled/stop-gradient rules and
            # are excluded from the oracle, as the reference excludes them
            continue
        ga_flat = np.asarray(ga, np.float64).reshape(-1)
        n = pa.size
        if subset is not None and n > subset:
            indices = rng.choice(n, subset, replace=False)
        else:
            indices = np.arange(n)
        for idx in indices:
            plus = loss_at(li, idx, epsilon)
            minus = loss_at(li, idx, -epsilon)
            numeric = (plus - minus) / (2 * epsilon)
            a = ga_flat[idx]
            abs_err = abs(a - numeric)
            denom = abs(a) + abs(numeric)
            rel_err = abs_err / denom if denom > 0 else 0.0
            checked += 1
            if rel_err > max_err_seen:
                max_err_seen = rel_err
            if rel_err > max_rel_error and abs_err > min_abs_error:
                fails += 1
                if print_results:
                    print(f"FAIL param {paths[li]}[{idx}]: analytic={a:.8e} "
                          f"numeric={numeric:.8e} relErr={rel_err:.4e}")
            elif print_results:
                print(f"ok   param {paths[li]}[{idx}]: analytic={a:.8e} "
                      f"numeric={numeric:.8e} relErr={rel_err:.4e}")
    if print_results or fails:
        print(f"gradient check: {checked - fails}/{checked} passed "
              f"(max rel err {max_err_seen:.4e})")
    return fails == 0
