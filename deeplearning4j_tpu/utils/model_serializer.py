"""Model save/restore — zip container, exact resume.

Reference ``util/ModelSerializer.java:52-110``: zip of ``configuration.json``
+ ``coefficients.bin`` (flat params) + updater state.  Here the container is:

  configuration.json   config serde JSON, tagged with the network class
  metadata.json        {"version", "net_class", "iteration", "epoch"}
  params.npz           param pytree, keys = "group/param" paths
  state.npz            non-trained state (BN running stats, ...)
  updater.npz          optimizer-state leaves, positional keys

Restoring with ``load_updater=True`` makes resume exact (the reference's
``saveUpdater`` flag — SURVEY §5 checkpoint/resume).  The flat
``coefficients.bin`` role is played by the npz key→array map: a stable,
inspectable serialization format rather than a runtime invariant.

Durability: ``write_model`` commits through the atomic temp-then-rename
helper (``faulttolerance/atomic.py``) — a crash mid-save leaves the
previous complete file, never a truncated zip.  A truncated or corrupt
container raises :class:`CorruptModelError` naming the path and the
member that failed, instead of surfacing raw ``zipfile``/``npz``
internals.  Restore also accepts a *checkpoint directory* from the
``faulttolerance.CheckpointManager`` store (the model payload inside it
is this same container).
"""
from __future__ import annotations

import io
import json
import os
import zipfile
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import serde
from ..faulttolerance.atomic import atomic_file

_VERSION = 1

__all__ = ["CorruptModelError", "write_model", "restore_model",
           "restore_multi_layer_network", "restore_computation_graph",
           "load_into"]


class CorruptModelError(RuntimeError):
    """A model container is truncated/corrupt.  Carries the ``path`` and,
    when known, the ``member`` inside the container that failed."""

    def __init__(self, path, member: Optional[str], detail: str):
        self.path = str(path)
        self.member = member
        where = f"{self.path}" + (f" [{member}]" if member else "")
        super().__init__(
            f"corrupt or truncated model container: {where}: {detail}")


def _flatten(tree, prefix="", out=None):
    """Arbitrary-depth dict-of-arrays → {"a/b/c": array} (handles nested
    groups like Bidirectional's {"fwd": {...}, "bwd": {...}})."""
    if out is None:
        out = {}
    for k, v in tree.items():
        path = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            _flatten(v, path, out)
        else:
            out[path] = np.asarray(v)
    return out


def _tree_to_npz_bytes(tree) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **_flatten(tree))
    return buf.getvalue()


def _npz_bytes_to_tree(data: bytes) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    with np.load(io.BytesIO(data)) as z:
        for k in z.files:
            parts = k.split("/")
            node = out
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = z[k]
    return out


def _leaves_to_npz_bytes(leaves) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})
    return buf.getvalue()


def _npz_bytes_to_leaves(data: bytes):
    with np.load(io.BytesIO(data)) as z:
        return [z[f"leaf_{i}"] for i in range(len(z.files))]


def write_model(net, path, save_updater: bool = True) -> None:
    """Save a MultiLayerNetwork or ComputationGraph
    (reference ``ModelSerializer.writeModel``).  The zip is staged on a
    temp path and atomically renamed into place — a crash mid-write can
    never leave a truncated container at ``path``."""
    meta = {
        "version": _VERSION,
        # checkpoint snapshots are proxy objects carrying the real class
        "net_class": getattr(net, "net_class", type(net).__name__),
        "iteration": net.iteration,
        "epoch": net.epoch,
        "has_updater": bool(save_updater and net.opt_state is not None),
    }
    with atomic_file(str(path)) as tmp:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("configuration.json", net.conf.to_json())
            zf.writestr("metadata.json", json.dumps(meta))
            zf.writestr("params.npz", _tree_to_npz_bytes(net.params))
            # state groups may be empty dicts — keep structure via params keys
            zf.writestr("state.npz", _tree_to_npz_bytes(net.state))
            if meta["has_updater"]:
                leaves = jax.tree_util.tree_leaves(net.opt_state)
                zf.writestr("updater.npz", _leaves_to_npz_bytes(leaves))


def _read_member(zf: zipfile.ZipFile, name: str, path) -> bytes:
    try:
        return zf.read(name)
    except KeyError:
        raise CorruptModelError(path, name, "member missing from container")
    except (zipfile.BadZipFile, zlib.error, EOFError, OSError) as e:
        raise CorruptModelError(path, name, f"{type(e).__name__}: {e}")


def _load_npz(data: bytes, member: str, path, loader):
    try:
        return loader(data)
    except (ValueError, KeyError, OSError, zipfile.BadZipFile,
            zlib.error, EOFError) as e:
        raise CorruptModelError(path, member, f"{type(e).__name__}: {e}")


def _read_container(path, load_updater: bool):
    """Read (meta, conf, params, state, updater_leaves) from a model zip,
    normalizing every truncation/corruption failure mode into
    CorruptModelError."""
    try:
        zf = zipfile.ZipFile(path, "r")
    except (zipfile.BadZipFile, EOFError) as e:
        raise CorruptModelError(path, None, f"{type(e).__name__}: {e}")
    with zf:
        raw_meta = _read_member(zf, "metadata.json", path)
        try:
            meta = json.loads(raw_meta)
        except ValueError as e:
            raise CorruptModelError(path, "metadata.json", str(e))
        try:
            conf = serde.from_json(
                _read_member(zf, "configuration.json", path).decode())
        except CorruptModelError:
            raise
        except Exception as e:
            raise CorruptModelError(path, "configuration.json",
                                    f"{type(e).__name__}: {e}")
        params = _load_npz(_read_member(zf, "params.npz", path),
                           "params.npz", path, _npz_bytes_to_tree)
        state = _load_npz(_read_member(zf, "state.npz", path),
                          "state.npz", path, _npz_bytes_to_tree)
        updater_leaves = None
        if load_updater and meta.get("has_updater") and \
                "updater.npz" in zf.namelist():
            updater_leaves = _load_npz(
                _read_member(zf, "updater.npz", path), "updater.npz", path,
                _npz_bytes_to_leaves)
    return meta, conf, params, state, updater_leaves


def _model_payload_path(path):
    """Accept a checkpoint DIRECTORY (faulttolerance store: the model
    container lives at ``<dir>/model.zip``) as well as a bare zip path."""
    p = str(path)
    if os.path.isdir(p):
        inner = os.path.join(p, "model.zip")
        if os.path.isfile(inner):
            return inner
        raise CorruptModelError(p, "model.zip",
                                "directory has no model.zip payload")
    return p


def _restore(path, expect_class: Optional[str], load_updater: bool):
    from ..nn.computation_graph import ComputationGraph
    from ..nn.conf.computation_graph import ComputationGraphConfiguration
    from ..nn.conf.multi_layer import MultiLayerConfiguration
    from ..nn.multilayer import MultiLayerNetwork

    path = _model_payload_path(path)
    meta, conf, params, state, updater_leaves = _read_container(
        path, load_updater)
    if expect_class and meta["net_class"] != expect_class:
        raise ValueError(
            f"saved model is a {meta['net_class']}, not a {expect_class}")
    if isinstance(conf, MultiLayerConfiguration):
        net = MultiLayerNetwork(conf)
    elif isinstance(conf, ComputationGraphConfiguration):
        net = ComputationGraph(conf)
    else:
        raise ValueError(f"unrecognized configuration type {type(conf)}")
    net.init()  # allocates correctly-structured trees + fresh opt state
    _install(net, meta, params, state, updater_leaves)
    return net


def load_into(net, path, load_updater: bool = True) -> None:
    """Restore a saved container INTO an existing network of the same
    topology (params, state, optionally updater state, iteration/epoch).
    The in-place counterpart of :func:`restore_model`, used by
    checkpoint-resume so the caller's network object keeps training."""
    path = _model_payload_path(path)
    meta, _conf, params, state, updater_leaves = _read_container(
        path, load_updater)
    if meta["net_class"] != type(net).__name__:
        raise ValueError(
            f"saved model is a {meta['net_class']}, not a "
            f"{type(net).__name__}")
    if not net.params:
        net.init()
    _install(net, meta, params, state, updater_leaves)


def _install(net, meta, params, state, updater_leaves) -> None:
    # overwrite with saved values (keep any group the save didn't know about)
    net.params = _merge_tree(net.params, params)
    net.state = _merge_tree(net.state, state)
    if updater_leaves is not None:
        treedef = jax.tree_util.tree_structure(net.opt_state)
        fresh = jax.tree_util.tree_leaves(net.opt_state)
        if len(fresh) != len(updater_leaves):
            raise ValueError(
                f"updater state mismatch: saved {len(updater_leaves)} leaves, "
                f"model needs {len(fresh)}")
        leaves = [jnp.asarray(s, f.dtype if hasattr(f, 'dtype') else None)
                  for s, f in zip(updater_leaves, fresh)]
        net.opt_state = jax.tree_util.tree_unflatten(treedef, leaves)
    net.iteration = int(meta.get("iteration", 0))
    net.epoch = int(meta.get("epoch", 0))


def _merge_tree(fresh, saved):
    """Recursively overlay saved arrays onto the freshly-initialized tree,
    preserving the fresh leaves' dtypes."""
    out = dict(fresh) if isinstance(fresh, dict) else {}
    for g, v in saved.items():
        if isinstance(v, dict):
            out[g] = _merge_tree(out.get(g, {}), v)
        else:
            want = out.get(g) if isinstance(out, dict) else None
            out[g] = jnp.asarray(
                v, want.dtype if hasattr(want, "dtype") else None)
    return out


def restore_multi_layer_network(path, load_updater: bool = True):
    """Reference ``ModelSerializer.restoreMultiLayerNetwork``."""
    return _restore(path, "MultiLayerNetwork", load_updater)


def restore_computation_graph(path, load_updater: bool = True):
    """Reference ``ModelSerializer.restoreComputationGraph``."""
    return _restore(path, "ComputationGraph", load_updater)


def restore_model(path, load_updater: bool = True):
    """Load either network type (reference ``ModelGuesser`` sniffing role)."""
    return _restore(path, None, load_updater)
