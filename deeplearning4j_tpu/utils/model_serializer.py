"""Model save/restore — zip container, exact resume.

Reference ``util/ModelSerializer.java:52-110``: zip of ``configuration.json``
+ ``coefficients.bin`` (flat params) + updater state.  Here the container is:

  configuration.json   config serde JSON, tagged with the network class
  metadata.json        {"version", "net_class", "iteration", "epoch"}
  params.npz           param pytree, keys = "group/param" paths
  state.npz            non-trained state (BN running stats, ...)
  updater.npz          optimizer-state leaves, positional keys

Restoring with ``load_updater=True`` makes resume exact (the reference's
``saveUpdater`` flag — SURVEY §5 checkpoint/resume).  The flat
``coefficients.bin`` role is played by the npz key→array map: a stable,
inspectable serialization format rather than a runtime invariant.
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import serde

_VERSION = 1


def _flatten(tree, prefix="", out=None):
    """Arbitrary-depth dict-of-arrays → {"a/b/c": array} (handles nested
    groups like Bidirectional's {"fwd": {...}, "bwd": {...}})."""
    if out is None:
        out = {}
    for k, v in tree.items():
        path = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            _flatten(v, path, out)
        else:
            out[path] = np.asarray(v)
    return out


def _tree_to_npz_bytes(tree) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **_flatten(tree))
    return buf.getvalue()


def _npz_bytes_to_tree(data: bytes) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    with np.load(io.BytesIO(data)) as z:
        for k in z.files:
            parts = k.split("/")
            node = out
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = z[k]
    return out


def _leaves_to_npz_bytes(leaves) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})
    return buf.getvalue()


def _npz_bytes_to_leaves(data: bytes):
    with np.load(io.BytesIO(data)) as z:
        return [z[f"leaf_{i}"] for i in range(len(z.files))]


def write_model(net, path, save_updater: bool = True) -> None:
    """Save a MultiLayerNetwork or ComputationGraph
    (reference ``ModelSerializer.writeModel``)."""
    meta = {
        "version": _VERSION,
        "net_class": type(net).__name__,
        "iteration": net.iteration,
        "epoch": net.epoch,
        "has_updater": bool(save_updater and net.opt_state is not None),
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", net.conf.to_json())
        zf.writestr("metadata.json", json.dumps(meta))
        zf.writestr("params.npz", _tree_to_npz_bytes(net.params))
        # state groups may be empty dicts — keep structure via params keys
        zf.writestr("state.npz", _tree_to_npz_bytes(net.state))
        if meta["has_updater"]:
            leaves = jax.tree_util.tree_leaves(net.opt_state)
            zf.writestr("updater.npz", _leaves_to_npz_bytes(leaves))


def _restore(path, expect_class: Optional[str], load_updater: bool):
    from ..nn.computation_graph import ComputationGraph
    from ..nn.conf.computation_graph import ComputationGraphConfiguration
    from ..nn.conf.multi_layer import MultiLayerConfiguration
    from ..nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path, "r") as zf:
        meta = json.loads(zf.read("metadata.json"))
        conf = serde.from_json(zf.read("configuration.json").decode())
        params = _npz_bytes_to_tree(zf.read("params.npz"))
        state = _npz_bytes_to_tree(zf.read("state.npz"))
        updater_leaves = None
        if load_updater and meta.get("has_updater") and \
                "updater.npz" in zf.namelist():
            updater_leaves = _npz_bytes_to_leaves(zf.read("updater.npz"))

    if expect_class and meta["net_class"] != expect_class:
        raise ValueError(
            f"saved model is a {meta['net_class']}, not a {expect_class}")
    if isinstance(conf, MultiLayerConfiguration):
        net = MultiLayerNetwork(conf)
    elif isinstance(conf, ComputationGraphConfiguration):
        net = ComputationGraph(conf)
    else:
        raise ValueError(f"unrecognized configuration type {type(conf)}")
    net.init()  # allocates correctly-structured trees + fresh opt state
    # overwrite with saved values (keep any group the save didn't know about)
    net.params = _merge_tree(net.params, params)
    net.state = _merge_tree(net.state, state)
    if updater_leaves is not None:
        treedef = jax.tree_util.tree_structure(net.opt_state)
        fresh = jax.tree_util.tree_leaves(net.opt_state)
        if len(fresh) != len(updater_leaves):
            raise ValueError(
                f"updater state mismatch: saved {len(updater_leaves)} leaves, "
                f"model needs {len(fresh)}")
        leaves = [jnp.asarray(s, f.dtype if hasattr(f, 'dtype') else None)
                  for s, f in zip(updater_leaves, fresh)]
        net.opt_state = jax.tree_util.tree_unflatten(treedef, leaves)
    net.iteration = int(meta.get("iteration", 0))
    net.epoch = int(meta.get("epoch", 0))
    return net


def _merge_tree(fresh, saved):
    """Recursively overlay saved arrays onto the freshly-initialized tree,
    preserving the fresh leaves' dtypes."""
    out = dict(fresh) if isinstance(fresh, dict) else {}
    for g, v in saved.items():
        if isinstance(v, dict):
            out[g] = _merge_tree(out.get(g, {}), v)
        else:
            want = out.get(g) if isinstance(out, dict) else None
            out[g] = jnp.asarray(
                v, want.dtype if hasattr(want, "dtype") else None)
    return out


def restore_multi_layer_network(path, load_updater: bool = True):
    """Reference ``ModelSerializer.restoreMultiLayerNetwork``."""
    return _restore(path, "MultiLayerNetwork", load_updater)


def restore_computation_graph(path, load_updater: bool = True):
    """Reference ``ModelSerializer.restoreComputationGraph``."""
    return _restore(path, "ComputationGraph", load_updater)


def restore_model(path, load_updater: bool = True):
    """Load either network type (reference ``ModelGuesser`` sniffing role)."""
    return _restore(path, None, load_updater)
