"""ctypes loader for the native host kernels (``deeplearning4j_tpu/native_src.cpp``).

The library is compiled on demand with g++ into ``native/build/`` and cached;
every entry point has a pure-Python/numpy fallback so the framework works
where no toolchain exists (``available()`` reports which path is active).
The native path releases the GIL during codec/decode work, letting prefetch
threads overlap host decode with device steps — the role libnd4j's C++ side
plays for the reference.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

__all__ = ["available", "threshold_encode_native", "threshold_decode_native",
           "bitmap_encode_native", "bitmap_decode_native", "decode_cifar",
           "u8_to_f32", "parse_csv", "index_corpus"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

# source ships INSIDE the package so pip-installed trees compile too;
# the build cache lives next to it (falls back to pure numpy when the
# location is read-only or g++ is absent)
_SRC = Path(__file__).resolve().parents[1] / "native_src.cpp"
_BUILD_DIR = Path(
    os.environ.get("DL4J_TPU_NATIVE_BUILD_DIR",
                   str(_SRC.parent / "_native_build")))
_SO = _BUILD_DIR / "libdl4j_tpu_native.so"

_i8 = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
_u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")


def _compile() -> Optional[Path]:
    # compile to a per-process temp name, then atomically publish: concurrent
    # processes must never dlopen a half-written .so.  ANY filesystem issue
    # (source tree absent in a stripped install, read-only dir, no g++) must
    # fall back to pure Python, never crash the caller.
    tmp = None
    try:
        if _SO.exists() and (not _SRC.exists()
                             or _SO.stat().st_mtime >= _SRC.stat().st_mtime):
            return _SO
        if not _SRC.exists():
            return None
        _BUILD_DIR.mkdir(parents=True, exist_ok=True)
        tmp = _SO.with_suffix(f".tmp{os.getpid()}.so")
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               "-o", str(tmp), str(_SRC)]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if tmp is not None and tmp.exists():
            try:
                tmp.unlink()
            except OSError:
                pass


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("DL4J_TPU_DISABLE_NATIVE"):
            return None
        so = _compile()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(str(so))
            _bind(lib)
        except (OSError, AttributeError):  # truncated/stale .so: missing syms
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.dl4j_threshold_encode.restype = ctypes.c_int64
    lib.dl4j_threshold_encode.argtypes = [
        _f32, ctypes.c_int64, ctypes.c_float, ctypes.c_int64,
        _i32, _i8, _f32]
    lib.dl4j_threshold_decode.restype = None
    lib.dl4j_threshold_decode.argtypes = [
        _i32, _i8, ctypes.c_int64, ctypes.c_float, _f32, ctypes.c_int64]
    lib.dl4j_bitmap_encode.restype = ctypes.c_int64
    lib.dl4j_bitmap_encode.argtypes = [
        _f32, ctypes.c_int64, ctypes.c_float, _u8, _f32]
    lib.dl4j_bitmap_decode.restype = None
    lib.dl4j_bitmap_decode.argtypes = [
        _u8, ctypes.c_int64, ctypes.c_float, _f32]
    lib.dl4j_u8_to_f32.restype = None
    lib.dl4j_u8_to_f32.argtypes = [_u8, ctypes.c_int64, ctypes.c_float,
                                   _f32]
    lib.dl4j_decode_cifar.restype = None
    lib.dl4j_decode_cifar.argtypes = [_u8, ctypes.c_int64, ctypes.c_float,
                                      _i32, _f32]
    lib.dl4j_parse_csv.restype = ctypes.c_int64
    lib.dl4j_parse_csv.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, _f32,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
    lib.dl4j_index_corpus.restype = ctypes.c_int64
    lib.dl4j_index_corpus.argtypes = [
        ctypes.c_char_p, _i64, ctypes.c_int64, ctypes.c_char_p,
        ctypes.c_int64, _i32, ctypes.c_int64, _i64]


def available() -> bool:
    """True when the compiled native library is loadable."""
    return _load() is not None


# ---------------------------------------------------------------- wrappers
def threshold_encode_native(grad: np.ndarray, threshold: float,
                            max_k: Optional[int] = None
                            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (idx int32[count], signs int8[count], residual f32[n])."""
    grad = np.ascontiguousarray(grad, np.float32)
    n = grad.size
    k = int(max_k or max(1, n // 16))
    lib = _load()
    if lib is not None:
        idx = np.empty(k, np.int32)
        signs = np.empty(k, np.int8)
        residual = np.empty(n, np.float32)
        cnt = lib.dl4j_threshold_encode(grad, n, threshold, k, idx, signs,
                                        residual)
        return idx[:cnt].copy(), signs[:cnt].copy(), residual
    # numpy fallback
    over = np.flatnonzero(np.abs(grad) >= threshold)
    if len(over) > k:
        sel = np.argpartition(-np.abs(grad[over]), k - 1)[:k]
        over = np.sort(over[sel])
    signs = np.sign(grad[over]).astype(np.int8)
    signs[signs == 0] = 1
    residual = grad.copy()
    residual[over] -= signs * np.float32(threshold)
    return over.astype(np.int32), signs, residual


def threshold_decode_native(idx, signs, threshold: float, n: int) -> np.ndarray:
    idx = np.ascontiguousarray(idx, np.int32)
    signs = np.ascontiguousarray(signs, np.int8)
    lib = _load()
    out = np.empty(n, np.float32)
    if lib is not None:
        lib.dl4j_threshold_decode(idx, signs, len(idx), threshold, out, n)
        return out
    out[:] = 0
    out[idx] = signs.astype(np.float32) * threshold
    return out


def bitmap_encode_native(grad: np.ndarray, threshold: float
                         ) -> Tuple[np.ndarray, np.ndarray]:
    grad = np.ascontiguousarray(grad, np.float32)
    n = grad.size
    lib = _load()
    if lib is not None:
        packed = np.empty((n + 3) // 4, np.uint8)
        residual = np.empty(n, np.float32)
        lib.dl4j_bitmap_encode(grad, n, threshold, packed, residual)
        return packed, residual
    codes = np.where(grad >= threshold, 1,
                     np.where(grad <= -threshold, 2, 0)).astype(np.uint8)
    residual = grad - np.where(codes == 1, threshold,
                               np.where(codes == 2, -threshold, 0)
                               ).astype(np.float32)
    pad = (-n) % 4
    q = np.concatenate([codes, np.zeros(pad, np.uint8)]).reshape(-1, 4)
    packed = q[:, 0] | (q[:, 1] << 2) | (q[:, 2] << 4) | (q[:, 3] << 6)
    return packed.astype(np.uint8), residual


def bitmap_decode_native(packed: np.ndarray, threshold: float,
                         n: int) -> np.ndarray:
    packed = np.ascontiguousarray(packed, np.uint8)
    lib = _load()
    if lib is not None:
        out = np.empty(n, np.float32)
        lib.dl4j_bitmap_decode(packed, n, threshold, out)
        return out
    quads = np.stack([(packed >> s) & 0x3 for s in (0, 2, 4, 6)], 1)
    codes = quads.reshape(-1)[:n]
    return np.where(codes == 1, threshold,
                    np.where(codes == 2, -threshold, 0.0)).astype(np.float32)


def u8_to_f32(data: np.ndarray, scale: float = 1.0 / 255.0) -> np.ndarray:
    data = np.ascontiguousarray(data, np.uint8)
    lib = _load()
    if lib is not None:
        out = np.empty(data.size, np.float32)
        lib.dl4j_u8_to_f32(data.reshape(-1), data.size, scale, out)
        return out.reshape(data.shape)
    return data.astype(np.float32) * scale


def decode_cifar(raw: bytes, scale: float = 1.0 / 255.0
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR binary batch -> (labels int32[n], images f32 NHWC [n,32,32,3])."""
    buf = np.frombuffer(raw, np.uint8)
    if buf.size % 3073:
        raise ValueError("CIFAR batch not a multiple of 3073 bytes")
    n = buf.size // 3073
    lib = _load()
    if lib is not None:
        labels = np.empty(n, np.int32)
        images = np.empty(n * 3072, np.float32)
        lib.dl4j_decode_cifar(np.ascontiguousarray(buf), n, scale, labels,
                              images)
        return labels, images.reshape(n, 32, 32, 3)
    rec = buf.reshape(n, 3073)
    labels = rec[:, 0].astype(np.int32)
    chw = rec[:, 1:].reshape(n, 3, 32, 32)
    return labels, chw.transpose(0, 2, 3, 1).astype(np.float32) * scale


def parse_csv(text: bytes, delimiter: str = ",") -> np.ndarray:
    """ASCII float CSV -> [rows, cols] f32 (native strtof path when built)."""
    if isinstance(text, str):
        text = text.encode()
    lib = _load()
    if lib is not None:
        max_out = max(len(text) // 2 + 16, 64)  # >= one value per 2 chars
        out = np.empty(max_out, np.float32)
        ncols = ctypes.c_int64(0)
        nvals = lib.dl4j_parse_csv(text, len(text),
                                   delimiter.encode()[0], out, max_out,
                                   ctypes.byref(ncols))
        if nvals < 0:
            raise ValueError("malformed CSV (native parser)")
        c = ncols.value
        if c == 0:
            return np.empty((0, 0), np.float32)
        return out[:nvals].reshape(-1, c).copy()
    rows = [r for r in text.decode().splitlines() if r.strip()]
    return np.asarray([[float(v) for v in r.split(delimiter)] for r in rows],
                      np.float32)


def index_corpus(sentences, index_map):
    """Tokenize + vocab-index ``sentences`` (list of str) natively — the
    data-loader role the reference delegates to DataVec/libnd4j.  Returns a
    list of per-sentence int32 index arrays (views into one buffer, OOV
    dropped), or None when the native library is unavailable or the text
    uses Unicode whitespace (where str.split semantics require the Python
    path).  Token semantics are EXACTLY ``str.split()`` — the bulk-emission
    equivalence oracle in test_nlp pins this.
    """
    lib = _load()
    if lib is None or not index_map:
        return None
    try:
        parts = [s.encode() for s in sentences]
    except UnicodeEncodeError:
        return None   # lone surrogates (surrogateescape text): Python path
    offsets = np.zeros(len(parts) + 1, np.int64)
    np.cumsum([len(b) for b in parts], out=offsets[1:])
    text = b"".join(parts)
    words = [None] * len(index_map)
    for w, i in index_map.items():
        if not 0 <= i < len(words) or words[i] is not None:
            return None          # non-contiguous index space: Python path
        words[i] = w
    blob = "\n".join(words).encode()
    # worst case one token per 2 bytes WITHIN a sentence, but sentence
    # boundaries consume no separator byte — hence the +n_sent term
    cap = max((len(text) + len(parts)) // 2 + 16, 64)
    out_idx = np.empty(cap, np.int32)
    out_counts = np.zeros(len(parts), np.int64)
    total = lib.dl4j_index_corpus(text, offsets, len(parts), blob,
                                  len(blob), out_idx, cap, out_counts)
    if total < 0:
        return None              # unicode whitespace: fall back
    flat = out_idx[:total]
    return np.split(flat, np.cumsum(out_counts)[:-1].astype(np.int64))
