"""ZeRO-3 sharded SPMD training: params + optimizer state partitioned
over the data axis.

The replicated scale-out paths (``parallel/master*.py``,
``ParallelWrapper``) hold FULL params and FULL updater state per
worker, so model size is capped by one device and every step ships a
dense all-reduce.  This module is the weight-update sharding transform
of "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv:2004.13336, PAPERS.md) taken to its ZeRO-3 endpoint:

  - every parameter leaf (and its optax mu/nu/trace mirror) is laid out
    with a ``NamedSharding`` row-sharded over ``data``
    (``mesh.zero3_spec``: first axis divisible by dp; sub-threshold
    leaves — biases, norms — replicate, sharding them saves nothing);
  - the train step is the SAME jitted program every network uses
    (``_get_jitted("train_step")`` through the process-global trace
    cache): GSPMD sees sharded param inputs + a data-sharded batch and
    itself inserts the forward all-gather, turns the gradient reduction
    into a reduce-scatter, and keeps the update shard-local — the
    all-reduce → reduce-scatter + all-gather rewrite is derived from
    the shardings, not hand-written collectives;
  - because sharding lives in the ARGUMENTS, not the trace, one Python
    trace serves every mesh size: a dp=2 and a dp=8 run share one
    ``training_compile_total{fn="train_step"}`` tick (each dp still
    gets its own XLA executable — lowering is per-sharding, tracing is
    not).  This is what collapses the thread-pool "replica" abstraction
    into one program.

Mixed precision composes for free: with a bf16 ``PrecisionPolicy`` the
sharded params ARE the f32 masters (``nn/precision``) — the in-step
cast produces bf16 compute values while the updater applies its f32
update to the local shard only ("sharded masters").

Numerics: at a fixed global batch the sharded step is BIT-FOR-BIT the
replicated step on the same mesh whenever GSPMD gathers the sharded
params before the matmul — its choice for every representative shape
(tier-1 pins dp=2/4/8 bitwise); with a *tiny* sharded contracting dim
it may partial-compute + all-reduce instead, which reassociates that
reduction and bounds parity at ~1e-6-relative (f32) — the same noise
class as changing dp in any data-parallel run (also pinned).  Across
dp sizes results always agree to reassociation tolerance.

Checkpoints: ``faulttolerance.checkpoint`` grows ``save_sharded`` /
``restore_sharded`` (portable-collectives resharding, arXiv:2112.01075)
— each process writes only its shard blocks plus a topology manifest,
and a restore reassembles host-side and re-places onto ANY mesh (a
4-way checkpoint resumes 8-way), which is also what lets an elastic
rejoin re-place a sharded model onto the surviving world.  Multi-writer
worlds commit through the two-phase ``ShardBarrier`` staged protocol
(every process's block + generation-fenced marker land before the
primary's manifest+rename), and ``ElasticTrainer`` drives the whole
loop: barrier saves at round boundaries, membership changes rebuilding
the mesh over survivors via ``restore_sharded(mesh=survivors)``, one
train-step trace across topology changes.

Sparse embedding tables ride the same layout: a ``sparse_grad=True``
embedding table is simply the first VERY large parameter this rule
row-shards (``zero3_spec`` puts the vocab axis over ``data``, its
optax mirrors included), so vocabulary size is no longer capped by one
device's HBM.  The train step's densified pre-pass (``nn/sparse``)
then makes the per-step exchange O(touched rows): GSPMD derives, from
these same argument shardings, a ragged touched-row lookup — the
replicated id blocks gather shard-locally and an O(capacity·dim)
all-reduce returns the requested rows to every requester — and the
backward's coalesced index+value blocks reduce back to their owner
shards the same way, while the row scatter-update (params and
mirrors) stays shard-local.  No hand-written collectives, no second
trace: a dp=2 and dp=8 sparse run still share the ONE train-step
trace, and checkpoints reshard through the same
``save_sharded``/``restore_sharded`` per-leaf block format (the table
is just a big leaf; dp=4 → dp=2 restores digest-exact, pinned in
tests/test_sparse_embedding.py).

Gather/compute overlap (the second half of the arXiv:2004.13336 win):
because the forward all-gather of each layer's shard is emitted at its
USE SITE — the step folds over layers consuming ``params[name]`` one
at a time, so GSPMD materializes layer k+1's gather as a separate
collective from layer k's matmul rather than one up-front blob — XLA's
latency-hiding scheduler may legally start layer k+1's all-gather
while layer k computes.  On TPU that overlap is armed by
:func:`enable_gather_compute_overlap` (async all-gather thunks + the
latency-hiding scheduler; a no-op on rigs without a TPU runtime, where
the flags don't exist), which :class:`ShardedTrainer` applies
best-effort at construction.  Two invariants make this a pure
scheduling change: the collective CENSUS is untouched (the dp=2/dp=4
golden pins in tests/test_audit.py hold exactly — same ops, same
bytes, different start times), so the proof instrument is stepprof's
per-step ``device`` slice medians, not census drift; and the bounded
dispatch window the inherited fit loop runs (``nn/dispatch``) keeps
the HOST a step ahead, so the dispatch of step N+1 overlaps step N's
gather+compute chain end-to-end.

The derived collective layout is GUARDED at the IR level: graftaudit
(``tools/graftaudit``, rule AX003) compiles the canonical dp=2/dp=4
sharded train steps from their recorded argument shardings and flags a
dense all-reduce of (near-)param bytes — the pattern that appears when
some op defeats the GSPMD scatter/gather derivation — and
``tests/test_audit.py`` pins both censuses EXACTLY (golden collective
signature), so a layout regression fails tier-1 instead of a profile
review.  The sparse-table program has its own canonical pin:
``train_step[embedding_zero3]``'s committed card must contain no
collective carrying O(vocab·dim) bytes.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DEFAULT_MIN_SHARD_SIZE, place_sharded, shard_params
from .wrapper import ParallelWrapper

__all__ = ["ShardedTrainer", "per_device_param_bytes", "param_bytes",
           "enable_gather_compute_overlap", "OVERLAP_XLA_FLAGS",
           "DEFAULT_MIN_SHARD_SIZE"]

#: TPU compiler flags that turn the use-site forward all-gathers into
#: async thunks and let the latency-hiding scheduler start layer k+1's
#: gather while layer k computes.  Scheduling-only: the collective
#: census (ops, bytes, golden dp=2/dp=4 pins) is identical with or
#: without them.
OVERLAP_XLA_FLAGS = (
    "--xla_tpu_enable_async_all_gather=true",
    "--xla_tpu_enable_latency_hiding_scheduler=true",
)


def _tpu_platform_selected() -> bool:
    """True unless this process has PINNED its jax platform set to one
    that excludes TPU (``JAX_PLATFORMS=cpu`` and friends) — in that
    case the TPU client will never be built here, and any TPU-only
    ``XLA_FLAGS`` we write would outlive us in ``os.environ``, get
    inherited by child processes, and fatally abort their CPU-only
    XLA flag parse."""
    sel = None
    try:
        sel = jax.config.jax_platforms  # mirrors JAX_PLATFORMS
    except Exception:
        pass
    if not sel:
        sel = (os.environ.get("JAX_PLATFORMS")
               or os.environ.get("JAX_PLATFORM_NAME"))
    if not sel:
        return True  # unpinned: TPU may still be selected at init
    return "tpu" in [p.strip() for p in sel.lower().split(",")]


def enable_gather_compute_overlap() -> bool:
    """Arm the TPU gather/compute-overlap flags (``OVERLAP_XLA_FLAGS``)
    by appending them to ``XLA_FLAGS``.  Returns True when the flags
    were applied (or already present) in time to matter.

    No-op (False) when no TPU runtime is installed OR the process has
    pinned a non-TPU platform (``JAX_PLATFORMS=cpu``) — these are
    TPU-runtime flag definitions, and XLA aborts on unknown
    ``XLA_FLAGS`` entries, so they must never leak onto a CPU-only rig
    (nor into its CHILD processes, which inherit the mutated environ;
    a libtpu wheel can be installed on a box that still runs CPU-only)
    — or when the TPU backend already initialized (XLA snapshots the
    flags at backend init; late edits are silently dead, so report the
    truth rather than pretend).
    """
    if not _tpu_platform_selected():
        return False
    try:
        import importlib.util
        if importlib.util.find_spec("libtpu") is None:
            return False
    except Exception:
        return False
    flags = os.environ.get("XLA_FLAGS", "")
    missing = [f for f in OVERLAP_XLA_FLAGS if f.split("=")[0] not in flags]
    if not missing:
        return True
    try:
        # jax's backend table is lazy per-platform: flags still land if
        # the TPU client hasn't been built yet, even when CPU is up
        from jax._src import xla_bridge
        if "tpu" in getattr(xla_bridge, "_backends", {}):
            return False
    except Exception:
        pass
    os.environ["XLA_FLAGS"] = (flags + " " + " ".join(missing)).strip()
    return True


def param_bytes(params) -> int:
    """Global (unsharded) parameter bytes of a pytree."""
    return sum(int(np.prod(getattr(l, "shape", ()), dtype=np.int64))
               * np.dtype(l.dtype).itemsize
               for l in jax.tree_util.tree_leaves(params))


def per_device_param_bytes(params) -> int:
    """Bytes ONE device holds for a pytree: sharded leaves count their
    shard only (``sharding.shard_shape``), replicated/host leaves count
    whole — the ~1/dp memory-win number the bench line reports."""
    total = 0
    for l in jax.tree_util.tree_leaves(params):
        shape = getattr(l, "shape", ())
        sh = getattr(l, "sharding", None)
        if sh is not None and hasattr(sh, "shard_shape"):
            shape = sh.shard_shape(tuple(shape))
        total += int(np.prod(shape, dtype=np.int64)) * \
            np.dtype(l.dtype).itemsize
    return total


class ShardedTrainer(ParallelWrapper):
    """Drop-in ``fit`` with ZeRO-3 param + updater sharding over ``data``.

    Same contract as :class:`ParallelWrapper` (it IS one — the batch
    loop, trimming, listener plumbing, and the shared jitted step are
    inherited); only the placement differs: params, grads and updater
    state live row-sharded over the data axis, so per-device parameter
    memory is ~1/dp of the replicated wrapper's and the gradient
    all-reduce becomes reduce-scatter + (forward) all-gather.

    ``min_shard_size``: leaves with fewer elements replicate (the
    collective latency would exceed the memory saved).

    ``gather_compute_overlap``: arm the TPU async-all-gather +
    latency-hiding-scheduler flags (module docstring) so the forward
    gathers overlap layer compute; ``overlap_armed`` records whether
    the flags actually landed (always False on a CPU rig).
    """

    def __init__(self, model, mesh: Optional[Mesh] = None, *,
                 min_shard_size: int = DEFAULT_MIN_SHARD_SIZE,
                 gather_compute_overlap: bool = True):
        self.min_shard_size = int(min_shard_size)
        self.overlap_armed = (enable_gather_compute_overlap()
                              if gather_compute_overlap else False)
        super().__init__(model, mesh)

    # ------------------------------------------------------------------
    def _place(self):
        m, mesh = self.model, self.mesh
        self.param_shardings = shard_params(mesh, m.params,
                                            min_size=self.min_shard_size)
        m.params = jax.tree_util.tree_map(place_sharded, m.params,
                                          self.param_shardings)
        repl = NamedSharding(mesh, P())
        m.state = jax.tree_util.tree_map(
            lambda a: place_sharded(a, repl), m.state)
        # fused-RNG key: replicate up front so the first step already has
        # the sharding the step's successor-key output carries
        m._rng = place_sharded(m._rng, repl)
        if m.opt_state is not None:
            # leaf-wise, not treedef-matched: optax multi_transform wraps
            # the param-shaped mu/nu subtrees in MaskedNode sentinels, so
            # an exact-structure match never fires.  A mirror leaf has
            # exactly its param's shape, so the per-leaf zero3 rule makes
            # the identical shard/replicate decision the params got.
            opt_sh = shard_params(mesh, m.opt_state,
                                  min_size=self.min_shard_size)
            m.opt_state = jax.tree_util.tree_map(place_sharded,
                                                 m.opt_state, opt_sh)

    # ------------------------------------------------------- memory view
    def per_device_param_bytes(self) -> int:
        return per_device_param_bytes(self.model.params)

    def global_param_bytes(self) -> int:
        return param_bytes(self.model.params)

    # ---------------------------------------------------------- persist
    def save_sharded(self, manager, **kwargs) -> str:
        """Shard-aware checkpoint through a ``CheckpointManager`` — this
        process writes only its shard blocks + the topology manifest
        (``faulttolerance.checkpoint.save_sharded``).  Multi-process
        worlds pass ``barrier=ShardBarrier(...)`` (or run under
        ``ElasticTrainer``, which builds the barrier from the cluster
        view): the primary commits only after every live writer's block
        lands."""
        return manager.save_sharded(self.model, **kwargs)

    def average_params(self):
        """No-op like the parent's, but the returned tree is SHARDED —
        materializing it would defeat the 1/dp layout; callers that need
        host values should go through checkpoint save_sharded."""
        return self.model.params
