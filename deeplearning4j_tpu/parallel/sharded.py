"""ZeRO-3 sharded SPMD training: params + optimizer state partitioned
over the data axis.

The replicated scale-out paths (``parallel/master*.py``,
``ParallelWrapper``) hold FULL params and FULL updater state per
worker, so model size is capped by one device and every step ships a
dense all-reduce.  This module is the weight-update sharding transform
of "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv:2004.13336, PAPERS.md) taken to its ZeRO-3 endpoint:

  - every parameter leaf (and its optax mu/nu/trace mirror) is laid out
    with a ``NamedSharding`` row-sharded over ``data``
    (``mesh.zero3_spec``: first axis divisible by dp; sub-threshold
    leaves — biases, norms — replicate, sharding them saves nothing);
  - the train step is the SAME jitted program every network uses
    (``_get_jitted("train_step")`` through the process-global trace
    cache): GSPMD sees sharded param inputs + a data-sharded batch and
    itself inserts the forward all-gather, turns the gradient reduction
    into a reduce-scatter, and keeps the update shard-local — the
    all-reduce → reduce-scatter + all-gather rewrite is derived from
    the shardings, not hand-written collectives;
  - because sharding lives in the ARGUMENTS, not the trace, one Python
    trace serves every mesh size: a dp=2 and a dp=8 run share one
    ``training_compile_total{fn="train_step"}`` tick (each dp still
    gets its own XLA executable — lowering is per-sharding, tracing is
    not).  This is what collapses the thread-pool "replica" abstraction
    into one program.

Mixed precision composes for free: with a bf16 ``PrecisionPolicy`` the
sharded params ARE the f32 masters (``nn/precision``) — the in-step
cast produces bf16 compute values while the updater applies its f32
update to the local shard only ("sharded masters").

Numerics: at a fixed global batch the sharded step is BIT-FOR-BIT the
replicated step on the same mesh whenever GSPMD gathers the sharded
params before the matmul — its choice for every representative shape
(tier-1 pins dp=2/4/8 bitwise); with a *tiny* sharded contracting dim
it may partial-compute + all-reduce instead, which reassociates that
reduction and bounds parity at ~1e-6-relative (f32) — the same noise
class as changing dp in any data-parallel run (also pinned).  Across
dp sizes results always agree to reassociation tolerance.

Checkpoints: ``faulttolerance.checkpoint`` grows ``save_sharded`` /
``restore_sharded`` (portable-collectives resharding, arXiv:2112.01075)
— each process writes only its shard blocks plus a topology manifest,
and a restore reassembles host-side and re-places onto ANY mesh (a
4-way checkpoint resumes 8-way), which is also what lets an elastic
rejoin re-place a sharded model onto the surviving world.  Multi-writer
worlds commit through the two-phase ``ShardBarrier`` staged protocol
(every process's block + generation-fenced marker land before the
primary's manifest+rename), and ``ElasticTrainer`` drives the whole
loop: barrier saves at round boundaries, membership changes rebuilding
the mesh over survivors via ``restore_sharded(mesh=survivors)``, one
train-step trace across topology changes.

Sparse embedding tables ride the same layout: a ``sparse_grad=True``
embedding table is simply the first VERY large parameter this rule
row-shards (``zero3_spec`` puts the vocab axis over ``data``, its
optax mirrors included), so vocabulary size is no longer capped by one
device's HBM.  The train step's densified pre-pass (``nn/sparse``)
then makes the per-step exchange O(touched rows): GSPMD derives, from
these same argument shardings, a ragged touched-row lookup — the
replicated id blocks gather shard-locally and an O(capacity·dim)
all-reduce returns the requested rows to every requester — and the
backward's coalesced index+value blocks reduce back to their owner
shards the same way, while the row scatter-update (params and
mirrors) stays shard-local.  No hand-written collectives, no second
trace: a dp=2 and dp=8 sparse run still share the ONE train-step
trace, and checkpoints reshard through the same
``save_sharded``/``restore_sharded`` per-leaf block format (the table
is just a big leaf; dp=4 → dp=2 restores digest-exact, pinned in
tests/test_sparse_embedding.py).

The derived collective layout is GUARDED at the IR level: graftaudit
(``tools/graftaudit``, rule AX003) compiles the canonical dp=2/dp=4
sharded train steps from their recorded argument shardings and flags a
dense all-reduce of (near-)param bytes — the pattern that appears when
some op defeats the GSPMD scatter/gather derivation — and
``tests/test_audit.py`` pins both censuses EXACTLY (golden collective
signature), so a layout regression fails tier-1 instead of a profile
review.  The sparse-table program has its own canonical pin:
``train_step[embedding_zero3]``'s committed card must contain no
collective carrying O(vocab·dim) bytes.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DEFAULT_MIN_SHARD_SIZE, place_sharded, shard_params
from .wrapper import ParallelWrapper

__all__ = ["ShardedTrainer", "per_device_param_bytes", "param_bytes",
           "DEFAULT_MIN_SHARD_SIZE"]


def param_bytes(params) -> int:
    """Global (unsharded) parameter bytes of a pytree."""
    return sum(int(np.prod(getattr(l, "shape", ()), dtype=np.int64))
               * np.dtype(l.dtype).itemsize
               for l in jax.tree_util.tree_leaves(params))


def per_device_param_bytes(params) -> int:
    """Bytes ONE device holds for a pytree: sharded leaves count their
    shard only (``sharding.shard_shape``), replicated/host leaves count
    whole — the ~1/dp memory-win number the bench line reports."""
    total = 0
    for l in jax.tree_util.tree_leaves(params):
        shape = getattr(l, "shape", ())
        sh = getattr(l, "sharding", None)
        if sh is not None and hasattr(sh, "shard_shape"):
            shape = sh.shard_shape(tuple(shape))
        total += int(np.prod(shape, dtype=np.int64)) * \
            np.dtype(l.dtype).itemsize
    return total


class ShardedTrainer(ParallelWrapper):
    """Drop-in ``fit`` with ZeRO-3 param + updater sharding over ``data``.

    Same contract as :class:`ParallelWrapper` (it IS one — the batch
    loop, trimming, listener plumbing, and the shared jitted step are
    inherited); only the placement differs: params, grads and updater
    state live row-sharded over the data axis, so per-device parameter
    memory is ~1/dp of the replicated wrapper's and the gradient
    all-reduce becomes reduce-scatter + (forward) all-gather.

    ``min_shard_size``: leaves with fewer elements replicate (the
    collective latency would exceed the memory saved).
    """

    def __init__(self, model, mesh: Optional[Mesh] = None, *,
                 min_shard_size: int = DEFAULT_MIN_SHARD_SIZE):
        self.min_shard_size = int(min_shard_size)
        super().__init__(model, mesh)

    # ------------------------------------------------------------------
    def _place(self):
        m, mesh = self.model, self.mesh
        self.param_shardings = shard_params(mesh, m.params,
                                            min_size=self.min_shard_size)
        m.params = jax.tree_util.tree_map(place_sharded, m.params,
                                          self.param_shardings)
        repl = NamedSharding(mesh, P())
        m.state = jax.tree_util.tree_map(
            lambda a: place_sharded(a, repl), m.state)
        if m.opt_state is not None:
            # leaf-wise, not treedef-matched: optax multi_transform wraps
            # the param-shaped mu/nu subtrees in MaskedNode sentinels, so
            # an exact-structure match never fires.  A mirror leaf has
            # exactly its param's shape, so the per-leaf zero3 rule makes
            # the identical shard/replicate decision the params got.
            opt_sh = shard_params(mesh, m.opt_state,
                                  min_size=self.min_shard_size)
            m.opt_state = jax.tree_util.tree_map(place_sharded,
                                                 m.opt_state, opt_sh)

    # ------------------------------------------------------- memory view
    def per_device_param_bytes(self) -> int:
        return per_device_param_bytes(self.model.params)

    def global_param_bytes(self) -> int:
        return param_bytes(self.model.params)

    # ---------------------------------------------------------- persist
    def save_sharded(self, manager, **kwargs) -> str:
        """Shard-aware checkpoint through a ``CheckpointManager`` — this
        process writes only its shard blocks + the topology manifest
        (``faulttolerance.checkpoint.save_sharded``).  Multi-process
        worlds pass ``barrier=ShardBarrier(...)`` (or run under
        ``ElasticTrainer``, which builds the barrier from the cluster
        view): the primary commits only after every live writer's block
        lands."""
        return manager.save_sharded(self.model, **kwargs)

    def average_params(self):
        """No-op like the parent's, but the returned tree is SHARDED —
        materializing it would defeat the 1/dp layout; callers that need
        host values should go through checkpoint save_sharded."""
        return self.model.params
