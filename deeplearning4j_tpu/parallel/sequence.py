"""Sequence/context parallelism: ring attention + Ulysses (all-to-all).

The reference framework predates transformers and has NO long-context story
beyond truncated BPTT (SURVEY.md §5).  This module is the TPU build's
first-class replacement: shard the time axis of q/k/v over the mesh 'seq'
axis and compute exact attention with either

  * **ring attention** — k/v shards rotate around the ring via
    ``lax.ppermute`` (ICI neighbor exchange); each step attends the local q
    block to the visiting k/v block and merges with the running online-softmax
    partials (``ops.attention.combine_blocks``).  Memory per device: O(t/n);
    comms: n-1 neighbor hops fully overlappable with compute by XLA.
  * **Ulysses** — one ``lax.all_to_all`` reswizzles [seq-shard, all heads] ->
    [all seq, head-shard], runs ordinary (flash) attention per head group,
    and a second all-to-all restores the layout.  Cheaper comms for
    head-rich models; requires n_heads % axis_size == 0.

Both are designed to run INSIDE ``shard_map`` over a mesh with a 'seq' axis —
``MultiHeadAttention`` picks them up via ``attn_impl='ring'|'ulysses'`` when
the training step is sequence-sharded (see ``parallel.dryrun``).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .pipeline import _HAS_VMA
from ..ops.attention import (attn_block, combine_blocks, finalize_blocks,
                             init_blocks)


def ring_self_attention(q, k, v, *, axis_name: str, causal: bool = False,
                        scale: Optional[float] = None):
    """Exact attention with q/k/v sharded [b, h, t/n, d] over ``axis_name``.

    Shard i holds global positions [i*t_blk, (i+1)*t_blk).  k/v blocks rotate
    ring-wise; online-softmax partials make the result exactly equal to full
    attention (up to float32 reduction order).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, t_blk, d = q.shape
    # Initial partials must be marked as device-varying over the seq axis for
    # shard_map's carry typing (they combine with axis-varying blocks).  On
    # jax versions without the varying-manual-axes machinery (pcast,
    # jax >= 0.6) shard_map values are untyped-varying already.
    if _HAS_VMA:
        acc, m, l = jax.tree.map(
            lambda a: lax.pcast(a, (axis_name,), to="varying"),
            init_blocks(b, h, t_blk, d, q.dtype))
    else:
        acc, m, l = init_blocks(b, h, t_blk, d, q.dtype)
    q_off = idx * t_blk
    perm = [(j, (j + 1) % n) for j in range(n)]

    # n is the static mesh-axis size, so unroll in Python: XLA sees a straight
    # compute/ppermute chain it can overlap, and the final (useless) rotation
    # is simply not emitted — n-1 neighbor hops total.
    k_cur, v_cur = k, v
    for i in range(n):
        # Block currently visiting came from shard (idx - i) mod n.
        src = (idx - i) % n
        a2, m2, l2 = attn_block(q, k_cur, v_cur, causal=causal, scale=scale,
                                q_offset=q_off, k_offset=src * t_blk)
        acc, m, l = combine_blocks(acc, m, l, a2, m2, l2)
        if i < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    return finalize_blocks(acc, m, l, q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None, attn_fn=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    In: [b, h, t/n, d] seq-sharded.  all_to_all -> [b, h/n, t, d]
    head-sharded, full attention locally (``attn_fn``, default reference
    SDPA), all_to_all back.  Requires h % axis_size == 0.
    """
    from ..ops.attention import sdpa_reference
    if attn_fn is None:
        attn_fn = sdpa_reference
    n = lax.psum(1, axis_name)  # static axis size
    if q.shape[1] % n:
        raise ValueError(f"ulysses_attention needs n_heads ({q.shape[1]}) "
                         f"divisible by the '{axis_name}' axis size ({n})")
    # [b, h, t_blk, d] -> split heads across devices, gather time:
    # all_to_all(split_axis=heads, concat_axis=time)
    qg = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    o = attn_fn(qg, kg, vg, causal=causal, scale=scale)
    # [b, h/n, t, d] -> back to [b, h, t_blk, d]
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1, tiled=True)
