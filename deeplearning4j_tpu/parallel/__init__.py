"""Parallelism: mesh construction, DP/TP wrapper, GPipe pipeline,
ring/Ulysses sequence parallelism (reference ``deeplearning4j-scaleout``)."""
from .inference import InferenceMode, ParallelInference
from .mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS, make_mesh, shard_batch
from .pipeline import gpipe, stack_stage_params
from .sequence import ring_self_attention, ulysses_attention
from .wrapper import ParallelWrapper, megatron_dense_rule

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS", "InferenceMode",
    "ParallelInference", "ParallelWrapper", "gpipe", "make_mesh",
    "megatron_dense_rule", "ring_self_attention", "shard_batch",
    "stack_stage_params", "ulysses_attention",
]
