"""Parallelism: mesh construction, DP/TP wrapper, GPipe pipeline,
ring/Ulysses sequence parallelism, expert-parallel MoE (reference
``deeplearning4j-scaleout``)."""
from .accumulation import (EncodedGradientsAccumulator, EncodingHandler,
                           bitmap_decode, bitmap_encode, threshold_decode,
                           threshold_encode)
from .remote import (RemoteGradientSharing, decode_message_bytes,
                     encode_message_bytes)
from .expert import init_moe_params, make_moe_train_step, moe_ffn
from .distributed import (ElasticTrainer, global_device_mesh,
                          initialize_distributed)
from .inference import InferenceMode, ParallelInference
from .layer import DistributedLayerTrainer
from .master import (ParameterAveragingTrainingMaster,
                     SharedGradientsTrainingMaster, TrainingMaster,
                     TrainingMasterStats, tree_average)
from .mesh import (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, make_mesh,
                   place_sharded, shard_batch, shard_params, zero3_spec)
from .pipeline import gpipe, stack_stage_params
from .sequence import ring_self_attention, ulysses_attention
from .sharded import (ShardedTrainer, param_bytes, per_device_param_bytes)
from .wrapper import ParallelWrapper, megatron_dense_rule

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS", "ElasticTrainer",
    "EncodedGradientsAccumulator", "EncodingHandler", "InferenceMode",
    "ParallelInference", "ParallelWrapper",
    "ParameterAveragingTrainingMaster", "SharedGradientsTrainingMaster",
    "TrainingMaster", "bitmap_decode", "bitmap_encode",
    "global_device_mesh", "gpipe", "initialize_distributed", "make_mesh",
    "megatron_dense_rule", "ring_self_attention", "shard_batch",
    "stack_stage_params", "threshold_decode", "threshold_encode",
    "ShardedTrainer", "shard_params", "zero3_spec", "place_sharded",
    "param_bytes", "per_device_param_bytes",
    "tree_average", "ulysses_attention", "init_moe_params",
    "make_moe_train_step", "moe_ffn", "TrainingMasterStats",
    "RemoteGradientSharing", "encode_message_bytes", "decode_message_bytes",
    "DistributedLayerTrainer",
]
