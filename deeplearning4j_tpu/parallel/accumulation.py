"""Quantized gradient sharing (reference
``optimize/solvers/accumulation/``: ``GradientsAccumulator.java:12``,
``EncodedGradientsAccumulator.java``, ``EncodingHandler.java:138-180`` —
threshold/bitmap encoding with residual carry and adaptive threshold, and
``FancyBlockingQueue.java`` multi-consumer broadcast).

TPU-first framing: *within* a slice, dense all-reduce over ICI is strictly
better than quantization — that path is ``ParallelWrapper``/``pjit`` and no
accumulator is involved.  This module serves the reference's asynchronous
role across the *DCN* boundary (multi-slice / multi-host gossip), where
bandwidth is scarce and 1-bit-style compression pays.  Encode/decode are
jitted device ops (the reference runs them as native libnd4j kernels).

Encoding semantics (mirrors ``Nd4j.getExecutioner().thresholdEncode``):
values with ``|g| >= t`` are transmitted as ``sign * t``; the remainder —
including the clipped excess ``g - sign*t`` of transmitted values — stays in
the sender's residual and re-accumulates into later rounds, so nothing is
ever lost (just delayed).
"""
from __future__ import annotations

import functools
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["threshold_encode", "threshold_decode", "bitmap_encode",
           "bitmap_decode", "EncodingHandler", "EncodedGradientsAccumulator"]


@functools.partial(jax.jit, static_argnames=("k",))  # graftlint: disable=JX028  (gradient-codec kernel on the host exchange path; not a model program)
def _threshold_encode_flat(flat, threshold, k: int):
    """Top-k thresholded sparsification.  Returns (idx[k], signs[k], count,
    residual).  Entries beyond ``count`` are padding (idx == -1)."""
    mags = jnp.abs(flat)
    over = mags >= threshold
    count = jnp.sum(over.astype(jnp.int32))
    # rank by magnitude so a too-small k keeps the largest entries
    vals, idx = jax.lax.top_k(jnp.where(over, mags, -1.0), k)
    valid = vals > 0
    take = jnp.minimum(count, k)
    idx = jnp.where(valid, idx, -1)
    signs = jnp.where(valid, jnp.sign(flat[jnp.where(idx >= 0, idx, 0)]), 0.0)
    # residual: subtract the transmitted ±t at transmitted positions
    delta = jnp.zeros_like(flat).at[jnp.where(idx >= 0, idx, 0)].add(
        jnp.where(valid, signs * threshold, 0.0))
    return idx, signs.astype(jnp.int8), take, flat - delta


def threshold_encode(flat, threshold: float, max_elements: Optional[int] = None
                     ) -> Tuple[Dict[str, Any], jnp.ndarray]:
    """Encode a flat float vector; returns (message, residual)."""
    flat = jnp.asarray(flat)
    k = int(max_elements or max(1, flat.size // 16))
    idx, signs, count, residual = _threshold_encode_flat(
        flat, jnp.asarray(threshold, flat.dtype), k)
    n = int(count)
    msg = {"kind": "threshold", "size": int(flat.size),
           "threshold": float(threshold),
           "idx": np.asarray(idx)[:n], "signs": np.asarray(signs)[:n]}
    return msg, residual


def threshold_decode(msg: Dict[str, Any]) -> jnp.ndarray:
    out = np.zeros(msg["size"], np.float32)
    out[msg["idx"]] = msg["signs"].astype(np.float32) * msg["threshold"]
    return jnp.asarray(out)


@jax.jit  # graftlint: disable=JX028  (gradient-codec kernel on the host exchange path; not a model program)
def _bitmap_encode_flat(flat, threshold):
    """2-bit dense codes (0 none, 1 +t, 2 -t) packed 4/byte."""
    codes = jnp.where(flat >= threshold, 1,
                      jnp.where(flat <= -threshold, 2, 0)).astype(jnp.uint8)
    residual = flat - jnp.where(codes == 1, threshold,
                                jnp.where(codes == 2, -threshold, 0.0))
    pad = (-codes.size) % 4
    padded = jnp.concatenate([codes, jnp.zeros((pad,), jnp.uint8)])
    quads = padded.reshape(-1, 4)
    packed = (quads[:, 0] | (quads[:, 1] << 2) | (quads[:, 2] << 4)
              | (quads[:, 3] << 6))
    return packed, residual


def bitmap_encode(flat, threshold: float) -> Tuple[Dict[str, Any], jnp.ndarray]:
    flat = jnp.asarray(flat)
    packed, residual = _bitmap_encode_flat(
        flat, jnp.asarray(threshold, flat.dtype))
    return ({"kind": "bitmap", "size": int(flat.size),
             "threshold": float(threshold), "packed": np.asarray(packed)},
            residual)


def bitmap_decode(msg: Dict[str, Any]) -> jnp.ndarray:
    packed = msg["packed"]
    quads = np.stack([(packed >> s) & 0x3 for s in (0, 2, 4, 6)], axis=1)
    codes = quads.reshape(-1)[:msg["size"]]
    t = msg["threshold"]
    return jnp.asarray(np.where(codes == 1, t,
                                np.where(codes == 2, -t, 0.0)).astype(np.float32))


def decode(msg: Dict[str, Any]) -> jnp.ndarray:
    return (threshold_decode if msg["kind"] == "threshold"
            else bitmap_decode)(msg)


class EncodingHandler:
    """Adaptive-threshold encoder with residual carry (reference
    ``EncodingHandler.java``: threshold selection + decay, and the
    threshold-vs-bitmap switch at 1/16 density).

    One handler per worker; ``encode_update`` takes the worker's raw gradient
    pytree-flattened vector, adds the residual, and emits a message.
    """

    DENSITY_SWITCH = 1.0 / 16.0  # bitmap cheaper above this (2 bits/elem)

    def __init__(self, initial_threshold: float = 1e-3,
                 min_threshold: float = 1e-9, decay: float = 0.95,
                 boost: float = 1.2, target_density: float = 1e-2,
                 backend: str = "device"):
        self.threshold = initial_threshold
        self.min_threshold = min_threshold
        self.decay = decay
        self.boost = boost
        self.target_density = target_density
        if backend not in ("device", "host"):
            raise ValueError("backend must be 'device' (jit) or 'host' "
                             "(native C++ codec)")
        self.backend = backend
        self.residual: Optional[jnp.ndarray] = None
        self.last_density = 0.0

    def _encode_host(self, flat: np.ndarray) -> Dict[str, Any]:
        """C++ codec path (``utils/native.py``): compress on host CPU right
        before the NIC — the DCN deployment shape, no device round-trip."""
        from ..utils.native import (bitmap_encode_native,
                                    threshold_encode_native)
        density = float(np.mean(np.abs(flat) >= self.threshold))
        self.last_density = density
        if density > self.DENSITY_SWITCH:
            packed, residual = bitmap_encode_native(flat, self.threshold)
            msg = {"kind": "bitmap", "size": int(flat.size),
                   "threshold": float(self.threshold), "packed": packed}
        else:
            idx, signs, residual = threshold_encode_native(
                flat, self.threshold, max(1, flat.size // 16))
            msg = {"kind": "threshold", "size": int(flat.size),
                   "threshold": float(self.threshold),
                   "idx": idx, "signs": signs}
        # stays numpy: the whole point of the host backend is no device
        # round-trip for residual bookkeeping
        self.residual = residual
        return msg

    def encode_update(self, flat_grad) -> Dict[str, Any]:
        if self.backend == "host":
            flat = np.asarray(flat_grad, np.float32)
            if self.residual is not None:
                flat = flat + np.asarray(self.residual, np.float32)
            msg = self._encode_host(flat)
            self._adapt()
            return msg
        flat = jnp.asarray(flat_grad)
        if self.residual is not None:
            flat = flat + self.residual
        density = float(jnp.mean((jnp.abs(flat) >= self.threshold)
                                 .astype(jnp.float32)))
        self.last_density = density
        if density > self.DENSITY_SWITCH:
            msg, self.residual = bitmap_encode(flat, self.threshold)
        else:
            msg, self.residual = threshold_encode(flat, self.threshold)
        self._adapt()
        return msg

    def _adapt(self) -> None:
        """Too sparse -> decay threshold; too dense -> boost."""
        if self.last_density < self.target_density / 10.0:
            self.threshold = max(self.threshold * self.decay,
                                 self.min_threshold)
        elif self.last_density > self.target_density * 10.0:
            self.threshold *= self.boost


class EncodedGradientsAccumulator:
    """Decentralized multi-worker update exchange (reference
    ``EncodedGradientsAccumulator.java`` + ``FancyBlockingQueue``): each
    worker ``store_update``s its encoded gradient, which fans out to every
    *other* worker's queue; workers drain with ``apply_updates`` before their
    next local step.  No master, no barrier — stale updates are applied late,
    residuals guarantee eventual delivery.
    """

    def __init__(self, n_workers: int, handler_factory=EncodingHandler,
                 queue_limit: int = 64):
        self.n_workers = n_workers
        self.handlers = [handler_factory() for _ in range(n_workers)]
        self.queues: List["queue.Queue"] = [queue.Queue(maxsize=queue_limit)
                                            for _ in range(n_workers)]
        self._lock = threading.Lock()
        self.messages_sent = 0
        self.bytes_sent = 0

    @staticmethod
    def _msg_bytes(msg: Dict[str, Any]) -> int:
        if msg["kind"] == "threshold":
            return msg["idx"].nbytes + msg["signs"].nbytes + 16
        return msg["packed"].nbytes + 16

    def store_update(self, worker_id: int, flat_grad) -> Dict[str, Any]:
        """Encode this worker's gradient and broadcast to peers."""
        msg = self.handlers[worker_id].encode_update(flat_grad)
        with self._lock:
            self.messages_sent += 1
            self.bytes_sent += self._msg_bytes(msg)
        for w in range(self.n_workers):
            if w != worker_id:
                self.queues[w].put(msg)
        return msg

    def apply_updates(self, worker_id: int, flat_params) -> jnp.ndarray:
        """Drain this worker's queue; returns params + sum(decoded peers)."""
        total = None
        while True:
            try:
                msg = self.queues[worker_id].get_nowait()
            except queue.Empty:
                break
            dec = decode(msg)
            total = dec if total is None else total + dec
        if total is None:
            return jnp.asarray(flat_params)
        return jnp.asarray(flat_params) + total

    def has_anything(self, worker_id: int) -> bool:
        return not self.queues[worker_id].empty()
