"""TrainingMaster orchestration over real OS processes.

The in-process masters (``parallel/master.py``) prove the averaging /
shared-gradients *semantics* with thread replicas; this module runs the same
contracts with workers as separate processes — the reference's driver +
executor-JVM topology (``ParameterAveragingTrainingMaster.java:62``,
``SharedTrainingWrapper.java:48``).  Coordination rides the
``TcpMessageBroker`` hub (the Aeron/Spark-transport role):

- **averaging**: each worker fits its shard ``averaging_frequency`` batches
  per round, publishes its raveled params (+ optionally updater state) as a
  dense frame, then waits for the master's averaged frame — a synchronous
  parameter-averaging barrier across processes.
- **shared**: workers exchange threshold-quantized param-updates peer-to-peer
  through ``RemoteGradientSharing`` (the SilentUpdatesMessage wire format) —
  no barrier; the master collects worker 0's final table.

``evaluate`` / ``score`` fan the dataset out over worker processes which
return partial ``Evaluation`` JSON / loss sums for the master to merge
(the ``SparkDl4jMultiLayer.evaluate``/``calculateScore`` map-reduce).

Workers are spawned as ``python -m deeplearning4j_tpu.parallel.master_mp``
with a job directory holding the serialized model, the shard, and a spec;
the test rig (tests/test_masters_mp.py) pins workers to CPU devices so the
whole topology is provable without TPU hardware — the reference's
``local[N]`` posture (``BaseSparkTest.java:46``).
"""
from __future__ import annotations

import io
import json
import os
import struct
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["MultiprocessMaster"]

_UP = "mp.up"          # worker -> master dense frames (averaging rounds)
_DOWN = "mp.down"      # master -> workers averaged frame
_FINAL = "mp.final"    # shared mode: final tables
_DONE = "mp.done"      # per-worker result json
_GRADS = "mp.grads"    # shared mode: quantized updates (RemoteGradientSharing)


def _encode_frame(wid: int, rnd: int, vec: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(vec))
    return struct.pack("<ii", wid, rnd) + buf.getvalue()


def _decode_frame(data: bytes):
    wid, rnd = struct.unpack_from("<ii", data)
    vec = np.load(io.BytesIO(data[8:]), allow_pickle=False)
    return wid, rnd, vec


def _ravel(model, with_opt: bool):
    from jax.flatten_util import ravel_pytree
    flat_p, unravel_p = ravel_pytree(model.params)
    if not with_opt:
        return np.asarray(flat_p), (unravel_p, None, flat_p.size)
    flat_o, unravel_o = ravel_pytree(model.opt_state)
    vec = np.concatenate([np.asarray(flat_p), np.asarray(flat_o)])
    return vec, (unravel_p, unravel_o, flat_p.size)


def _unravel_into(model, vec, meta) -> None:
    import jax.numpy as jnp
    unravel_p, unravel_o, n_p = meta
    vec = jnp.asarray(vec)
    model.params = unravel_p(vec[:n_p])
    if unravel_o is not None:
        model.opt_state = unravel_o(vec[n_p:])


def _save_batches(path: str, batches: List[Any]) -> None:
    arrs = {}
    for i, (x, y) in enumerate(batches):
        arrs[f"x{i}"] = np.asarray(x)
        arrs[f"y{i}"] = np.asarray(y)
    np.savez(path, n=np.int64(len(batches)), **arrs)


def _load_batches(path: str):
    z = np.load(path)
    return [(z[f"x{i}"], z[f"y{i}"]) for i in range(int(z["n"]))]


class MultiprocessMaster:
    """Orchestrates N worker processes training one model.

    ``mode``: "averaging" (ParameterAveraging contract) or "shared"
    (SharedGradients / quantized peer-to-peer contract).
    ``worker_env``: extra env vars for workers (the test rig passes
    ``JAX_PLATFORMS=cpu``; production hosts would pass their chip topology).
    """

    def __init__(self, num_workers: int = 2, mode: str = "averaging",
                 averaging_frequency: int = 5, average_updaters: bool = True,
                 threshold: float = 1e-3, timeout: float = 300.0,
                 worker_env: Optional[Dict[str, str]] = None):
        if mode not in ("averaging", "shared"):
            raise ValueError(f"unknown mode {mode!r}")
        self.num_workers = num_workers
        self.mode = mode
        self.averaging_frequency = max(1, averaging_frequency)
        self.average_updaters = average_updaters
        self.threshold = threshold
        self.timeout = timeout
        self.worker_env = dict(worker_env or {})
        self.last_results: List[Dict[str, Any]] = []

    # -- plumbing ------------------------------------------------------------
    def _spawn(self, jobdir: str, wid: int, port: int) -> subprocess.Popen:
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root   # drops any TPU sitecustomize hook
        env.update(self.worker_env)
        log = open(os.path.join(jobdir, f"worker_{wid}.log"), "w")
        p = subprocess.Popen(
            [sys.executable, "-m", "deeplearning4j_tpu.parallel.master_mp",
             jobdir, str(wid), str(port)],
            env=env, stdout=log, stderr=subprocess.STDOUT)
        p._logfile = log
        return p

    def _run_job(self, model, jobdir: str, spec: Dict[str, Any],
                 setup, run):
        """Write the job, serve the broker, create master-side subscriptions
        (``setup`` — BEFORE any worker can publish, the broker retains
        nothing), spawn workers, run the master protocol (``run``), join
        workers, return its result."""
        from ..streaming.broker import TcpMessageBroker
        from ..utils import model_serializer

        model_serializer.write_model(model, os.path.join(jobdir, "model.zip"))
        broker = TcpMessageBroker().serve()
        spec = dict(spec, port=broker.port, num_workers=self.num_workers,
                    averaging_frequency=self.averaging_frequency,
                    average_updaters=self.average_updaters,
                    threshold=self.threshold, timeout=self.timeout)
        with open(os.path.join(jobdir, "spec.json"), "w") as f:
            json.dump(spec, f)
        done_sub = broker.subscribe(_DONE)
        subs = setup(broker)
        procs = [self._spawn(jobdir, w, broker.port)
                 for w in range(self.num_workers)]
        self._procs = procs
        try:
            out = run(broker, subs)
            results: Dict[int, Dict[str, Any]] = {}
            deadline = time.time() + self.timeout
            while len(results) < self.num_workers:
                payload = done_sub.poll(timeout=1.0)
                if payload is not None:
                    r = json.loads(payload.decode())
                    results[int(r["wid"])] = r
                    continue
                self._check_liveness(jobdir)
                if time.time() > deadline:
                    raise RuntimeError(
                        "workers did not report: "
                        + self._logs_tail(jobdir))
            for w, p in enumerate(procs):
                rc = p.wait(timeout=30)
                if rc != 0:
                    raise RuntimeError(f"worker {w} rc={rc}: "
                                       + self._logs_tail(jobdir))
            self.last_results = [results[w] for w in range(self.num_workers)]
            return out
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p._logfile.close()
            broker.shutdown()

    def _logs_tail(self, jobdir: str) -> str:
        outs = []
        for w in range(self.num_workers):
            path = os.path.join(jobdir, f"worker_{w}.log")
            if os.path.exists(path):
                with open(path) as f:
                    outs.append(f"[worker {w}] " + f.read()[-2000:])
        return "\n".join(outs)

    def _check_liveness(self, jobdir: str) -> None:
        """Fail fast when a worker is already dead instead of burning the
        full collection timeout."""
        for w, p in enumerate(getattr(self, "_procs", ())):
            rc = p.poll()
            if rc is not None and rc != 0:
                raise RuntimeError(f"worker {w} died (rc={rc}): "
                                   + self._logs_tail(jobdir))

    def _collect(self, sub, want: int, what: str, jobdir: str):
        frames: Dict[int, np.ndarray] = {}
        deadline = time.time() + self.timeout
        while len(frames) < want:
            payload = sub.poll(timeout=1.0)
            if payload is not None:
                wid, _, vec = _decode_frame(payload)
                frames[wid] = vec
                continue
            self._check_liveness(jobdir)
            if time.time() > deadline:
                raise RuntimeError(f"timed out collecting {what}: "
                                   + self._logs_tail(jobdir))
        return frames

    def _prepare_jobdir(self, iterator, jobdir: Optional[str]):
        """Materialize the job directory + per-worker shards (shared by the
        fit and evaluate/score fan-outs so sharding can't diverge)."""
        import tempfile

        from .master import _chunk_batches

        jobdir = jobdir or tempfile.mkdtemp(prefix="dl4j_mp_")
        os.makedirs(jobdir, exist_ok=True)
        parts = _chunk_batches(iterator, self.num_workers)
        for w, part in enumerate(parts):
            _save_batches(os.path.join(jobdir, f"shard_{w}.npz"), part)
        return jobdir, parts

    # -- training ------------------------------------------------------------
    def fit(self, model, iterator, jobdir: Optional[str] = None) -> None:
        jobdir, parts = self._prepare_jobdir(iterator, jobdir)
        n_rounds = (max((len(p) for p in parts), default=0)
                    + self.averaging_frequency - 1) // self.averaging_frequency
        _, meta = _ravel(model, self.average_updaters
                         and self.mode == "averaging")

        def setup(broker):
            return broker.subscribe(
                _UP if self.mode == "averaging" else _FINAL)

        def run(broker, sub):
            if self.mode == "averaging":
                last = None
                for rnd in range(n_rounds):
                    frames = self._collect(sub, self.num_workers,
                                           f"round {rnd}", jobdir)
                    last = np.mean([frames[w] for w in sorted(frames)],
                                   axis=0)
                    broker.publish(_DOWN, _encode_frame(-1, rnd, last))
                return last
            frames = self._collect(sub, self.num_workers, "final tables",
                                   jobdir)
            return frames[0]   # worker 0's table IS the model (no master copy)

        spec = {"task": "fit", "mode": self.mode, "n_rounds": n_rounds}
        vec = self._run_job(model, jobdir, spec, setup, run)
        if vec is not None:
            _unravel_into(model, vec, meta)

    # -- evaluation / scoring fan-out ---------------------------------------
    def _fan_out_task(self, model, iterator, task: str,
                      jobdir: Optional[str]):
        jobdir, _ = self._prepare_jobdir(iterator, jobdir)
        self._run_job(model, jobdir, {"task": task, "mode": self.mode},
                      lambda broker: None, lambda broker, subs: None)
        return self.last_results

    def evaluate(self, model, iterator, jobdir: Optional[str] = None):
        """Distributed classification evaluation: per-process partial
        ``Evaluation`` objects merged on the master."""
        from ..evaluation.classification import Evaluation
        results = self._fan_out_task(model, iterator, "evaluate", jobdir)
        merged = Evaluation()
        for r in results:
            if r.get("evaluation"):
                merged.merge(Evaluation.from_json(r["evaluation"]))
        return merged

    def score(self, model, iterator, average: bool = True,
              jobdir: Optional[str] = None) -> float:
        results = self._fan_out_task(model, iterator, "score", jobdir)
        total = sum(r["loss_sum"] for r in results)
        n = sum(r["n_examples"] for r in results)
        return total / max(n, 1) if average else total


# --------------------------------------------------------------------- worker
def _worker_main(jobdir: str, wid: int, port: int) -> None:
    with open(os.path.join(jobdir, "spec.json")) as f:
        spec = json.load(f)

    from ..streaming.broker import TcpMessageBroker
    from ..utils import model_serializer

    broker = TcpMessageBroker(port=port)    # client endpoints only
    model = model_serializer.restore_multi_layer_network(
        os.path.join(jobdir, "model.zip"))
    batches = _load_batches(os.path.join(jobdir, f"shard_{wid}.npz"))
    result: Dict[str, Any] = {"wid": wid, "steps": 0}

    task = spec["task"]
    if task == "fit" and spec["mode"] == "averaging":
        down = broker.subscribe(_DOWN)      # subscribe BEFORE first publish
        _, meta = _ravel(model, spec["average_updaters"])
        freq = spec["averaging_frequency"]
        for rnd in range(spec["n_rounds"]):
            for batch in batches[rnd * freq:(rnd + 1) * freq]:
                model.fit_batch(batch)
                result["steps"] += 1
            vec, _ = _ravel(model, spec["average_updaters"])
            broker.publish(_UP, _encode_frame(wid, rnd, vec))
            # barrier timeout rides the master's configured deadline so a
            # fast worker can't abort a round the master would still accept
            payload = down.poll(timeout=float(spec["timeout"]))
            if payload is None:
                raise RuntimeError(f"worker {wid}: no averaged frame")
            _, got_rnd, avg = _decode_frame(payload)
            assert got_rnd == rnd, (got_rnd, rnd)
            _unravel_into(model, avg, meta)
    elif task == "fit":                     # shared gradients
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        from .accumulation import EncodingHandler
        from .remote import RemoteGradientSharing

        sharing = RemoteGradientSharing(
            broker, wid, topic=_GRADS,
            handler=EncodingHandler(initial_threshold=spec["threshold"]))
        time.sleep(0.5)   # let every peer's subscription reach the hub
        for batch in batches:
            flat_before, unravel = ravel_pytree(model.params)
            flat_before = jnp.array(flat_before)
            model.fit_batch(batch)
            result["steps"] += 1
            flat_after, _ = ravel_pytree(model.params)
            sharing.publish_update(flat_after - flat_before)
            merged = sharing.apply_updates(flat_after, timeout=0.05)
            model.params = unravel(merged)
        # settle: drain stragglers so every process converges
        time.sleep(1.0)
        flat, unravel = ravel_pytree(model.params)
        model.params = unravel(sharing.apply_updates(flat, timeout=0.5))
        vec, _ = _ravel(model, False)
        broker.publish(_FINAL, _encode_frame(wid, 0, vec))
        result["messages_sent"] = sharing.messages_sent
        result["messages_applied"] = sharing.messages_applied
    elif task == "evaluate":
        from ..evaluation.classification import Evaluation
        ev = Evaluation()
        for x, y in batches:
            ev.eval(np.asarray(y), np.asarray(model.output(x)))
        result["evaluation"] = ev.to_json()
        result["n_examples"] = int(sum(np.asarray(x).shape[0]
                                       for x, _ in batches))
    elif task == "score":
        total, n = 0.0, 0
        for x, y in batches:
            bs = int(np.asarray(x).shape[0])
            total += model.score(x=x, y=y) * bs
            n += bs
        result["loss_sum"] = total
        result["n_examples"] = n
    else:
        raise ValueError(f"unknown task {task!r}")

    result["score"] = model.get_score() if task == "fit" else None
    broker.publish(_DONE, json.dumps(result).encode())


if __name__ == "__main__":
    _worker_main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
