"""TrainingMaster orchestration over real OS processes.

The in-process masters (``parallel/master.py``) prove the averaging /
shared-gradients *semantics* with thread replicas; this module runs the same
contracts with workers as separate processes — the reference's driver +
executor-JVM topology (``ParameterAveragingTrainingMaster.java:62``,
``SharedTrainingWrapper.java:48``).  Coordination rides the
``TcpMessageBroker`` hub (the Aeron/Spark-transport role):

- **averaging**: each worker fits its shard ``averaging_frequency`` batches
  per round, publishes its raveled params (+ optionally updater state) as a
  dense frame, then waits for the master's averaged frame — a synchronous
  parameter-averaging barrier across processes.
- **shared**: workers exchange threshold-quantized param-updates peer-to-peer
  through ``RemoteGradientSharing`` (the SilentUpdatesMessage wire format).
  Arrival is explicit, never timed (the ``SharedTrainingWrapper.java:48``
  registration posture): every subscription is hub-acked, a ready/go
  barrier gates the first publish, and completion is a drain barrier —
  each worker declares its sent-count on a flush topic — together with a
  dense end-of-job residual frame (the quantizer's undelivered remainder)
  — and peers drain until per-sender applied counts reach the declared
  counts and all residuals are in.  Every final table then equals
  init + Σ(all workers' exact deltas); the master asserts inter-worker
  agreement within a float-noise tolerance and installs the mean.

**Task retry** mirrors Spark's RDD-lineage re-execution
(``ParameterAveragingTrainingMaster.java:62``: a lost partition is simply
recomputed from the broadcast parameters): when a worker process exits
without delivering its contribution — any exit code; rc==0 without a
result is just as dead — the master respawns it with a resume spec:

- averaging: restart at the current round from the last averaged frame
  (exactly the broadcast-params re-execution contract);
- shared: re-execute the full shard via a RESYNC handshake — the
  replacement subscribes (hub-acked) first, then asks the master for a
  seed built from its mirror (init + every quantized update seen, plus
  folded residuals and per-sender sequence counts).  Per-sender FIFO +
  sequence numbers make the seed/subscription overlap dedup exactly: no
  update is lost or double-applied.  Semantically the retry is still
  *at-least-once* over BATCHES (the dead incarnation's transmitted
  updates stay in everyone's tables and the replacement re-trains the
  whole shard), so the final-table agreement assertion is waived for the
  run and recorded in ``last_table_spread = None``.
- evaluate/score: stateless — the shard is simply re-executed.

``evaluate`` / ``score`` fan the dataset out over worker processes which
return partial ``Evaluation`` JSON / loss sums for the master to merge
(the ``SparkDl4jMultiLayer.evaluate``/``calculateScore`` map-reduce).

Workers are spawned as ``python -m deeplearning4j_tpu.parallel.master_mp``
with a job directory holding the serialized model, the shard, and a spec;
the test rig (tests/test_masters_mp.py) pins workers to CPU devices so the
whole topology is provable without TPU hardware — the reference's
``local[N]`` posture (``BaseSparkTest.java:46``).
"""
from __future__ import annotations

import contextlib
import io
import json
import os
import struct
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..observability.clock import monotonic_s
from ..observability.recorder import get_flight_recorder
from ..observability.registry import default_registry
from ..observability.tracer import SpanContext, get_tracer

__all__ = ["MultiprocessMaster"]

_UP = "mp.up"          # worker -> master dense frames (averaging rounds)
_DOWN = "mp.down"      # master -> workers averaged frame
_FINAL = "mp.final"    # shared mode: final tables
_DONE = "mp.done"      # per-worker result json
_GRADS = "mp.grads"    # shared mode: quantized updates (RemoteGradientSharing)
_READY = "mp.ready"    # shared mode: worker subscriptions are hub-acked
_GO = "mp.go"          # shared mode: master saw N readies — publishing may start
_FLUSH = "mp.flush"    # shared mode: per-worker declared sent-counts
_RESID = "mp.resid"    # shared mode: dense end-of-job residual flush
_SEED = "mp.seed"      # shared mode: master -> respawned worker resync seed
_HB = "mp.hb"          # worker -> master heartbeat {wid, steps}
_DEAD = "mp.dead"      # master -> workers: eviction notice {wid}

_HB_INTERVAL_S = 0.5   # worker heartbeat period (lease renewal analogue)


def _encode_frame(wid: int, rnd: int, vec: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(vec))
    return struct.pack("<ii", wid, rnd) + buf.getvalue()


def _decode_frame(data: bytes):
    wid, rnd = struct.unpack_from("<ii", data)
    vec = np.load(io.BytesIO(data[8:]), allow_pickle=False)
    return wid, rnd, vec


def _ravel(model, with_opt: bool):
    from jax.flatten_util import ravel_pytree
    flat_p, unravel_p = ravel_pytree(model.params)
    if not with_opt:
        return np.asarray(flat_p), (unravel_p, None, flat_p.size)
    flat_o, unravel_o = ravel_pytree(model.opt_state)
    vec = np.concatenate([np.asarray(flat_p), np.asarray(flat_o)])
    return vec, (unravel_p, unravel_o, flat_p.size)


def _unravel_into(model, vec, meta) -> None:
    import jax.numpy as jnp
    unravel_p, unravel_o, n_p = meta
    vec = jnp.asarray(vec)
    model.params = unravel_p(vec[:n_p])
    if unravel_o is not None:
        model.opt_state = unravel_o(vec[n_p:])


def _save_batches(path: str, batches: List[Any]) -> None:
    arrs = {}
    for i, (x, y) in enumerate(batches):
        arrs[f"x{i}"] = np.asarray(x)
        arrs[f"y{i}"] = np.asarray(y)
    np.savez(path, n=np.int64(len(batches)), **arrs)


def _load_batches(path: str):
    z = np.load(path)
    return [(z[f"x{i}"], z[f"y{i}"]) for i in range(int(z["n"]))]


class MultiprocessMaster:
    """Orchestrates N worker processes training one model.

    ``mode``: "averaging" (ParameterAveraging contract) or "shared"
    (SharedGradients / quantized peer-to-peer contract).
    ``worker_env``: extra env vars for workers (the test rig passes
    ``JAX_PLATFORMS=cpu``; production hosts would pass their chip topology).
    ``max_task_retries``: per-worker respawn budget before the job fails
    (the Spark task-retry knob; re-execution semantics in the module doc).
    ``fault_injection``: test-only hook — keys ``die_before_publish``
    (averaging, {wid: round}), ``die_after_batches`` (shared, {wid: k}),
    ``die_at_start`` (evaluate/score, [wid]), ``die_before_done`` /
    ``exit_nonzero_after_done`` ([wid]), ``slow_start`` ({wid: seconds}),
    ``hang_after_batches`` ({wid: k}: the training loop wedges after k
    batches while the heartbeat thread keeps beating — the stall
    watchdog's test case) — applied only to a worker's first incarnation.
    ``straggler_timeout_s``: heartbeat-stall watchdog (see attribute doc).
    """

    _DEAD_GRACE = 2.0   # seconds a dead worker's in-flight message may lag
    # subclasses repoint these to reuse the spawn/retry/collect machinery
    # for other job types (nlp/distributed_vectors rides it for Word2Vec)
    _WORKER_MODULE = "deeplearning4j_tpu.parallel.master_mp"
    _STATELESS_TASKS = ("evaluate", "score")   # _DONE is the contribution

    def __init__(self, num_workers: int = 2, mode: str = "averaging",
                 averaging_frequency: int = 5, average_updaters: bool = True,
                 threshold: float = 1e-3, timeout: float = 300.0,
                 worker_env: Optional[Dict[str, str]] = None,
                 max_task_retries: int = 2,
                 agreement_tol: float = 1e-3,
                 workdir: Optional[str] = None,
                 fault_injection: Optional[Dict[str, Any]] = None,
                 retry_backoff_s: float = 0.1, retry_seed: int = 0,
                 straggler_timeout_s: Optional[float] = None):
        from ..faulttolerance.faults import RetryPolicy
        if mode not in ("averaging", "shared"):
            raise ValueError(f"unknown mode {mode!r}")
        self.retry_policy = RetryPolicy(max_retries=max_task_retries,
                                        backoff_s=retry_backoff_s,
                                        seed=retry_seed)
        self.num_workers = num_workers
        self.mode = mode
        self.averaging_frequency = max(1, averaging_frequency)
        self.average_updaters = average_updaters
        self.threshold = threshold
        self.timeout = timeout
        self.worker_env = dict(worker_env or {})
        self.max_task_retries = max_task_retries
        self.agreement_tol = agreement_tol
        self.workdir = workdir   # parent for auto-created job directories
        self.fault_injection = dict(fault_injection or {})
        # heartbeat-stall watchdog (the thread masters' straggler timeout
        # promoted across the process boundary): a worker whose process is
        # alive but whose heartbeats stop carrying progress for longer
        # than this is killed and respawned.  None = off.  Must be sized
        # well past a normal round (training + barrier waits make no
        # "steps" progress while a worker legitimately blocks).
        self.straggler_timeout_s = straggler_timeout_s
        self.last_results: List[Dict[str, Any]] = []
        self.retried_workers: set = set()
        self.last_table_spread: Optional[float] = None
        self.evicted_workers: set = set()

    # -- plumbing ------------------------------------------------------------
    def _spawn(self, jobdir: str, wid: int, port: int,
               resume_file: Optional[str] = None) -> subprocess.Popen:
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        # prepend, never replace, so user-supplied PYTHONPATH dependencies
        # stay importable — EXCEPT entries that inject a sitecustomize
        # interpreter hook: a host hook re-run per worker (e.g. a TPU PJRT
        # relay session claim) breaks worker device pinning, so those are
        # deliberately dropped.  worker_env may still override wholesale.
        prev = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                if p and not os.path.exists(
                    os.path.join(p, "sitecustomize.py"))
                and not os.path.isdir(os.path.join(p, "sitecustomize"))]
        env["PYTHONPATH"] = os.pathsep.join([pkg_root] + prev)
        env.update(self.worker_env)
        log = open(os.path.join(jobdir, f"worker_{wid}.log"), "a")
        argv = [sys.executable, "-m", self._WORKER_MODULE,
                jobdir, str(wid), str(port)]
        if resume_file:
            argv.append(resume_file)
        p = subprocess.Popen(argv, env=env, stdout=log,
                             stderr=subprocess.STDOUT)
        p._logfile = log
        if hasattr(self, "_hb"):
            # (re)arm the stall watchdog for this incarnation: progress
            # clock starts at spawn, steps at -1 (= no beat seen yet)
            self._hb[wid] = [monotonic_s(), -1]
        return p

    def _run_job(self, model, jobdir: str, spec: Dict[str, Any],
                 setup, run,
                 resume_payload: Optional[
                     Callable[[int], Tuple[Dict[str, Any],
                                           Optional[np.ndarray]]]] = None):
        """Write the job, serve the broker, create master-side subscriptions
        (``setup`` — BEFORE any worker can publish, the broker retains
        nothing), spawn workers, run the master protocol (``run``), join
        workers, return its result.  ``resume_payload(wid)`` builds the
        (resume-spec, frame) a respawned worker restarts from."""
        from ..streaming.broker import TcpMessageBroker

        self._write_job(model, jobdir)
        # max_queue=0: the master protocol is a reliable transport (the
        # Aeron role) — exact-count drain barriers need lossless delivery;
        # memory is bounded by job size
        broker = TcpMessageBroker(max_queue=0).serve()
        # span-context propagation to worker PROCESSES: the context rides
        # the job spec; each worker re-roots its local spans under it
        # (inert when tracing is off — ctx is None)
        ctx = get_tracer().current_context()
        spec = dict(spec, port=broker.port, num_workers=self.num_workers,
                    averaging_frequency=self.averaging_frequency,
                    average_updaters=self.average_updaters,
                    threshold=self.threshold, timeout=self.timeout,
                    fault=self.fault_injection,
                    trace=None if ctx is None else ctx.to_dict())
        with open(os.path.join(jobdir, "spec.json"), "w") as f:
            json.dump(spec, f)
        done_sub = broker.subscribe(_DONE)
        # heartbeat intake: registered before any worker can beat
        self._hb_sub = broker.subscribe(_HB)
        # wid -> [last_progress_monotonic_s, steps]; seeded at spawn so a
        # worker that wedges before its first beat still trips the watchdog
        self._hb: Dict[int, List[float]] = {}
        subs = setup(broker)
        self._broker = broker
        self._port = broker.port
        self._resume_payload = resume_payload
        self._retries: Dict[int, int] = {}
        self._dead_since: Dict[int, float] = {}
        self.retried_workers = set()
        self.evicted_workers = set()
        self._procs: Dict[int, subprocess.Popen] = {
            w: self._spawn(jobdir, w, broker.port)
            for w in range(self.num_workers)}
        try:
            out = run(broker, subs)
            if spec["task"] not in self._STATELESS_TASKS:
                # every fit contribution is in; a worker respawned from
                # here on only needs to report (for stateless tasks the
                # _DONE message IS the contribution — full re-execution)
                self._resume_payload = \
                    lambda wid: ({"skip_to_done": True}, None)
            results: Dict[int, Dict[str, Any]] = {}
            deadline = time.time() + self.timeout
            while len(results) < self.num_workers:
                payload = done_sub.poll(timeout=0.25)
                if payload is not None:
                    r = json.loads(payload.decode())
                    results[int(r["wid"])] = r
                    continue
                if self._check_liveness(jobdir, satisfied=results.keys()):
                    deadline = time.time() + self.timeout
                if time.time() > deadline:
                    raise RuntimeError(
                        "workers did not report: "
                        + self._logs_tail(jobdir))
            for w, p in self._procs.items():
                rc = p.wait(timeout=30)
                if rc != 0:
                    # its contribution was already received (the results
                    # loop completed), so a teardown crash doesn't fail
                    # the job — record it for the caller instead
                    results[w]["exit_code"] = rc
            self.last_results = [results[w] for w in range(self.num_workers)]
            return out
        finally:
            for p in self._procs.values():
                if p.poll() is None:
                    p.kill()
                p._logfile.close()
            broker.shutdown()

    def _write_job(self, model, jobdir: str) -> None:
        """Serialize the trainee into the job directory (subclasses swap
        the serialization format for their model family)."""
        from ..utils import model_serializer
        model_serializer.write_model(model, os.path.join(jobdir, "model.zip"))

    def _logs_tail(self, jobdir: str) -> str:
        outs = []
        for w in range(self.num_workers):
            path = os.path.join(jobdir, f"worker_{w}.log")
            if os.path.exists(path):
                with open(path) as f:
                    outs.append(f"[worker {w}] " + f.read()[-2000:])
        return "\n".join(outs)

    def _drain_heartbeats(self) -> None:
        """Fold pending worker heartbeats into the watchdog state and the
        ``cluster_heartbeat_age_seconds`` gauge.  The progress clock only
        advances when ``steps`` moves: a wedged worker whose heartbeat
        thread still beats (but whose training loop is stuck) ages out
        exactly like a silent one."""
        sub = getattr(self, "_hb_sub", None)
        if sub is None:
            return
        now = monotonic_s()
        rec = get_flight_recorder()
        while True:
            payload = sub.poll(timeout=0.001)
            if payload is None:
                break
            try:
                d = json.loads(payload.decode())
                wid, steps = int(d["wid"]), int(d.get("steps", 0))
            except (ValueError, KeyError):
                wid = None    # malformed beat (foreign payload): ignore
            if wid is None:
                continue
            cur = self._hb.get(wid)
            if cur is None or steps > cur[1]:
                self._hb[wid] = [now, steps]
                if rec is not None:
                    # the heartbeat trail is what an eviction dump replays
                    rec.record("cluster", "heartbeat", worker=wid,
                               steps=steps)
        reg = default_registry()
        if reg.enabled and self._hb:
            age = reg.gauge("cluster_heartbeat_age_seconds",
                            "Seconds since a worker last made heartbeat "
                            "progress", ("worker",))
            for wid, (t, _) in self._hb.items():
                age.labels(str(wid)).set(max(0.0, now - t))

    def _check_liveness(self, jobdir: str, satisfied=()) -> bool:
        """Respawn workers that exited — ANY exit code — without delivering
        the contribution the current phase is collecting (``satisfied``).
        A short grace window lets a just-published in-flight message land
        before the respawn triggers.  With ``straggler_timeout_s`` set, a
        worker whose process is ALIVE but whose heartbeats stopped
        carrying progress for longer than the timeout is killed and
        respawned too (the thread masters' straggler watchdog, fed by
        process heartbeats).  Returns True when someone was respawned
        (callers extend their deadline: the replacement redoes work)."""
        self._drain_heartbeats()
        respawned = False
        now = monotonic_s()
        reg = default_registry()
        # registry child resolved BEFORE the per-worker loop (JX022: the
        # cached-child idiom — name/label lookups don't belong in loops)
        evict_c = reg.counter(
            "cluster_evictions_total",
            "Workers evicted from the membership view",
            ("reason",)).labels("heartbeat_stall") if reg.enabled else None
        for wid, p in list(self._procs.items()):
            if p.poll() is None or wid in satisfied:
                self._dead_since.pop(wid, None)
                if p.poll() is None and wid not in satisfied and \
                        self.straggler_timeout_s is not None:
                    hb = self._hb.get(wid)
                    if hb is not None and \
                            now - hb[0] > self.straggler_timeout_s:
                        if evict_c is not None:
                            evict_c.inc()
                        self.evicted_workers.add(wid)
                        self._record_eviction(wid, hb, now, jobdir)
                        p.kill()
                        p.wait(timeout=30)
                        self._respawn(wid, jobdir)
                        respawned = True
                continue
            first = self._dead_since.setdefault(wid, now)
            if now - first < self._DEAD_GRACE:
                continue
            self._dead_since.pop(wid, None)
            self._respawn(wid, jobdir)
            respawned = True
        return respawned

    def _record_eviction(self, wid: int, hb, now: float,
                         jobdir: str) -> None:
        """Watchdog eviction forensics: the coordinator commits the
        flight-recorder window (incl. the evicted worker's heartbeat
        trail on the cluster channel) into the job directory — the
        artifact that says WHY worker ``wid`` was killed, written by the
        surviving side before the respawn even starts."""
        rec = get_flight_recorder()
        if rec is None or not rec.enabled:
            return
        rec.record("cluster", "watchdog_eviction", worker=wid,
                   stalled_s=round(now - hb[0], 3), steps=hb[1],
                   timeout_s=self.straggler_timeout_s)
        rec.maybe_dump("watchdog_eviction", directory=jobdir)

    def _respawn(self, wid: int, jobdir: str) -> None:
        n = self._retries.get(wid, 0) + 1
        reg = default_registry()
        if n > self.max_task_retries:
            # the mp topology has no surviving-replica pool to re-chunk a
            # shard onto mid-protocol (the averaging barrier counts all N
            # workers), so an exhausted budget fails the job — recorded as
            # a lost worker for the shared fleet dashboards
            if reg.enabled:
                reg.counter("training_worker_lost_total",
                            "Workers permanently lost (retries/straggler "
                            "budget exhausted)", ("mode",)
                            ).labels("mp").inc()
            if self.mode == "shared":
                # eviction notice: surviving peers drop this sender from
                # their drain barriers IMMEDIATELY instead of spinning
                # until their own deadline — an evicted peer never blocks
                # the drain longer than the master's liveness verdict
                try:
                    self._broker.publish(
                        _DEAD, json.dumps({"wid": wid}).encode())
                except (ConnectionError, OSError):
                    pass   # hub teardown is already in flight
            raise RuntimeError(
                f"worker {wid} failed after {n - 1} retries: "
                + self._logs_tail(jobdir))
        self._retries[wid] = n
        self.retried_workers.add(wid)
        if reg.enabled:
            reg.counter("mp_worker_respawns_total",
                        "Dead worker processes respawned by task retry",
                        ("mode",)).labels(self.mode).inc()
            reg.counter("training_worker_retries_total",
                        "Worker round retries in the training masters",
                        ("mode",)).labels("mp").inc()
        # seeded exponential backoff + jitter: a crash-looping host must
        # not be respawned at full tilt (and N masters sharing a node
        # shouldn't stampede in lockstep)
        self.retry_policy.sleep(n, worker=wid)
        old = self._procs[wid]
        if old.poll() is None:
            old.kill()
        old._logfile.close()
        resume, frame = (self._resume_payload(wid)
                         if self._resume_payload else ({}, None))
        resume = dict(resume)
        if frame is not None:
            fnpy = os.path.join(jobdir, f"resume_{wid}_{n}.npy")
            np.save(fnpy, np.asarray(frame))
            resume["frame"] = fnpy
        rf = os.path.join(jobdir, f"resume_{wid}_{n}.json")
        with open(rf, "w") as f:
            json.dump(resume, f)
        self._procs[wid] = self._spawn(jobdir, wid, self._port,
                                       resume_file=rf)

    def _collect_loop(self, sub, want: int, what: str, jobdir: str,
                      decode_fn,
                      on_idle: Optional[Callable[[], None]] = None):
        """One collection loop for every phase: poll, decode (``decode_fn``
        returns ``(wid, value)`` or ``(None, None)`` to skip stale
        payloads), run ``on_idle`` between polls, respawn dead workers
        (extending the deadline — the replacement redoes work)."""
        got: Dict[int, Any] = {}
        deadline = time.time() + self.timeout
        while len(got) < want:
            payload = sub.poll(timeout=0.25)
            if payload is not None:
                wid, value = decode_fn(payload)
                if wid is not None:
                    got[wid] = value
                continue
            if on_idle is not None:
                on_idle()
            if self._check_liveness(jobdir, satisfied=got.keys()):
                deadline = time.time() + self.timeout
            if time.time() > deadline:
                raise RuntimeError(f"timed out collecting {what}: "
                                   + self._logs_tail(jobdir))
        return got

    def _collect(self, sub, want: int, what: str, jobdir: str,
                 rnd: Optional[int] = None,
                 on_idle: Optional[Callable[[], None]] = None):
        """Collect one dense frame per worker; ``rnd`` filters stale frames
        from pre-respawn incarnations."""
        def decode_fn(payload):
            wid, got_rnd, vec = _decode_frame(payload)
            if rnd is not None and got_rnd != rnd:
                return None, None
            return wid, vec
        return self._collect_loop(sub, want, what, jobdir, decode_fn,
                                  on_idle)

    def _collect_json(self, sub, what: str, jobdir: str,
                      on_idle: Optional[Callable[[], None]] = None,
                      sink: Optional[Callable[[int, Dict[str, Any]],
                                              None]] = None
                      ) -> Dict[int, Dict[str, Any]]:
        """Collect one small JSON message per worker (ready / flush);
        ``sink`` observes each message as it lands (the shared master
        mirrors flush declarations for resync seeds)."""
        def decode_fn(payload):
            d = json.loads(payload.decode())
            wid = int(d["wid"])
            if sink is not None:
                sink(wid, d)
            return wid, d
        return self._collect_loop(sub, self.num_workers, what, jobdir,
                                  decode_fn, on_idle)

    def _prepare_jobdir(self, iterator, jobdir: Optional[str]):
        """Materialize the job directory + per-worker shards (shared by the
        fit and evaluate/score fan-outs so sharding can't diverge)."""
        import tempfile

        from .master import _chunk_batches

        if jobdir is None:
            if self.workdir:
                os.makedirs(self.workdir, exist_ok=True)
            jobdir = tempfile.mkdtemp(prefix="dl4j_mp_", dir=self.workdir)
        os.makedirs(jobdir, exist_ok=True)
        parts = _chunk_batches(iterator, self.num_workers)
        for w, part in enumerate(parts):
            _save_batches(os.path.join(jobdir, f"shard_{w}.npz"), part)
        return jobdir, parts

    # -- training ------------------------------------------------------------
    def fit(self, model, iterator, jobdir: Optional[str] = None) -> None:
        with get_tracer().span("mp.fit", mode=self.mode,
                               workers=self.num_workers):
            jobdir, parts = self._prepare_jobdir(iterator, jobdir)
            n_rounds = (max((len(p) for p in parts), default=0)
                        + self.averaging_frequency - 1
                        ) // self.averaging_frequency
            with_opt = self.average_updaters and self.mode == "averaging"
            vec0, meta = _ravel(model, with_opt)

            if self.mode == "averaging":
                vec = self._fit_averaging(model, jobdir, n_rounds,
                                          np.asarray(vec0))
            else:
                vec = self._fit_shared(model, jobdir, np.asarray(vec0))
            if vec is not None:
                _unravel_into(model, vec, meta)

    def _fit_averaging(self, model, jobdir: str, n_rounds: int,
                       vec0: np.ndarray):
        state = {"rnd": 0, "last": vec0}

        def resume_payload(wid):
            # re-execution from the broadcast params: the respawned worker
            # restarts at the round being collected, seeded with the last
            # averaged frame (round 0: the initial model)
            return {"start_round": state["rnd"]}, state["last"]

        def run(broker, sub):
            last = None
            for rnd in range(n_rounds):
                state["rnd"] = rnd
                frames = self._collect(sub, self.num_workers,
                                       f"round {rnd}", jobdir, rnd=rnd)
                last = np.mean([frames[w] for w in sorted(frames)], axis=0)
                state["last"] = last
                broker.publish(_DOWN, _encode_frame(-1, rnd, last))
            # a crash between the last barrier and the _DONE report is
            # handled by _run_job's skip_to_done resume swap
            return last

        spec = {"task": "fit", "mode": "averaging", "n_rounds": n_rounds}
        return self._run_job(model, jobdir, spec,
                             lambda broker: broker.subscribe(_UP),
                             run, resume_payload)

    def _fit_shared(self, model, jobdir: str, vec0: np.ndarray):
        from .accumulation import decode as _decode_update
        from .remote import decode_message_bytes

        state: Dict[str, Any] = {
            "go": False, "broker": None,
            "mirror": vec0.copy(),      # init + every quantized update seen
            "mirror_counts": {},        # per-sender updates in the mirror
            "resid_sum": np.zeros_like(vec0),
            "resid_wids": set(),        # whose residuals resid_sum holds
            "declared": {},             # flush declarations seen so far
            "grads_sub": None, "resid_sub": None, "ready_sub": None,
            "seed_n": 0,
        }

        def drain_mirror(settle: float = 0.001):
            """``settle``: how long a poll gap ends the drain — resync
            seeds use a longer window so an in-flight frame (mid-transfer
            on the subscription socket) lands in the seed rather than
            falling between seed and the replacement's subscription."""
            while True:
                payload = state["grads_sub"].poll(timeout=settle)
                if payload is None:
                    break
                sender, seq, msg = decode_message_bytes(payload)
                state["mirror"] += np.asarray(_decode_update(msg))
                # per-sender FIFO (one publisher connection) makes seqs
                # arrive dense and in order: the highest seen == the count
                # folded into the mirror, which seeds exact dedup
                state["mirror_counts"][sender] = max(
                    state["mirror_counts"].get(sender, 0), seq)
            while True:
                payload = state["resid_sub"].poll(timeout=settle)
                if payload is None:
                    break
                r_wid, _, vec = _decode_frame(payload)
                if r_wid not in state["resid_wids"]:
                    state["resid_wids"].add(r_wid)
                    state["resid_sum"] += vec

        def serve_resyncs():
            """Answer a respawned worker's resync request with a seed:
            mirror + folded residuals, plus the per-sender bookkeeping the
            replacement needs to run an exact drain barrier (module doc).
            The replacement subscribed (hub-acked) BEFORE requesting, so
            everything published after the seed snapshot reaches it
            directly; sequence numbers dedup the overlap exactly."""
            while True:
                payload = state["ready_sub"].poll(timeout=0.001)
                if payload is None:
                    return
                d = json.loads(payload.decode())
                if not d.get("resync"):
                    continue     # stale pre-go READY from a dead worker
                # settle-drain: a frame mid-transfer on the mirror socket
                # must land in the seed (the replacement can't receive it
                # — it was fanned out before its subscription); 50 ms of
                # silence on loopback means nothing is in flight.  If an
                # extreme straggler still slips through, the replacement's
                # drain barrier times out, and the NEXT resync sees it —
                # self-healing at the cost of one retry.
                drain_mirror(settle=0.05)
                w = int(d["wid"])
                state["seed_n"] += 1
                seed_file = os.path.join(
                    jobdir, f"seed_{w}_{state['seed_n']}.npy")
                np.save(seed_file, state["mirror"] + state["resid_sum"])
                meta = {"wid": w, "file": seed_file,
                        "resid_wids": sorted(state["resid_wids"]),
                        "prior_sent": state["mirror_counts"].get(w, 0),
                        "declared": {str(k): v for k, v
                                     in state["declared"].items()},
                        "mirror_counts": {str(k): v for k, v
                                          in state["mirror_counts"].items()}}
                state["broker"].publish(_SEED, json.dumps(meta).encode())

        def on_idle():
            drain_mirror()
            serve_resyncs()

        def resume_payload(wid):
            # pre-go death: nothing was published — a clean restart.
            # post-go death: the replacement bootstraps via resync, so no
            # frame is shipped at spawn time (it would already be stale).
            return ({"restart": True, "go_done": state["go"]}, None)

        def setup(broker):
            state["broker"] = broker
            state["grads_sub"] = broker.subscribe(_GRADS, ack=True)
            state["resid_sub"] = broker.subscribe(_RESID, ack=True)
            state["ready_sub"] = broker.subscribe(_READY)
            return (broker.subscribe(_FLUSH), broker.subscribe(_FINAL))

        def run(broker, subs):
            flush_sub, final_sub = subs
            self._collect_json(state["ready_sub"], "ready barrier", jobdir)
            broker.publish(_GO, b"go")
            state["go"] = True

            def flush_sink(wid, d):
                state["declared"][wid] = int(d["sent"])
            declared = self._collect_json(flush_sub, "flush counts", jobdir,
                                          on_idle=on_idle, sink=flush_sink)
            finals = self._collect(final_sub, self.num_workers,
                                   "final tables", jobdir,
                                   on_idle=on_idle)
            tables = np.stack([finals[w] for w in sorted(finals)])
            if not self.retried_workers:
                # after a clean drain + dense residual flush every table is
                # init + Σ(all exact deltas); remaining spread is float32
                # summation-order noise, so the bound is tight
                del declared  # counts were the barrier, not the check
                spread = float(np.max(tables.max(axis=0) - tables.min(axis=0))
                               ) if len(tables) > 1 else 0.0
                if spread > self.agreement_tol:
                    raise RuntimeError(
                        f"shared-mode final tables diverge: spread "
                        f"{spread:.3e} > agreement_tol "
                        f"{self.agreement_tol:.3e}")
                self.last_table_spread = spread
            else:
                # at-least-once re-execution re-applied updates; agreement
                # is waived for the run (module doc)
                self.last_table_spread = None
            return tables.mean(axis=0)

        spec = {"task": "fit", "mode": "shared"}
        return self._run_job(model, jobdir, spec, setup, run, resume_payload)

    # -- evaluation / scoring fan-out ---------------------------------------
    def _fan_out_task(self, model, iterator, task: str,
                      jobdir: Optional[str]):
        with get_tracer().span(f"mp.{task}", mode=self.mode,
                               workers=self.num_workers):
            jobdir, _ = self._prepare_jobdir(iterator, jobdir)
            # stateless shards: a respawned worker simply re-executes
            self._run_job(model, jobdir, {"task": task, "mode": self.mode},
                          lambda broker: None, lambda broker, subs: None,
                          resume_payload=lambda wid: ({}, None))
            return self.last_results

    def evaluate(self, model, iterator, jobdir: Optional[str] = None):
        """Distributed classification evaluation: per-process partial
        ``Evaluation`` objects merged on the master."""
        from ..evaluation.classification import Evaluation
        results = self._fan_out_task(model, iterator, "evaluate", jobdir)
        merged = Evaluation()
        for r in results:
            if r.get("evaluation"):
                merged.merge(Evaluation.from_json(r["evaluation"]))
        return merged

    def score(self, model, iterator, average: bool = True,
              jobdir: Optional[str] = None) -> float:
        results = self._fan_out_task(model, iterator, "score", jobdir)
        total = sum(r["loss_sum"] for r in results)
        n = sum(r["n_examples"] for r in results)
        return total / max(n, 1) if average else total


# --------------------------------------------------------------------- worker
def _maybe_hang(fault: Dict[str, Any], wid: int, steps: int) -> None:
    """Fault-injection hook (NOT protocol timing): ``hang_after_batches``
    wedges the training loop after ``steps`` batches while the heartbeat
    thread keeps beating with a frozen count — the stall watchdog's
    prey."""
    if fault.get("hang_after_batches", {}).get(str(wid)) == steps:
        time.sleep(3600)


def _start_heartbeat(broker, wid: int,
                     result: Dict[str, Any]) -> threading.Event:
    """Worker-side lease analogue: publish ``{wid, steps}`` on the
    heartbeat topic every ``_HB_INTERVAL_S`` until the returned event is
    set.  ``steps`` rides along so the master's watchdog can tell a
    wedged-but-alive worker (beats arrive, progress doesn't) from a
    healthy one."""
    stop = threading.Event()

    def beat():
        while True:
            try:
                broker.publish(_HB, json.dumps(
                    {"wid": wid,
                     "steps": int(result.get("steps", 0))}).encode())
            except (ConnectionError, OSError):
                return    # hub gone: the master died or is tearing down
            if stop.wait(_HB_INTERVAL_S):
                return

    threading.Thread(target=beat, daemon=True,
                     name=f"mp-heartbeat-{wid}").start()
    return stop


def _worker_main(jobdir: str, wid: int, port: int,
                 resume_file: Optional[str] = None) -> None:
    with open(os.path.join(jobdir, "spec.json")) as f:
        spec = json.load(f)
    # re-root this process's spans under the master's context (from the
    # job spec); a no-op unless the worker enables its tracer (e.g. via
    # DL4J_TPU_TRACE=1 in worker_env)
    tracer = get_tracer()
    with contextlib.ExitStack() as stack:
        ctx = spec.get("trace")
        if ctx:
            stack.enter_context(tracer.attach(SpanContext.from_dict(ctx)))
        stack.enter_context(tracer.span("mp.worker", worker=wid,
                                        task=spec.get("task")))
        _worker_task(jobdir, wid, port, spec, resume_file)


def _worker_task(jobdir: str, wid: int, port: int, spec: Dict[str, Any],
                 resume_file: Optional[str] = None) -> None:
    resumed = resume_file is not None
    resume: Dict[str, Any] = {}
    if resumed:
        with open(resume_file) as f:
            resume = json.load(f)
    fault = {} if resumed else spec.get("fault", {})
    if fault.get("slow_start", {}).get(str(wid)):
        time.sleep(float(fault["slow_start"][str(wid)]))

    from ..streaming.broker import TcpMessageBroker
    from ..utils import model_serializer

    broker = TcpMessageBroker(port=port)    # client endpoints only
    result: Dict[str, Any] = {"wid": wid, "steps": 0, "resumed": resumed}
    hb_stop = _start_heartbeat(broker, wid, result)
    try:
        if resume.get("skip_to_done"):
            # predecessor crashed after its last fit contribution was
            # collected; nothing to redo — just report
            result.update({"skipped": True, "score": None})
            broker.publish(_DONE, json.dumps(result).encode())
            return
        _worker_run(broker, jobdir, wid, spec, resume, fault, result)
    finally:
        hb_stop.set()


def _worker_run(broker, jobdir: str, wid: int, spec: Dict[str, Any],
                resume: Dict[str, Any], fault: Dict[str, Any],
                result: Dict[str, Any]) -> None:
    from ..utils import model_serializer

    model = model_serializer.restore_multi_layer_network(
        os.path.join(jobdir, "model.zip"))
    batches = _load_batches(os.path.join(jobdir, f"shard_{wid}.npz"))

    task = spec["task"]
    if task == "fit" and spec["mode"] == "averaging":
        # hub-acked: registered before the first _UP publish, so the
        # averaged reply cannot race past this subscription
        down = broker.subscribe(_DOWN, ack=True)
        _, meta = _ravel(model, spec["average_updaters"])
        if resume.get("frame"):
            _unravel_into(model, np.load(resume["frame"]), meta)
        freq = spec["averaging_frequency"]
        for rnd in range(int(resume.get("start_round", 0)), spec["n_rounds"]):
            for batch in batches[rnd * freq:(rnd + 1) * freq]:
                model.fit_batch(batch)
                result["steps"] += 1
                _maybe_hang(fault, wid, result["steps"])
            if fault.get("die_before_publish", {}).get(str(wid)) == rnd:
                os._exit(3)
            vec, _ = _ravel(model, spec["average_updaters"])
            broker.publish(_UP, _encode_frame(wid, rnd, vec))
            # barrier timeout rides the master's configured deadline so a
            # fast worker can't abort a round the master would still accept
            payload = down.poll(timeout=float(spec["timeout"]))
            if payload is None:
                raise RuntimeError(f"worker {wid}: no averaged frame")
            _, got_rnd, avg = _decode_frame(payload)
            assert got_rnd == rnd, (got_rnd, rnd)
            _unravel_into(model, avg, meta)
    elif task == "fit":                     # shared gradients
        _worker_shared_fit(broker, model, batches, spec, resume, fault,
                           wid, result)
    elif task == "evaluate":
        if wid in fault.get("die_at_start", []):
            os._exit(3)
        from ..evaluation.classification import Evaluation
        ev = Evaluation()
        for x, y in batches:
            ev.eval(np.asarray(y), np.asarray(model.output(x)))
        result["evaluation"] = ev.to_json()
        result["n_examples"] = int(sum(np.asarray(x).shape[0]
                                       for x, _ in batches))
    elif task == "score":
        if wid in fault.get("die_at_start", []):
            os._exit(3)
        total, n = 0.0, 0
        for x, y in batches:
            bs = int(np.asarray(x).shape[0])
            total += model.score(x=x, y=y) * bs
            n += bs
        result["loss_sum"] = total
        result["n_examples"] = n
    else:
        raise ValueError(f"unknown task {task!r}")

    result["score"] = model.get_score() if task == "fit" else None
    if wid in fault.get("die_before_done", []):
        os._exit(3)
    broker.publish(_DONE, json.dumps(result).encode())
    if wid in fault.get("exit_nonzero_after_done", []):
        os._exit(5)


def _worker_shared_fit(broker, model, batches, spec, resume, fault,
                       wid: int, result: Dict[str, Any]) -> None:
    """Shared-gradients worker protocol — every arrival explicit:

    1. hub-acked subscriptions (gradients, flush, residual, go/seed);
    2. publish READY, wait for the master's GO.  A replacement respawned
       after GO instead performs a RESYNC handshake: having subscribed
       first (hub-acked), it asks the master for a seed — mirror table +
       folded residuals + per-sender sequence counts — so nothing
       published after the seed snapshot can be missed, and the
       seed/subscription overlap is deduped exactly by sequence number;
    3. train, publishing quantized updates and applying peers';
    4. publish FLUSH declaring the TOTAL sent-count (prior incarnations
       included, so peers' count barriers stay exact) and the handler's
       residual as one dense frame (quantization keeps the clipped excess
       at the sender — "delayed, never lost"; job end is where the delay
       runs out, so the remainder ships dense exactly once);
    5. drain until every peer's applied count (plus what the seed already
       contained) reaches its declared count and every peer's residual is
       accounted for, then add the residuals: each table becomes
       init + Σ(all workers' exact deltas), so the master's agreement
       check is a float-noise bound;
    6. publish the final table for the master's agreement check + mean.
    """
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from .accumulation import EncodingHandler
    from .remote import RemoteGradientSharing

    handler = EncodingHandler(initial_threshold=spec["threshold"])
    flush_sub = broker.subscribe(_FLUSH, ack=True)
    resid_sub = broker.subscribe(_RESID, ack=True)
    dead_sub = broker.subscribe(_DEAD, ack=True)
    timeout = float(spec["timeout"])
    post_go_resume = bool(resume.get("go_done"))
    prior_sent = 0
    declared: Dict[int, int] = {}
    mirror_counts: Dict[int, int] = {}
    resids_done: set = set()
    if not post_go_resume:
        sharing = RemoteGradientSharing(broker, wid, topic=_GRADS,
                                        handler=handler, ack=True)
        go_sub = broker.subscribe(_GO, ack=True)
        broker.publish(_READY, json.dumps({"wid": wid}).encode())
        if go_sub.poll(timeout=timeout) is None:
            raise RuntimeError(f"worker {wid}: no GO from master")
    else:
        # resync handshake: subscribe FIRST (hub-acked), then request the
        # seed — updates published after the seed snapshot arrive on the
        # subscription, updates before it are in the seed, and the seed's
        # per-sender counts dedup the overlap exactly (skip_seqs)
        grads_sub_first = broker.subscribe(_GRADS, ack=True)
        seed_sub = broker.subscribe(_SEED, ack=True)
        broker.publish(_READY, json.dumps(
            {"wid": wid, "resync": True}).encode())
        deadline = time.time() + timeout
        meta = None
        while meta is None:
            payload = seed_sub.poll(timeout=1.0)
            if payload is not None:
                d = json.loads(payload.decode())
                if int(d["wid"]) == wid:
                    meta = d
            elif time.time() > deadline:
                raise RuntimeError(f"worker {wid}: no resync seed")
        _, pmeta = _ravel(model, False)
        _unravel_into(model, np.load(meta["file"]), pmeta)
        prior_sent = int(meta["prior_sent"])
        declared = {int(k): int(v) for k, v in meta["declared"].items()}
        mirror_counts = {int(k): int(v)
                         for k, v in meta["mirror_counts"].items()}
        resids_done = set(int(w) for w in meta["resid_wids"])
        sharing = RemoteGradientSharing(
            broker, wid, topic=_GRADS, handler=handler,
            seq_base=prior_sent, skip_seqs=mirror_counts,
            sub=grads_sub_first)
    die_after = fault.get("die_after_batches", {}).get(str(wid))
    for i, batch in enumerate(batches):
        if die_after == i:
            os._exit(3)
        flat_before, unravel = ravel_pytree(model.params)
        flat_before = jnp.array(flat_before)
        model.fit_batch(batch)
        result["steps"] += 1
        _maybe_hang(fault, wid, result["steps"])
        flat_after, _ = ravel_pytree(model.params)
        sharing.publish_update(flat_after - flat_before)
        merged = sharing.apply_updates(flat_after, timeout=0.05)
        model.params = unravel(merged)
    broker.publish(_FLUSH, json.dumps(
        {"wid": wid, "sent": prior_sent + sharing.messages_sent}).encode())
    flat, unravel = ravel_pytree(model.params)
    flat = jnp.asarray(flat)
    resid = sharing.handler.residual
    resid = (np.zeros(int(flat.size), np.float32) if resid is None
             else np.asarray(resid, np.float32))
    broker.publish(_RESID, _encode_frame(wid, 0, resid))
    # drain barrier: applied[p] (+ the seed's mirror_counts[p]) must reach
    # p's declared count and p's residual must be in (directly or folded
    # into the seed) — a respawned peer's re-flush overwrites its declared
    # count (its earlier messages only push applied past it: >= holds).
    # A master eviction notice (_DEAD) marks a peer dead: it drops out of
    # the barrier immediately, so an evicted peer can never hold the
    # survivors hostage until their own deadline.
    resids: Dict[int, np.ndarray] = {}
    deadline = time.time() + timeout
    while True:
        missing = sharing.unresolved_peers(
            declared, spec["num_workers"], mirror_counts=mirror_counts,
            resids_seen=resids, resids_folded=resids_done)
        if not missing:
            break
        payload = flush_sub.poll(timeout=0.05)
        if payload is not None:
            d = json.loads(payload.decode())
            declared[int(d["wid"])] = int(d["sent"])
        payload = resid_sub.poll(timeout=0.05)
        if payload is not None:
            r_wid, _, r_vec = _decode_frame(payload)
            if r_wid != wid and r_wid not in resids_done:
                resids[r_wid] = r_vec
        payload = dead_sub.poll(timeout=0.001)
        if payload is not None:
            sharing.mark_dead(int(json.loads(payload.decode())["wid"]))
        # unbounded drain here: the barrier loop carries its own deadline
        flat = sharing.apply_updates(flat, timeout=0.05, max_messages=0)
        if time.time() > deadline:
            raise RuntimeError(
                f"worker {wid}: drain barrier incomplete, "
                f"missing peers {missing}")
    for p in sorted(resids):
        flat = flat + jnp.asarray(resids[p])
    model.params = unravel(flat)
    vec, _ = _ravel(model, False)
    broker.publish(_FINAL, _encode_frame(wid, 0, vec))
    result["messages_sent"] = sharing.messages_sent
    result["messages_applied"] = sharing.messages_applied
    result["applied_per_peer"] = {
        str(k): v for k, v in sorted(sharing.applied_per_peer.items())}


if __name__ == "__main__":
    _worker_main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
                 sys.argv[4] if len(sys.argv) > 4 else None)
