"""Device mesh construction + sharding rules.

TPU-native replacement for the reference's parallelism plumbing
(``parallelism/ParallelWrapper.java:58``, Spark TrainingMasters): instead of
model replicas + explicit averaging/gradient messages, we lay parameters and
data out over a ``jax.sharding.Mesh`` and let XLA's SPMD partitioner insert
the ICI collectives (psum for DP gradient reduction ≙ averageAndPropagate;
all-gather/reduce-scatter for TP ≙ nothing in the reference — it had no TP).

Axis names (the scaling-book convention):
  data    — batch axis (DP)
  model   — tensor-parallel axis (TP)
  seq     — sequence/context-parallel axis (SP / ring attention)
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"

#: ZeRO-3 layout threshold: param leaves with fewer elements replicate
#: (sharding a bias saves nothing and adds a collective)
DEFAULT_MIN_SHARD_SIZE = 1024


def make_mesh(n_devices: Optional[int] = None, *, dp: Optional[int] = None,
              tp: int = 1, sp: int = 1, devices=None) -> Mesh:
    """Build a (data, model, seq) mesh. dp defaults to filling all devices;
    an explicit ``dp`` smaller than the device count takes the first
    ``dp*tp*sp`` devices (sub-meshes of one device set share trace-cache
    entries, so a dp=2 and a dp=4 run compile from ONE trace)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if dp is None:
        if n_devices % (tp * sp):
            raise ValueError(f"{n_devices} devices not divisible by tp*sp={tp*sp}")
        dp = n_devices // (tp * sp)
    need = dp * tp * sp
    if need > len(devices):
        raise ValueError(
            f"mesh dp*tp*sp = {dp}*{tp}*{sp} = {need} oversubscribes the "
            f"{len(devices)} available device(s) — lower dp (or tp/sp), or "
            "pass more devices=")
    arr = np.array(devices[:need]).reshape(dp, tp, sp)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS, SEQ_AXIS))


def batch_spec(ndim: int, *, seq_axis: Optional[int] = None) -> P:
    """Shard axis 0 over data; optionally a time axis over seq."""
    spec = [None] * ndim
    spec[0] = DATA_AXIS
    if seq_axis is not None and ndim > seq_axis:
        spec[seq_axis] = SEQ_AXIS
    return P(*spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, x, *, seq_axis: Optional[int] = None):
    if x is None:
        return None
    sh = NamedSharding(mesh, batch_spec(np.ndim(x), seq_axis=seq_axis))
    return place_sharded(x, sh)


def zero3_spec(shape: Sequence[int], dp: int, min_size: int) -> P:
    """ZeRO-3 row-sharding rule for ONE parameter leaf: the first axis
    divisible by the data-axis size is sharded over ``data``; leaves with
    fewer than ``min_size`` elements (biases, scalars, norms) replicate —
    sharding them saves nothing and costs a collective per step."""
    if dp <= 1 or int(np.prod(shape, dtype=np.int64)) < max(min_size, dp):
        return P()
    for i, n in enumerate(shape):
        if n >= dp and n % dp == 0:
            spec = [None] * len(shape)
            spec[i] = DATA_AXIS
            return P(*spec)
    return P()


def shard_params(mesh: Mesh, pytree, min_size: int = DEFAULT_MIN_SHARD_SIZE):
    """NamedSharding pytree for a param (or param-shaped) pytree: each
    leaf row-sharded over the ``data`` axis per :func:`zero3_spec`, with
    a replicated fallback for sub-threshold leaves.  Shared by the
    ZeRO-3 trainer (``parallel/sharded.py``), checkpoint resharding, and
    the tests that pin the layout rules."""
    dp = mesh.shape.get(DATA_AXIS, 1)
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(
            mesh, zero3_spec(np.shape(leaf), dp, min_size)), pytree)


def place_sharded(x, sharding: NamedSharding):
    """``device_put`` onto a NamedSharding, with a per-shard fallback.

    Some backends (the CPU backend under multi-process
    ``jax.distributed``, PR 7's recorded limitation) don't implement a
    direct ``device_put`` onto a multi-process NamedSharding.  Rather
    than crash mid-fit, fall back to placing each addressable shard on
    its own device and assembling with
    ``jax.make_array_from_single_device_arrays`` — semantically the same
    placement, built from the primitives every backend has."""
    if x is None:
        return None
    if isinstance(x, jax.Array) and x.sharding == sharding:
        # already committed to exactly this layout: the elastic remesh
        # path re-places every leaf after a restore_sharded that placed
        # them itself — skip the redundant device_put round
        return x
    try:
        return jax.device_put(x, sharding)
    except Exception as direct_err:
        host = np.asarray(x)
        try:
            idx_map = sharding.addressable_devices_indices_map(host.shape)
            arrs = [jax.device_put(host[idx], d)
                    for d, idx in idx_map.items()]
            return jax.make_array_from_single_device_arrays(
                host.shape, sharding, arrs)
        except Exception:
            raise direct_err
