"""Device mesh construction + sharding rules.

TPU-native replacement for the reference's parallelism plumbing
(``parallelism/ParallelWrapper.java:58``, Spark TrainingMasters): instead of
model replicas + explicit averaging/gradient messages, we lay parameters and
data out over a ``jax.sharding.Mesh`` and let XLA's SPMD partitioner insert
the ICI collectives (psum for DP gradient reduction ≙ averageAndPropagate;
all-gather/reduce-scatter for TP ≙ nothing in the reference — it had no TP).

Axis names (the scaling-book convention):
  data    — batch axis (DP)
  model   — tensor-parallel axis (TP)
  seq     — sequence/context-parallel axis (SP / ring attention)
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def make_mesh(n_devices: Optional[int] = None, *, dp: Optional[int] = None,
              tp: int = 1, sp: int = 1, devices=None) -> Mesh:
    """Build a (data, model, seq) mesh. dp defaults to filling all devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if dp is None:
        if n_devices % (tp * sp):
            raise ValueError(f"{n_devices} devices not divisible by tp*sp={tp*sp}")
        dp = n_devices // (tp * sp)
    arr = np.array(devices).reshape(dp, tp, sp)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS, SEQ_AXIS))


def batch_spec(ndim: int, *, seq_axis: Optional[int] = None) -> P:
    """Shard axis 0 over data; optionally a time axis over seq."""
    spec = [None] * ndim
    spec[0] = DATA_AXIS
    if seq_axis is not None and ndim > seq_axis:
        spec[seq_axis] = SEQ_AXIS
    return P(*spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, x, *, seq_axis: Optional[int] = None):
    if x is None:
        return None
    sh = NamedSharding(mesh, batch_spec(np.ndim(x), seq_axis=seq_axis))
    return jax.device_put(x, sh)
