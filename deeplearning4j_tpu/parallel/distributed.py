"""Multi-host bootstrap + elastic checkpoint-restart (the role of the
reference's Spark driver + ``VoidParameterServer`` over Aeron,
``SharedTrainingMaster.java:451-469``, re-based on the JAX multi-process
runtime: one process per host, XLA collectives over ICI/DCN).

Failure model (SURVEY §5): the reference delegates recovery to Spark RDD
lineage; JAX has no lineage, so recovery is *checkpoint-mediated* — every
process restarts from the latest complete checkpoint and data iterators
fast-forward.  ``ElasticTrainer`` implements that loop for any model with
``fit_batch``/serializer support.
"""
from __future__ import annotations

import logging
import os
from typing import Callable, Iterable, Optional

import jax

from ..observability.clock import monotonic_s
from ..observability.recorder import get_flight_recorder

__all__ = ["initialize_distributed", "global_device_mesh", "ElasticTrainer"]

log = logging.getLogger("deeplearning4j_tpu.parallel")


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """``jax.distributed.initialize`` wrapper; no-op single-process when no
    coordinator is configured (so the same training script runs 1-host and
    N-host).  Env fallbacks: DL4J_TPU_COORDINATOR / _NPROCS / _PROC_ID."""
    coordinator_address = coordinator_address or os.environ.get(
        "DL4J_TPU_COORDINATOR")
    if not coordinator_address:
        return False
    num_processes = num_processes or int(os.environ.get("DL4J_TPU_NPROCS", 1))
    process_id = process_id if process_id is not None else int(
        os.environ.get("DL4J_TPU_PROC_ID", 0))
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def global_device_mesh(*, dp: Optional[int] = None, tp: int = 1, sp: int = 1,
                       local_fallback: bool = False):
    """Mesh over ALL processes' devices (``jax.devices()`` is global after
    ``initialize_distributed``).  Data axis is outermost so DP gradients
    reduce over DCN once per step while tp/sp collectives stay on ICI —
    the 'collectives ride ICI' layout rule.

    ``local_fallback=True`` probes whether the backend can EXECUTE a
    computation spanning the multi-process mesh and falls back to a
    process-LOCAL mesh when it cannot (the CPU backend places
    multi-process arrays through ``place_sharded``'s per-shard fallback
    but refuses the computation itself: "Multiprocess computations
    aren't implemented").  Under the fallback every process trains its
    own replica on its own devices — with identical batches the SPMD
    replicas stay byte-identical, which is exactly the posture the
    two-process elastic tests need on the CPU rig."""
    from .mesh import make_mesh
    mesh = make_mesh(len(jax.devices()), dp=dp, tp=tp, sp=sp)
    if local_fallback and jax.process_count() > 1 and \
            not _global_compute_supported(mesh):
        local = make_mesh(len(jax.local_devices()), tp=tp, sp=sp,
                          devices=jax.local_devices())
        # loud: the fallback changes semantics — per-process replicas
        # over the LOCAL devices, and an explicit dp= (sized for the
        # global device count) is superseded by the local device count
        log.warning(
            "backend cannot execute multi-process computations: falling "
            "back from the global mesh %s to the process-local mesh %s "
            "(independent per-process replicas%s)",
            dict(mesh.shape), dict(local.shape),
            f"; requested dp={dp} superseded" if dp is not None else "")
        return local
    return mesh


def _global_compute_supported(mesh) -> bool:
    """One tiny jitted add over an array placed on ``mesh``: True when the
    backend runs multi-process computations, False when only placement
    works.  The verdict depends on the backend alone, so every process
    of the world agrees without coordinating."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec
    from .mesh import place_sharded
    try:
        x = place_sharded(np.zeros((), np.float32),
                          NamedSharding(mesh, PartitionSpec()))
        jax.jit(lambda a: a + 1)(x).block_until_ready()  # graftlint: disable=JX004,JX028  (one-shot backend capability probe)
        return True
    except Exception as e:
        # any failure means "don't trust global computation here", but
        # the reason must be auditable — an unrelated transient (OOM,
        # device error) silently flipping a fleet into solo replicas
        # would otherwise look like a numerics bug
        log.warning("multi-process computation probe failed (%s: %s) — "
                    "treating the backend as placement-only",
                    type(e).__name__, str(e)[:200])
        return False


class ElasticTrainer:
    """Checkpoint-restart training driver over the durable
    :class:`~..faulttolerance.checkpoint.CheckpointManager` store.

    ``fit`` consumes ``iterator_factory()`` (a fresh batch iterable per
    call), checkpoints atomically every ``save_freq`` steps through the
    manager (manifest checksums, ``.tmp-`` staged commit — no ad-hoc zip
    files), and on (re)start resumes from the newest COMPLETE checkpoint:
    partial or checksum-corrupt directories are skipped, restore brings
    back params + updater + RNG + the global data cursor, and already-
    consumed batches fast-forward without touching the RNG — an
    interrupted-then-resumed run matches the uninterrupted run exactly.
    Crash at any point loses at most ``save_freq - 1`` steps.

    **Elastic membership** (optional): pass a ``member``
    (:class:`~..faulttolerance.cluster.ClusterMember`) — and, on exactly
    one host, a ``coordinator`` — and the global batch sequence is
    deterministically re-chunked over the CURRENT world size at every
    round (= ``save_freq`` batches) boundary: batch ``i`` belongs to rank
    ``i % world_size`` (``cluster.shard_owner``).  A killed host's lease
    expires, the coordinator evicts it at the next boundary, and the
    survivors' ownership map re-covers its shard; when the host restarts
    it restores the newest complete checkpoint from the SHARED store and
    is re-admitted at a boundary under a bumped rendezvous generation —
    its pre-eviction incarnation can never write into the newer round.
    """

    def __init__(self, model, checkpoint_dir: str, save_freq: int = 10,
                 keep_last: int = 2, *, manager=None, member=None,
                 coordinator=None, background: bool = False,
                 mesh_factory=None, barrier_timeout_s: float = 30.0):
        from ..faulttolerance.checkpoint import CheckpointManager
        from ..parallel.sharded import ShardedTrainer
        self.model = model
        # A mesh wrapper (ParallelWrapper) trains, but its underlying
        # network is what serializes; after restore the wrapper re-places
        # the loaded host arrays onto the mesh.  Membership-less
        # multi-process runs give each process its own checkpoint_dir
        # (SPMD training is deterministic, so the replicas' checkpoints
        # are identical); membership runs SHARE one store.
        inner = getattr(model, "model", None)
        self._net = inner if (inner is not None
                              and hasattr(model, "_place")) else model
        self.dir = checkpoint_dir
        self.save_freq = max(1, save_freq)
        self.keep_last = max(1, keep_last)
        self.manager = manager if manager is not None else CheckpointManager(
            checkpoint_dir, keep_last=self.keep_last, background=background)
        self.member = member
        self.coordinator = coordinator
        # A ZeRO-3 ShardedTrainer flips the trainer into SPMD-sharded
        # posture: checkpoints go through save_sharded (multi-writer
        # barrier under membership), restores through
        # restore_sharded(mesh=...), every live member trains every
        # batch (the sharded step is collective over the mesh — the
        # i%world data split only applies to independent replicas), and
        # membership changes rebuild the mesh over the survivors.
        self.sharded = isinstance(model, ShardedTrainer)
        # mesh_factory(world_size) -> the survivor mesh after a
        # membership change (sharded mode only).  None = keep the mesh.
        self.mesh_factory = mesh_factory
        self.barrier_timeout_s = float(barrier_timeout_s)
        self.last_restored_step = 0
        self.last_view = None
        self.trained_steps = 0      # batches THIS member actually fitted
        self.replayed_steps = 0     # of those, orphan re-covers (evictions)
        self.barrier_aborts = 0     # lost barrier rounds (never lost data)
        self.reshard_events = []    # one dict per survivor-mesh rebuild

    # -- checkpoint bookkeeping ------------------------------------------
    def latest_step(self) -> int:
        """Global step of the newest COMPLETE checkpoint (0 = none);
        corrupt/partial directories are never candidates."""
        ckpts = self.manager.checkpoints()
        return int(ckpts[-1][2].get("step", ckpts[-1][0])) if ckpts else 0

    def _save(self, step: int, view=None) -> None:
        # the checkpoint records the generation of the view it was
        # written under — the durable-path counterpart of the
        # coordinator's accept() fence: a restore can audit WHICH
        # rendezvous epoch produced the state it is about to adopt
        cursor = {"batch_seq": int(step)}
        if view is not None:
            cursor["generation"] = int(view.generation)
        if not self.sharded:
            self.manager.save(self._net, cursor=cursor, step=int(step),
                              blocking=None)
            return
        if view is None or self.member is None or view.world_size <= 1:
            self.manager.save_sharded(self._net, cursor=cursor,
                                      step=int(step), process_index=0,
                                      process_count=1, blocking=None)
            return
        rank = view.rank_of(self.member.worker_id)
        if rank is None:
            return              # not (yet) admitted: nothing to contribute
        from ..faulttolerance.checkpoint import ShardBarrierError
        try:
            self.manager.save_sharded(
                self._net, cursor=cursor, step=int(step),
                process_index=rank, process_count=view.world_size,
                barrier=self._barrier_for(view))
        except ShardBarrierError as e:
            # a lost ROUND, never lost training: the previous complete
            # checkpoint still stands and the next boundary retries the
            # save under the refreshed membership view
            self.barrier_aborts += 1
            rec = get_flight_recorder()
            if rec is not None:
                rec.record("cluster", "barrier_abort", step=int(step),
                           generation=int(view.generation), error=str(e))

    def _barrier_for(self, view):
        """The barrier contract for one multi-writer save round: the
        view's generation fences the staging dir, lease reads supply the
        liveness verdict, and a seeded RetryPolicy paces the primary's
        marker polls (bounded by ``barrier_timeout_s``)."""
        from ..faulttolerance.checkpoint import ShardBarrier
        from ..faulttolerance.cluster import live_ranks
        from ..faulttolerance.faults import RetryPolicy
        store = self.member.store
        return ShardBarrier(
            generation=int(view.generation),
            timeout_s=self.barrier_timeout_s,
            policy=RetryPolicy(backoff_s=0.02, max_backoff_s=0.25,
                               seed=int(view.generation)),
            live_fn=lambda: live_ranks(store, view))

    def restore_latest(self) -> int:
        """Restore the newest complete checkpoint into the model; returns
        its global step (0 = fresh start).  A truncated/corrupt newest
        checkpoint is skipped in favor of the previous complete one, and
        ``.tmp-`` staging orphans from a crashed writer are swept
        (under membership only AGED orphans go — a peer's in-flight
        barrier round must not be reclaimed from under its writers).
        A sharded checkpoint restores through ``restore_sharded`` onto
        the model's CURRENT mesh — the survivor mesh at a rejoin — with
        params, updater mirrors, RNG and cursor digest-exact."""
        self.manager.sweep_orphans(
            min_age_s=2.0 * self.barrier_timeout_s
            if self.member is not None else 0.0)
        path = self.manager.latest()
        step = 0
        if path is not None:
            # restore_any: the manager owns the dense-vs-sharded layout
            # sniff; a sharded dir re-places onto the model's mesh
            _, state = self.manager.restore_any(
                path=path, net=self._net, **self._reshard_kwargs())
            cursor = state.get("cursor") or {}
            step = int(cursor.get("batch_seq", state.get("iteration", 0)))
            if self._net is not self.model:
                self.model._place()   # re-shard restored arrays on the mesh
        self.last_restored_step = step
        return step

    def _reshard_kwargs(self, mesh=None):
        kw = {"mesh": mesh if mesh is not None
              else getattr(self.model, "mesh", None)}
        mss = getattr(self.model, "min_shard_size", None)
        if mss is not None:
            kw["min_shard_size"] = mss
        return kw

    def _remesh(self, view, step: int) -> None:
        """Membership changed: rebuild the mesh over the survivors and
        route the model through ``restore_sharded(mesh=survivors)`` —
        the boundary's just-committed barrier checkpoint re-placed under
        the new topology (params + updater mirrors + RNG + cursor, a
        pure byte re-placement).  When the boundary's save did NOT land
        (an aborted barrier round), the LIVE state is re-placed instead
        — restoring an older checkpoint here would silently rewind
        training past batches the loop already consumed.  Either way the
        train step keeps its single process-global trace: sharding lives
        in the arguments, not the jaxpr."""
        if not self.sharded or self.mesh_factory is None or view is None:
            return
        new_mesh = self.mesh_factory(view.world_size)
        if new_mesh is None or new_mesh == getattr(self.model, "mesh",
                                                   None):
            return
        t0 = monotonic_s()
        ckpts = self.manager.checkpoints()
        newest = ckpts[-1] if ckpts else None
        via = "replace_live"
        if newest is not None and int(newest[2].get("step", newest[0])) \
                == int(step) and newest[2].get("sharded"):
            self.manager.restore_sharded(
                path=newest[1], net=self._net,
                **self._reshard_kwargs(mesh=new_mesh))
            via = "restore_sharded"
        # remesh either way: re-target the wrapper and refresh
        # replicated state + shardings (leaves restore_sharded already
        # placed under the new layout short-circuit in place_sharded)
        self.model.remesh(new_mesh)
        from .mesh import DATA_AXIS
        event = {"step": int(step), "world_size": view.world_size,
                 "generation": int(view.generation),
                 "dp": int(new_mesh.shape.get(DATA_AXIS, 1)),
                 "via": via, "ms": (monotonic_s() - t0) * 1e3,
                 "t": monotonic_s()}   # completion stamp (bench timing)
        self.reshard_events.append(event)
        rec = get_flight_recorder()
        if rec is not None:
            rec.record("cluster", "survivor_remesh", **event)

    # -- membership -------------------------------------------------------
    def _round_view(self, round_index: int):
        """The membership view this round runs under: the coordinator
        installs it (evictions/admissions + generation bump happen HERE,
        at the round boundary), plain members read it."""
        if self.coordinator is not None:
            return self.coordinator.begin_round(round_index)
        if self.member is not None:
            return self.member.view()
        return None

    def _owner_of(self, index: int, view) -> Optional[int]:
        """Worker id that owns global batch ``index`` under ``view``
        (None = no view/empty view: everyone trains)."""
        if view is None or self.member is None or not view.members:
            return None
        from ..faulttolerance.cluster import shard_owner
        return view.members[shard_owner(index, view.world_size)]

    def _owns(self, index: int, view) -> bool:
        owner = self._owner_of(index, view)
        if owner is None:
            # no installed view: solo posture.  A member NOT in the view
            # (pre-admission) trains nothing — its heartbeat gets it
            # admitted at a boundary
            return view is None or self.member is None
        if self.sharded:
            # SPMD posture: the sharded step is collective over the
            # mesh, so every ADMITTED member executes every batch (the
            # i%world data split only applies to independent replicas);
            # membership gates admission, fencing, and barrier writes
            return view.rank_of(self.member.worker_id) is not None
        return owner == self.member.worker_id

    def _writes_checkpoint(self, view) -> bool:
        """Who calls ``_save`` at a boundary: the primary always; under
        a sharded multi-writer world, EVERY admitted member (each must
        contribute its shard block before the primary can commit)."""
        if self._is_primary(view):
            return True
        return (self.sharded and view is not None
                and self.member is not None
                and view.rank_of(self.member.worker_id) is not None)

    def _replay_orphans(self, old_view, new_view, window) -> None:
        """Batches owned by a member evicted between ``old_view`` and
        ``new_view`` were never trained by anyone — re-cover them on this
        member if the NEW ownership map assigns them here.  ``window``
        retains the recent (index, batch, owner) triples this member did
        not train, spanning the lease TTL: a member's death is only
        *detected* when its lease expires, so every batch "covered" by
        its zombie lease is still replayable."""
        if old_view is None or new_view is None or not window:
            return
        lost = set(old_view.members) - set(new_view.members)
        if not lost:
            return
        rec = get_flight_recorder()
        if rec is not None:
            # membership transition forensics: who fell out, at which
            # generation, and how many orphaned batches this member holds
            rec.record("cluster", "members_lost",
                       lost=sorted(lost),
                       generation=int(new_view.generation),
                       window=len(window))
        me = self.member.worker_id
        keep = []
        for index, batch, owner, t in window:
            if owner in lost:
                if self._owner_of(index, new_view) == me:
                    self.model.fit_batch(batch)
                    self.trained_steps += 1
                    self.replayed_steps += 1
                # a surviving peer replays the rest; either way the
                # orphan is resolved — don't replay it again on a later
                # transition
                continue
            keep.append((index, batch, owner, t))
        window[:] = keep

    def _is_primary(self, view) -> bool:
        """Under membership exactly one live member — the lowest-ranked —
        writes checkpoints into the shared store."""
        if view is None or self.member is None:
            return True
        return bool(view.members) and view.members[0] == self.member.worker_id

    # -- training loop ----------------------------------------------------
    def fit(self, iterator_factory: Callable[[], Iterable],
            max_steps: Optional[int] = None) -> int:
        """Run (or resume) training; returns the final global step (the
        cluster-wide data cursor — every member advances it identically,
        whether or not it owned a given batch)."""
        step = self.restore_latest()
        # the heartbeat makes this (re)joiner visible; the coordinator
        # admits it — and counts the rejoin — at the next boundary.  A
        # member the CALLER already started is the caller's to stop.
        started_member = (self.member is not None
                          and self.member._thread is None)
        if started_member:
            self.member.start()
        done = 0
        last_saved = step
        self.trained_steps = 0
        self.replayed_steps = 0
        self.barrier_aborts = 0
        self.reshard_events = []
        view = self._round_view(step // self.save_freq)
        self.last_view = view
        # orphan-replay window: batches this member did NOT train, kept
        # for ~2 lease TTLs of wall time — a dead member's batches are
        # replayable for as long as its zombie lease could have "covered"
        # them.  (A second failure inside the same lease window can still
        # lose the dead member's last batches to a committed cursor —
        # exactly-once under compound faults needs acked rounds, which is
        # the ROADMAP follow-up.)
        window: list = [] if (self.member is not None
                              and not self.sharded) else None
        horizon_s = (2.0 * self.member.lease_ttl_s
                     if self.member is not None else 0.0)
        try:
            for batch in iterator_factory():
                if done < step:      # fast-forward batches already trained
                    done += 1
                    continue
                if max_steps is not None and done >= max_steps:
                    break
                if done > last_saved and done % self.save_freq == 0:
                    # round boundary: refresh the view FIRST (evictions,
                    # admissions, generation bump), re-cover any batches
                    # orphaned by an eviction, and only then let the
                    # CURRENT primary commit the cursor — a stale member
                    # that lost its place never writes the shared store
                    new_view = self._round_view(done // self.save_freq)
                    self._replay_orphans(view, new_view, window)
                    changed = (view is not None and new_view is not None
                               and new_view.generation != view.generation)
                    view = new_view
                    self.last_view = view
                    if self._writes_checkpoint(view):
                        self._save(done, view)
                    last_saved = done
                    if changed:
                        # survivors rebuild the mesh AFTER the save: the
                        # boundary checkpoint (written by the surviving
                        # writers under the new view) reshards onto the
                        # survivor mesh digest-exact
                        self._remesh(view, done)
                if self._owns(done, view):
                    self.model.fit_batch(batch)
                    self.trained_steps += 1
                    rec = get_flight_recorder()
                    if rec is not None:
                        rec.record("train", "elastic_step", step=done,
                                   worker=(None if self.member is None
                                           else self.member.worker_id))
                elif window is not None:
                    now = monotonic_s()
                    window.append((done, batch,
                                   self._owner_of(done, view), now))
                    while window and now - window[0][3] > horizon_s:
                        window.pop(0)
                done += 1
            if done > last_saved:
                if self.member is not None:
                    new_view = self._round_view(done // self.save_freq)
                    self._replay_orphans(view, new_view, window)
                    view = new_view
                    self.last_view = view
                if self._writes_checkpoint(view):
                    self._save(done, view)
        except Exception as e:
            rec = get_flight_recorder()
            if rec is not None:
                # the crash artifact lands in the shared checkpoint
                # store: the one place every incarnation can reach
                rec.record("train", "elastic_fit_exception",
                           error=f"{type(e).__name__}: {e}", step=done)
                rec.maybe_dump("elastic_fit_exception", directory=self.dir)
            raise
        finally:
            self.manager.wait()
            if started_member:
                self.member.stop()
        return done
