"""Multi-host bootstrap + elastic checkpoint-restart (the role of the
reference's Spark driver + ``VoidParameterServer`` over Aeron,
``SharedTrainingMaster.java:451-469``, re-based on the JAX multi-process
runtime: one process per host, XLA collectives over ICI/DCN).

Failure model (SURVEY §5): the reference delegates recovery to Spark RDD
lineage; JAX has no lineage, so recovery is *checkpoint-mediated* — every
process restarts from the latest complete checkpoint and data iterators
fast-forward.  ``ElasticTrainer`` implements that loop for any model with
``fit_batch``/serializer support.
"""
from __future__ import annotations

import os
import tempfile
from typing import Callable, Iterable, Optional

import jax
import numpy as np

__all__ = ["initialize_distributed", "global_device_mesh", "ElasticTrainer"]


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """``jax.distributed.initialize`` wrapper; no-op single-process when no
    coordinator is configured (so the same training script runs 1-host and
    N-host).  Env fallbacks: DL4J_TPU_COORDINATOR / _NPROCS / _PROC_ID."""
    coordinator_address = coordinator_address or os.environ.get(
        "DL4J_TPU_COORDINATOR")
    if not coordinator_address:
        return False
    num_processes = num_processes or int(os.environ.get("DL4J_TPU_NPROCS", 1))
    process_id = process_id if process_id is not None else int(
        os.environ.get("DL4J_TPU_PROC_ID", 0))
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def global_device_mesh(*, dp: Optional[int] = None, tp: int = 1, sp: int = 1):
    """Mesh over ALL processes' devices (``jax.devices()`` is global after
    ``initialize_distributed``).  Data axis is outermost so DP gradients
    reduce over DCN once per step while tp/sp collectives stay on ICI —
    the 'collectives ride ICI' layout rule."""
    from .mesh import make_mesh
    return make_mesh(len(jax.devices()), dp=dp, tp=tp, sp=sp)


class ElasticTrainer:
    """Checkpoint-restart training driver.

    ``fit`` consumes ``iterator_factory()`` (a fresh batch iterable per call),
    checkpoints atomically every ``save_freq`` steps, and on (re)start resumes
    from the newest complete checkpoint — skipping the batches already
    consumed.  Crash at any point loses at most ``save_freq - 1`` steps.
    Reference analogues: ``earlystopping/saver/LocalFileModelSaver`` for the
    artifact, Spark re-execution for the recovery semantics.
    """

    def __init__(self, model, checkpoint_dir: str, save_freq: int = 10,
                 keep_last: int = 2):
        self.model = model
        # A mesh wrapper (ParallelWrapper) trains, but its underlying
        # network is what serializes; after restore the wrapper re-places
        # the loaded host arrays onto the mesh.  In multi-process runs give
        # each process its own checkpoint_dir (SPMD training is
        # deterministic, so the replicas' checkpoints are identical).
        inner = getattr(model, "model", None)
        self._net = inner if (inner is not None
                              and hasattr(model, "_place")) else model
        self.dir = checkpoint_dir
        self.save_freq = max(1, save_freq)
        self.keep_last = max(1, keep_last)
        self.last_restored_step = 0
        os.makedirs(checkpoint_dir, exist_ok=True)

    # -- checkpoint bookkeeping ------------------------------------------
    def _ckpt_path(self, step: int) -> str:
        return os.path.join(self.dir, f"ckpt_{step:012d}.zip")

    def latest_step(self) -> int:
        steps = [int(f[5:-4]) for f in os.listdir(self.dir)
                 if f.startswith("ckpt_") and f.endswith(".zip")]
        return max(steps) if steps else 0

    def _save(self, step: int) -> None:
        from ..utils.model_serializer import write_model
        path = self._ckpt_path(step)
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        os.close(fd)
        try:
            write_model(self._net, tmp, save_updater=True)
            os.replace(tmp, path)  # atomic: no torn checkpoints
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        self._gc(step)

    def _gc(self, newest: int) -> None:
        steps = sorted(int(f[5:-4]) for f in os.listdir(self.dir)
                       if f.startswith("ckpt_") and f.endswith(".zip"))
        for s in steps[:-self.keep_last]:
            os.unlink(self._ckpt_path(s))

    def restore_latest(self) -> int:
        """Load newest checkpoint into the model; returns its step (0=none)."""
        step = self.latest_step()
        if step:
            from ..utils.model_serializer import restore_model
            restored = restore_model(self._ckpt_path(step), load_updater=True)
            self._net.params = restored.params
            self._net.state = restored.state
            self._net.opt_state = restored.opt_state
            self._net.iteration = restored.iteration
            self._net.epoch = restored.epoch
            if self._net is not self.model:
                self.model._place()   # re-shard restored arrays on the mesh
        self.last_restored_step = step
        return step

    # -- training loop ----------------------------------------------------
    def fit(self, iterator_factory: Callable[[], Iterable],
            max_steps: Optional[int] = None) -> int:
        """Run (or resume) training; returns the final global step."""
        step = self.restore_latest()
        done = 0
        for batch in iterator_factory():
            if done < step:      # fast-forward batches already trained on
                done += 1
                continue
            if max_steps is not None and done >= max_steps:
                break
            self.model.fit_batch(batch)
            done += 1
            if done % self.save_freq == 0:
                self._save(done)
        if done % self.save_freq != 0 and done > step:
            self._save(done)
        return done
