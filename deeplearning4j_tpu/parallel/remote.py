"""Gradient sharing over a message broker (the DCN / multi-host path).

Reference: the Aeron transport under ``SharedTrainingMaster`` —
``RoutedTransport``/``MulticastTransport`` carrying ``SilentUpdatesMessage``
(threshold-quantized gradients) peer-to-peer, no barrier.  Here the same
encoded-update messages (``parallel/accumulation.py`` formats) get a
compact binary wire format and ride any broker with
publish/subscribe(topic) — in-process (``LocalMessageBroker``) for tests,
TCP (``TcpMessageBroker``) across processes/hosts.  Intra-slice sharing
stays dense all-reduce over ICI (ParallelWrapper); this is for the
bandwidth-starved boundary.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from .accumulation import EncodingHandler, decode

__all__ = ["encode_message_bytes", "decode_message_bytes",
           "RemoteGradientSharing"]

_MAGIC = b"GUP1"
_KINDS = ("threshold", "bitmap")


def encode_message_bytes(worker_id: int, msg: Dict[str, Any]) -> bytes:
    """Encoded-update message -> wire frame (the SilentUpdatesMessage
    serialization role)."""
    kind = _KINDS.index(msg["kind"])
    head = _MAGIC + struct.pack("<iBqf", worker_id, kind, msg["size"],
                                msg["threshold"])
    if msg["kind"] == "threshold":
        idx = np.ascontiguousarray(msg["idx"], np.int32)
        signs = np.ascontiguousarray(msg["signs"], np.int8)
        return head + struct.pack("<q", idx.size) + idx.tobytes() \
            + signs.tobytes()
    packed = np.ascontiguousarray(msg["packed"], np.uint8)
    return head + struct.pack("<q", packed.size) + packed.tobytes()


def decode_message_bytes(data: bytes):
    """Wire frame -> (worker_id, message dict)."""
    if data[:4] != _MAGIC:
        raise ValueError("bad gradient-update frame magic")
    worker_id, kind, size, threshold = struct.unpack_from("<iBqf", data, 4)
    n, = struct.unpack_from("<q", data, 4 + 17)
    off = 4 + 17 + 8
    if _KINDS[kind] == "threshold":
        idx = np.frombuffer(data, np.int32, count=n, offset=off)
        signs = np.frombuffer(data, np.int8, count=n, offset=off + 4 * n)
        msg = {"kind": "threshold", "size": size, "threshold": threshold,
               "idx": idx, "signs": signs}
    else:
        packed = np.frombuffer(data, np.uint8, count=n, offset=off)
        msg = {"kind": "bitmap", "size": size, "threshold": threshold,
               "packed": packed}
    return worker_id, msg


class RemoteGradientSharing:
    """One worker's endpoint: publish local encoded updates, drain and
    apply peers' (reference ``SharedTrainingWrapper`` + accumulator over
    Aeron).  All workers share one ``topic``; own messages are filtered by
    worker id."""

    def __init__(self, broker, worker_id: int, topic: str = "gradients",
                 handler: Optional[EncodingHandler] = None):
        self.broker = broker
        self.worker_id = worker_id
        self.topic = topic
        self.handler = handler or EncodingHandler()
        self._sub = broker.subscribe(topic)
        self.messages_sent = 0
        self.messages_applied = 0

    def publish_update(self, flat_grad) -> None:
        msg = self.handler.encode_update(flat_grad)
        self.broker.publish(self.topic,
                            encode_message_bytes(self.worker_id, msg))
        self.messages_sent += 1

    def apply_updates(self, flat_params, timeout: float = 0.0):
        """Drain pending peer messages into the flat param vector; returns
        the updated vector (stale messages apply late — by design)."""
        out = jnp.asarray(flat_params)
        while True:
            payload = self._sub.poll(timeout=timeout or 0.001)
            if payload is None:
                return out
            sender, msg = decode_message_bytes(payload)
            if sender == self.worker_id:
                continue      # own broadcast echo
            out = out + decode(msg)
            self.messages_applied += 1

    def close(self) -> None:
        if hasattr(self._sub, "close"):
            self._sub.close()
        elif hasattr(self.broker, "unsubscribe"):
            self.broker.unsubscribe(self.topic, self._sub)
