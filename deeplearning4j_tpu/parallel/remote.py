"""Gradient sharing over a message broker (the DCN / multi-host path).

Reference: the Aeron transport under ``SharedTrainingMaster`` —
``RoutedTransport``/``MulticastTransport`` carrying ``SilentUpdatesMessage``
(threshold-quantized gradients) peer-to-peer, no barrier.  Here the same
encoded-update messages (``parallel/accumulation.py`` formats) get a
compact binary wire format and ride any broker with
publish/subscribe(topic) — in-process (``LocalMessageBroker``) for tests,
TCP (``TcpMessageBroker``) across processes/hosts.  Intra-slice sharing
stays dense all-reduce over ICI (ParallelWrapper); this is for the
bandwidth-starved boundary.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from .accumulation import EncodingHandler, decode

__all__ = ["encode_message_bytes", "decode_message_bytes",
           "RemoteGradientSharing"]

_MAGIC = b"GUP2"
_KINDS = ("threshold", "bitmap")


def encode_message_bytes(worker_id: int, msg: Dict[str, Any],
                         seq: int = 0) -> bytes:
    """Encoded-update message -> wire frame (the SilentUpdatesMessage
    serialization role).  ``seq`` is a dense 1-based per-sender sequence
    number: combined with per-sender FIFO delivery it lets receivers
    dedup exactly (a resynced worker skips seq <= the count its seed
    already contains)."""
    kind = _KINDS.index(msg["kind"])
    head = _MAGIC + struct.pack("<iBqfq", worker_id, kind, msg["size"],
                                msg["threshold"], seq)
    if msg["kind"] == "threshold":
        idx = np.ascontiguousarray(msg["idx"], np.int32)
        signs = np.ascontiguousarray(msg["signs"], np.int8)
        return head + struct.pack("<q", idx.size) + idx.tobytes() \
            + signs.tobytes()
    packed = np.ascontiguousarray(msg["packed"], np.uint8)
    return head + struct.pack("<q", packed.size) + packed.tobytes()


def decode_message_bytes(data: bytes):
    """Wire frame -> (worker_id, seq, message dict)."""
    if data[:4] != _MAGIC:
        raise ValueError("bad gradient-update frame magic")
    worker_id, kind, size, threshold, seq = struct.unpack_from(
        "<iBqfq", data, 4)
    n, = struct.unpack_from("<q", data, 4 + 25)
    off = 4 + 25 + 8
    if _KINDS[kind] == "threshold":
        idx = np.frombuffer(data, np.int32, count=n, offset=off)
        signs = np.frombuffer(data, np.int8, count=n, offset=off + 4 * n)
        msg = {"kind": "threshold", "size": size, "threshold": threshold,
               "idx": idx, "signs": signs}
    else:
        packed = np.frombuffer(data, np.uint8, count=n, offset=off)
        msg = {"kind": "bitmap", "size": size, "threshold": threshold,
               "packed": packed}
    return worker_id, seq, msg


class RemoteGradientSharing:
    """One worker's endpoint: publish local encoded updates, drain and
    apply peers' (reference ``SharedTrainingWrapper`` + accumulator over
    Aeron).  All workers share one ``topic``; own messages are filtered by
    worker id."""

    #: default per-call drain bound (see ``apply_updates``): high enough
    #: that a healthy step drains everything, low enough that a flooding
    #: peer cannot starve the caller's training step in one call
    DEFAULT_MAX_DRAIN = 512

    def __init__(self, broker, worker_id: int, topic: str = "gradients",
                 handler: Optional[EncodingHandler] = None,
                 ack: bool = False, seq_base: int = 0,
                 skip_seqs: Optional[Dict[int, int]] = None, sub=None,
                 max_drain: Optional[int] = None):
        self.broker = broker
        self.worker_id = worker_id
        self.topic = topic
        self.handler = handler or EncodingHandler()
        # ``sub``: adopt an existing subscription (a resynced worker must
        # keep the one it opened BEFORE requesting its seed)
        if sub is not None:
            self._sub = sub
        else:
            self._sub = broker.subscribe(topic, ack=ack) if ack \
                else broker.subscribe(topic)
        # seq_base continues a predecessor incarnation's numbering so
        # per-sender sequence numbers stay dense across respawns
        self.seq_base = seq_base
        # skip_seqs[p]: sequence numbers <= this were already folded into
        # this worker's starting table (a resync seed) — exact dedup
        self.skip_seqs: Dict[int, int] = dict(skip_seqs or {})
        self.max_drain = self.DEFAULT_MAX_DRAIN if max_drain is None \
            else int(max_drain)
        self.messages_sent = 0
        self.messages_applied = 0
        # per-sender applied tallies back the drain barrier: a worker knows
        # it holds every peer update once applied[p] >= the count p
        # declared minus what its seed already contained
        self.applied_per_peer: Dict[int, int] = {}
        # dead-peer state (fed by the master's lease/liveness authority —
        # an eviction notice): a dead peer stops counting against the
        # drain barrier, so an evicted sender can never hang it
        self.dead_peers: set = set()

    def publish_update(self, flat_grad) -> None:
        msg = self.handler.encode_update(flat_grad)
        self.messages_sent += 1
        self.broker.publish(
            self.topic,
            encode_message_bytes(self.worker_id, msg,
                                 seq=self.seq_base + self.messages_sent))

    def apply_updates(self, flat_params, timeout: float = 0.0,
                      max_messages: Optional[int] = None):
        """Drain pending peer messages into the flat param vector; returns
        the updated vector (stale messages apply late — by design).
        Messages whose seq is at or below the sender's ``skip_seqs`` entry
        are already in this worker's starting table and are discarded.

        The drain is BOUNDED: at most ``max_messages`` (default: the
        endpoint's ``max_drain``) payloads are consumed per call, so a
        peer publishing faster than this worker trains cannot starve the
        caller's step inside one "drain until momentarily empty" loop —
        leftovers stay queued for the next call.  ``max_messages=0``
        disables the bound (the drain-barrier loops call repeatedly and
        bound themselves by their own deadline)."""
        out = jnp.asarray(flat_params)
        limit = self.max_drain if max_messages is None else int(max_messages)
        polled = 0
        while limit <= 0 or polled < limit:
            payload = self._sub.poll(timeout=timeout or 0.001)
            if payload is None:
                return out
            polled += 1
            sender, seq, msg = decode_message_bytes(payload)
            if sender == self.worker_id:
                continue      # own broadcast echo
            if seq and seq <= self.skip_seqs.get(sender, 0):
                continue      # already folded into the resync seed
                # (seq==0 marks an unsequenced frame — never deduped)
            out = out + decode(msg)
            self.messages_applied += 1
            self.applied_per_peer[sender] = \
                self.applied_per_peer.get(sender, 0) + 1
        return out

    # ------------------------------------------------------- dead peers
    def mark_dead(self, peer: int) -> None:
        """Record an eviction notice from the liveness authority: ``peer``
        will never publish again, so the drain barrier stops waiting on
        its declared count and residual."""
        self.dead_peers.add(int(peer))

    def unresolved_peers(self, declared: Dict[int, int], num_workers: int,
                         *, mirror_counts: Optional[Dict[int, int]] = None,
                         resids_seen=(), resids_folded=()) -> list:
        """Peers still blocking the drain barrier: no declared sent-count
        yet, missing residual, or applied (+ resync-seed) count below the
        declared count.  Peers in ``dead_peers`` are excluded — an
        evicted sender's contribution is whatever already arrived, and
        waiting longer cannot produce more."""
        mirror_counts = mirror_counts or {}
        out = []
        for p in range(int(num_workers)):
            if p == self.worker_id or p in self.dead_peers:
                continue
            if p not in declared \
                    or (p not in resids_seen and p not in resids_folded) \
                    or self.applied_per_peer.get(p, 0) \
                    + mirror_counts.get(p, 0) < declared[p]:
                out.append(p)
        return out

    def close(self) -> None:
        if hasattr(self._sub, "close"):
            self._sub.close()
        elif hasattr(self.broker, "unsubscribe"):
            self.broker.unsubscribe(self.topic, self._sub)
