"""Parallel inference (reference
``deeplearning4j-scaleout/.../parallelism/ParallelInference.java:32`` +
``inference/observers/BatchedInferenceObservable.java``).

TPU-first rethink: the reference spawns N model replicas on N GPUs and
round-robins requests; on TPU one jitted forward already saturates the chip,
and replication is a mesh axis, not threads.  What survives is the *dynamic
batching* idea — XLA compiles per shape, so serving variable singleton
requests is bucketed into padded batches (compile-once buckets) and executed
on a single dispatcher thread; caller threads block on futures.

Modes (reference ``InferenceMode``):
  INPLACE   — caller-thread synchronous forward (no queueing)
  BATCHED   — requests queue; dispatcher coalesces up to ``max_batch_size``
              items (waiting ``nano_wait``s for stragglers), pads to the
              bucket size, runs ONE forward, scatters results
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["ParallelInference", "InferenceMode"]


class InferenceMode:
    INPLACE = "INPLACE"
    BATCHED = "BATCHED"


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class ParallelInference:
    """Thread-safe inference front-end over one model.

    ``output(x)`` accepts a single example ``[features...]`` or a batch
    ``[n, features...]`` and returns the model output; in BATCHED mode
    concurrent callers are coalesced into one padded device batch.
    """

    def __init__(self, model, inference_mode: str = InferenceMode.BATCHED,
                 max_batch_size: int = 32, queue_limit: int = 256,
                 nano_wait: float = 0.002,
                 batch_buckets: Optional[Sequence[int]] = None):
        self.model = model
        self.mode = inference_mode
        self.max_batch_size = max_batch_size
        self.nano_wait = nano_wait
        buckets = list(batch_buckets) if batch_buckets else [
            b for b in (1, 2, 4, 8, 16, 32, 64, 128) if b < max_batch_size]
        if max_batch_size not in buckets:
            buckets.append(max_batch_size)  # top bucket must cover full batch
        self.buckets = sorted(buckets)
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_limit)
        self._shutdown = threading.Event()
        self._worker: Optional[threading.Thread] = None
        if self.mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(target=self._dispatch_loop,
                                            daemon=True)
            self._worker.start()

    # ------------------------------------------------------------------ API
    def output(self, x) -> np.ndarray:
        x = np.asarray(x)
        single = x.ndim == self._feature_ndim()
        if self.mode == InferenceMode.INPLACE or self._shutdown.is_set():
            out = np.asarray(self.model.output(x[None] if single else x))
            return out[0] if single else out
        batch = x[None] if single else x
        futures = [self._submit(batch[i]) for i in range(len(batch))]
        results = np.stack([f.result() for f in futures])
        return results[0] if single else results

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._worker is not None:
            self._queue.put(None)  # wake dispatcher
            self._worker.join(timeout=5)
        # fail any future still enqueued so its caller unblocks
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                item[1].set_exception(RuntimeError("ParallelInference shut down"))

    # ------------------------------------------------------------ internals
    def _feature_ndim(self) -> int:
        try:
            return len(self.model.conf.input_type.shape(-1)) - 1  # sans batch
        except Exception:
            return 1

    def _submit(self, example: np.ndarray) -> Future:
        f: Future = Future()
        self._queue.put((example, f))
        return f

    def _dispatch_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                continue
            pending: List = [item]
            # coalesce stragglers up to max batch
            time.sleep(self.nano_wait)
            while len(pending) < self.max_batch_size:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is not None:
                    pending.append(nxt)
            try:  # any failure (incl. ragged shapes) must not kill the loop
                examples = np.stack([ex for ex, _ in pending])
                n = len(examples)
                b = _bucket(n, self.buckets)
                if b > n:  # pad to bucket so XLA reuses the compiled executable
                    pad = np.repeat(examples[-1:], b - n, axis=0)
                    batch = np.concatenate([examples, pad])
                else:
                    batch = examples
                out = np.asarray(self.model.output(batch))[:n]
                for (_, fut), row in zip(pending, out):
                    fut.set_result(row)
            except Exception as e:
                for _, fut in pending:
                    if not fut.done():
                        fut.set_exception(e)
