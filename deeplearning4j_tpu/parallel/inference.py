"""Parallel inference (reference
``deeplearning4j-scaleout/.../parallelism/ParallelInference.java:32`` +
``inference/observers/BatchedInferenceObservable.java``).

TPU-first rethink: the reference spawns N model replicas on N GPUs and
round-robins requests; on TPU one jitted forward already saturates the chip,
and replication is a mesh axis, not threads.  What survives is the *dynamic
batching* idea — XLA compiles per shape, so serving variable singleton
requests is bucketed into padded batches (compile-once buckets) and executed
on a single dispatcher thread; caller threads block on futures.

Modes (reference ``InferenceMode``):
  INPLACE   — caller-thread synchronous forward (no queueing)
  BATCHED   — requests queue; dispatcher coalesces up to ``max_batch_size``
              items (waiting ``nano_wait``s for stragglers), pads to the
              bucket size, runs ONE forward, scatters results
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["ParallelInference", "InferenceMode", "InvalidInputError"]


class InvalidInputError(ValueError):
    """Request rejected up front (wrong feature shape) — a *client* error,
    distinguishable from ValueErrors raised inside the model forward."""


class InferenceMode:
    INPLACE = "INPLACE"
    BATCHED = "BATCHED"


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    # silently falling back to buckets[-1] would dispatch an UNPADDED
    # oversize batch (a fresh XLA compile per novel size); callers split
    # or reject before bucketing, so reaching here is a contract bug
    raise InvalidInputError(
        f"batch of {n} exceeds the top bucket {buckets[-1]}")


class ParallelInference:
    """Thread-safe inference front-end over one model.

    ``output(x)`` accepts a single example ``[features...]`` or a batch
    ``[n, features...]`` and returns the model output; in BATCHED mode
    concurrent callers are coalesced into one padded device batch.

    Explicit ``batch_buckets`` are respected as-is; a coalesced group
    larger than the top bucket follows ``oversize_policy``: ``"split"``
    (default) dispatches it in top-bucket chunks so every dispatch keeps a
    compiled shape, ``"reject"`` fails it with ``InvalidInputError``.
    """

    def __init__(self, model, inference_mode: str = InferenceMode.BATCHED,
                 max_batch_size: int = 32, queue_limit: int = 256,
                 nano_wait: float = 0.002,
                 batch_buckets: Optional[Sequence[int]] = None,
                 oversize_policy: str = "split"):
        if inference_mode not in (InferenceMode.INPLACE,
                                  InferenceMode.BATCHED):
            raise ValueError(
                f"unknown inference_mode '{inference_mode}'; expected "
                f"'{InferenceMode.INPLACE}' or '{InferenceMode.BATCHED}' "
                "(an unrecognized mode would queue requests with no "
                "dispatcher and hang)")
        if oversize_policy not in ("split", "reject"):
            raise ValueError(
                f"unknown oversize_policy '{oversize_policy}'; expected "
                "'split' (chunk oversize batches across dispatches) or "
                "'reject' (fail them with InvalidInputError)")
        self.model = model
        self.mode = inference_mode
        self.max_batch_size = max_batch_size
        self.nano_wait = nano_wait
        self.oversize_policy = oversize_policy
        # explicit buckets are respected as-is: a coalesced group larger
        # than the top bucket follows oversize_policy instead of being
        # silently dispatched unpadded.  The default ladder is the shared
        # serving ladder (data/shapes.serving_buckets) so this front-end
        # and the continuous-batching engine compile ONE shape set.
        from ..data.shapes import serving_buckets
        self.buckets = serving_buckets(max_batch_size, batch_buckets)
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_limit)
        self._shutdown = threading.Event()
        self._submit_lock = threading.Lock()  # orders submits vs shutdown
        self._worker: Optional[threading.Thread] = None
        if self.mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(target=self._dispatch_loop,
                                            daemon=True)
            self._worker.start()

    # ------------------------------------------------------------------ API
    def output(self, x) -> np.ndarray:
        x = np.asarray(x)
        single = x.ndim == self._feature_ndim()
        batch = x[None] if single else x
        expected = self._feature_shape()
        if expected is not None and tuple(batch.shape[1:]) != expected:
            raise InvalidInputError(f"expected feature shape {expected}, "
                                    f"got {tuple(batch.shape[1:])}")
        if self.mode == InferenceMode.INPLACE or self._shutdown.is_set():
            out = np.asarray(self.model.output(batch))
            return out[0] if single else out
        if (self.oversize_policy == "reject"
                and len(batch) > self.buckets[-1]):
            # fail fast rather than enqueueing work the dispatcher will
            # reject future-by-future anyway
            raise InvalidInputError(
                f"request batch of {len(batch)} exceeds the top bucket "
                f"{self.buckets[-1]} (oversize_policy='reject')")
        futures = [self._submit(batch[i]) for i in range(len(batch))]
        results = np.stack([f.result() for f in futures])
        return results[0] if single else results

    def shutdown(self) -> None:
        with self._submit_lock:  # no submit can now slip past the drain below
            self._shutdown.set()
        if self._worker is not None:
            try:
                self._queue.put_nowait(None)  # wake dispatcher
            except queue.Full:
                pass  # dispatcher is draining; the flag alone stops it
            self._worker.join(timeout=5)
        # fail any future still enqueued so its caller unblocks
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                item[1].set_exception(RuntimeError("ParallelInference shut down"))

    # ------------------------------------------------------------ internals
    def _feature_shape(self):
        try:
            return tuple(self.model.conf.input_type.shape(-1)[1:])
        except Exception:
            return None

    def _feature_ndim(self) -> int:
        shape = self._feature_shape()
        return len(shape) if shape is not None else 1

    def _submit(self, example: np.ndarray) -> Future:
        f: Future = Future()
        with self._submit_lock:
            if self._shutdown.is_set():
                raise RuntimeError("ParallelInference shut down")
            self._queue.put((example, f))
        return f

    def _dispatch_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                continue
            pending: List = [item]
            # coalesce stragglers up to max batch; skip the wait when a full
            # batch is already queued (saturated server shouldn't pay latency)
            if self._queue.qsize() < self.max_batch_size - 1 and self.nano_wait:
                time.sleep(self.nano_wait)
            while len(pending) < self.max_batch_size:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is not None:
                    pending.append(nxt)
            # group by feature shape: one malformed request must not fail the
            # innocent ones coalesced with it (shapes differ only when the
            # model exposes no input_type for up-front validation)
            groups: dict = {}
            for ex, fut in pending:
                groups.setdefault(tuple(np.shape(ex)), []).append((ex, fut))
            for group in groups.values():
                self._run_batch(group)

    def _run_batch(self, pending: List) -> None:
        top = self.buckets[-1]
        if len(pending) > top:
            if self.oversize_policy == "reject":
                err = InvalidInputError(
                    f"coalesced batch of {len(pending)} exceeds the top "
                    f"bucket {top} (oversize_policy='reject')")
                for _, fut in pending:
                    if not fut.done():
                        fut.set_exception(err)
                return
            # split: one dispatch per top-bucket chunk — every chunk keeps
            # a compiled-bucket shape instead of one unpadded novel shape
            for i in range(0, len(pending), top):
                self._run_batch(pending[i:i + top])
            return
        try:  # any failure must not kill the dispatch loop
            examples = np.stack([ex for ex, _ in pending])
            n = len(examples)
            b = _bucket(n, self.buckets)
            if b > n:  # pad to bucket so XLA reuses the compiled executable
                pad = np.repeat(examples[-1:], b - n, axis=0)
                batch = np.concatenate([examples, pad])
            else:
                batch = examples
            out = np.asarray(self.model.output(batch))[:n]
            for (_, fut), row in zip(pending, out):
                fut.set_result(row)
        except Exception as e:
            for _, fut in pending:
                if not fut.done():
                    fut.set_exception(e)
