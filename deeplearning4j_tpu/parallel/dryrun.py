"""Multi-chip dry run used by the driver (``__graft_entry__.dryrun_multichip``).

Builds an n-device mesh, shards the FULL training step (forward+backward+
optimizer update) with real dp×tp shardings, and executes one step on tiny
shapes.  Upgraded alongside the flagship model.
"""
from __future__ import annotations

import os

import numpy as np


def provision_devices(n_devices: int):
    """Return >= n_devices jax devices, self-provisioning a virtual CPU mesh.

    Real-hardware path first: if the default backend already exposes enough
    devices (an actual pod slice), use them.  Otherwise force the host
    platform to expose ``n_devices`` virtual CPU devices.  XLA_FLAGS must be
    set before the CPU backend initializes — it is lazy per-platform, so this
    works even when a TPU backend (e.g. the 'axon' plugin, which pins the
    default platform at interpreter start) is already up: ``jax.devices()``
    still reports the TPU, but ``jax.devices('cpu')`` honors the flag.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={max(n_devices, 8)}"
        ).strip()

    import jax

    try:
        devices = jax.devices()
    except RuntimeError:
        devices = []  # default backend failed to init (e.g. wedged TPU relay)
    if len(devices) >= n_devices:
        return devices[:n_devices]
    try:
        cpu = jax.devices("cpu")
    except RuntimeError:
        cpu = []
    if len(cpu) >= n_devices:
        return cpu[:n_devices]
    return None  # backend already up with too few devices; caller re-execs


def _run_in_subprocess(n_devices: int) -> None:
    """Re-exec the dry run in a fresh interpreter where XLA_FLAGS and
    JAX_PLATFORMS are set BEFORE jax initializes — the only reliable route
    when the calling process already brought up a too-small backend."""
    import subprocess
    import sys

    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={max(n_devices, 8)}"
    ).strip()
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c",
         "from deeplearning4j_tpu.parallel import dryrun; "
         f"dryrun._child_main({n_devices})"],
        env=env, capture_output=True, text=True, timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(
            f"subprocess dryrun failed (rc={proc.returncode}):\n"
            + proc.stderr[-4000:])


def run(n_devices: int) -> None:
    """Hermetic entry point: the dry run is a CPU-mesh *correctness* check and
    must never fail because of default-backend (TPU) health.  The in-process
    path pins every uncommitted array to the mesh devices; if it still fails
    for any reason (e.g. a wedged TPU relay poisoning backend init), fall back
    to a fresh ``JAX_PLATFORMS=cpu`` subprocess, which cannot see the TPU at
    all.  Mirrors the reference's always-runnable local-cluster proof
    (dl4j-spark BaseSparkTest.java:46 — ``local[N]``, no real cluster)."""
    devices = provision_devices(n_devices)
    if devices is None:
        return _run_in_subprocess(n_devices)
    try:
        _run_in_process(n_devices, devices)
    except Exception as e:
        import sys
        # stderr, not warnings.warn: the fallback must survive
        # warnings-as-errors runs.  If the subprocess also fails, Python's
        # implicit __context__ chaining preserves this first traceback.
        print(f"in-process dryrun failed ({type(e).__name__}: {e}); "
              "falling back to hermetic JAX_PLATFORMS=cpu subprocess",
              file=sys.stderr)
        _run_in_subprocess(n_devices)


def _child_main(n_devices: int) -> None:
    """Entry point the hermetic subprocess runs.  Never re-spawns — a failure
    here is terminal (surfaced to the parent via the subprocess rc), so the
    fallback chain is bounded at one level by construction."""
    devices = provision_devices(n_devices)
    if devices is None:
        raise RuntimeError(
            f"hermetic child could not provision {n_devices} devices")
    _run_in_process(n_devices, devices)


def _run_in_process(n_devices: int, devices) -> None:
    import jax

    # Pin uncommitted array creation (model init, PRNG keys, demo inputs) to
    # the dry-run devices.  Without this, when the default backend is a lone
    # TPU and the mesh is the CPU fallback, init ops run on the TPU and any
    # TPU-side flake fails a check whose purpose is CPU-mesh correctness.
    with jax.default_device(devices[0]):
        _train_steps(n_devices, devices)


def _train_steps(n_devices: int, devices) -> None:
    import jax

    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from .mesh import make_mesh
    from .wrapper import ParallelWrapper, megatron_dense_rule

    tp = 2 if n_devices % 2 == 0 else 1
    mesh = make_mesh(n_devices, tp=tp, devices=devices)

    conf = (NeuralNetConfiguration.builder()
            .seed(42).activation("relu").weight_init("xavier")
            .updater(Adam(learning_rate=1e-3))
            .list()
            .layer(DenseLayer(n_out=64))
            .layer(DenseLayer(n_out=64))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    model = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    dp = n_devices // tp
    batch = dp * 8  # divisible by the data axis (sharding requires it)
    x = rng.standard_normal((batch, 784), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]

    pw = ParallelWrapper(model, mesh, param_rule=megatron_dense_rule(model.params))
    # The PRNG key was created on the default backend at init; commit it to
    # the dry-run devices so the jitted step doesn't see mixed placements
    # (relevant when the default backend is a lone TPU and the mesh is CPU).
    model._rng = jax.device_put(model._rng, devices[0])
    pw.fit(x, y)
    assert np.isfinite(model.get_score()), "dry-run step produced non-finite loss"

    if n_devices % 8 == 0:
        _pipeline_seq_step(n_devices, devices)
        _expert_parallel_step(n_devices, devices)


def _pipeline_seq_step(n_devices: int, devices) -> None:
    """data×pipe×seq 3D-sharded transformer train step: GPipe microbatching
    with ring attention inside each stage, DP gradient pmean, SGD update.
    Model + step come from ``demo.py`` (shared with the pipeline tests)."""
    import jax
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5 keeps it in experimental
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from .demo import build_demo_inputs, make_pipelined_train_step

    dp, pp, sp = 2, 2, n_devices // 4
    stacked, xs, ys = build_demo_inputs(
        n_stages=pp, embed=8, n_heads=2, seq_len=4 * sp, microbatch=2 * dp,
        n_micro=pp)
    mesh = Mesh(np.array(devices[:n_devices]).reshape(dp, pp, sp),
                ("data", "pipe", "seq"))
    train_step = make_pipelined_train_step(n_heads=2)
    in_specs = (P("pipe"), P(None, "data", "seq"), P(None, "data", "seq"))
    # Inputs were built on the default backend; commit them to the mesh
    # (cross-backend device_put) so the jitted program sees one placement.
    stacked = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P("pipe"))), stacked)
    xs = jax.device_put(xs, NamedSharding(mesh, in_specs[1]))
    ys = jax.device_put(ys, NamedSharding(mesh, in_specs[2]))
    fn = jax.jit(shard_map(  # graftlint: disable=JX028  (dry-run validation probe; compiled once, never dispatched steady-state)
        train_step, mesh=mesh, in_specs=in_specs,
        out_specs=(P(), P("pipe"))))
    loss, _ = fn(stacked, xs, ys)
    assert np.isfinite(float(loss)), "pipeline dry-run produced non-finite loss"


def _expert_parallel_step(n_devices: int, devices) -> None:
    """data×expert MoE train step: top-1 routed FFN, tiled all-to-all
    token exchange over the expert axis, DP grad reduction."""
    import jax
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5 keeps it in experimental
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from .expert import init_moe_params, make_moe_train_step

    dp, ep = 2, n_devices // 2
    embed, hidden, experts = 8, 16, ep
    mesh = Mesh(np.array(devices[:n_devices]).reshape(dp, ep),
                ("data", "expert"))
    params = init_moe_params(jax.random.PRNGKey(0), experts, embed, hidden)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n_devices * 4, embed)).astype(np.float32)
    y = np.tanh(x @ rng.standard_normal((embed, embed)).astype(np.float32))
    pspec = {"router": P(None, None), "w1": P("expert"), "w2": P("expert")}
    batch_spec = P(("data", "expert"), None)
    params = {k: jax.device_put(v, NamedSharding(mesh, pspec[k]))
              for k, v in params.items()}
    x = jax.device_put(x, NamedSharding(mesh, batch_spec))
    y = jax.device_put(y, NamedSharding(mesh, batch_spec))
    fn = jax.jit(shard_map(  # graftlint: disable=JX028  (dry-run validation probe; compiled once, never dispatched steady-state)
        make_moe_train_step(capacity=4), mesh=mesh,
        in_specs=(pspec, batch_spec, batch_spec),
        out_specs=(pspec, P())))
    _, loss = fn(params, x, y)
    assert np.isfinite(float(loss)), "MoE dry-run produced non-finite loss"
