"""Multi-chip dry run used by the driver (``__graft_entry__.dryrun_multichip``).

Builds an n-device mesh, shards the FULL training step (forward+backward+
optimizer update) with real dp×tp shardings, and executes one step on tiny
shapes.  Upgraded alongside the flagship model.
"""
from __future__ import annotations

import numpy as np


def run(n_devices: int) -> None:
    import jax

    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(jax.devices())} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)")

    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from .mesh import make_mesh
    from .wrapper import ParallelWrapper, megatron_dense_rule

    tp = 2 if n_devices % 2 == 0 else 1
    mesh = make_mesh(n_devices, tp=tp)

    conf = (NeuralNetConfiguration.builder()
            .seed(42).activation("relu").weight_init("xavier")
            .updater(Adam(learning_rate=1e-3))
            .list()
            .layer(DenseLayer(n_out=64))
            .layer(DenseLayer(n_out=64))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    model = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    dp = n_devices // tp
    batch = dp * 8  # divisible by the data axis (sharding requires it)
    x = rng.standard_normal((batch, 784), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]

    pw = ParallelWrapper(model, mesh, param_rule=megatron_dense_rule(model.params))
    pw.fit(x, y)
    assert np.isfinite(model.get_score()), "dry-run step produced non-finite loss"

    if n_devices % 8 == 0:
        _pipeline_seq_step(n_devices)
        _expert_parallel_step(n_devices)


def _pipeline_seq_step(n_devices: int) -> None:
    """data×pipe×seq 3D-sharded transformer train step: GPipe microbatching
    with ring attention inside each stage, DP gradient pmean, SGD update.
    Model + step come from ``demo.py`` (shared with the pipeline tests)."""
    import jax
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from .demo import build_demo_inputs, make_pipelined_train_step

    dp, pp, sp = 2, 2, n_devices // 4
    stacked, xs, ys = build_demo_inputs(
        n_stages=pp, embed=8, n_heads=2, seq_len=4 * sp, microbatch=2 * dp,
        n_micro=pp)
    mesh = Mesh(np.array(jax.devices()[:n_devices]).reshape(dp, pp, sp),
                ("data", "pipe", "seq"))
    train_step = make_pipelined_train_step(n_heads=2)
    fn = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P("pipe"), P(None, "data", "seq"), P(None, "data", "seq")),
        out_specs=(P(), P("pipe"))))
    loss, _ = fn(stacked, xs, ys)
    assert np.isfinite(float(loss)), "pipeline dry-run produced non-finite loss"


def _expert_parallel_step(n_devices: int) -> None:
    """data×expert MoE train step: top-1 routed FFN, tiled all-to-all
    token exchange over the expert axis, DP grad reduction."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from .expert import init_moe_params, make_moe_train_step

    dp, ep = 2, n_devices // 2
    embed, hidden, experts = 8, 16, ep
    mesh = Mesh(np.array(jax.devices()[:n_devices]).reshape(dp, ep),
                ("data", "expert"))
    params = init_moe_params(jax.random.PRNGKey(0), experts, embed, hidden)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((n_devices * 4, embed)),
                    jnp.float32)
    y = jnp.tanh(x @ jnp.asarray(
        rng.standard_normal((embed, embed)), jnp.float32))
    pspec = {"router": P(None, None), "w1": P("expert"), "w2": P("expert")}
    fn = jax.jit(shard_map(
        make_moe_train_step(capacity=4), mesh=mesh,
        in_specs=(pspec, P(("data", "expert"), None),
                  P(("data", "expert"), None)),
        out_specs=(pspec, P())))
    _, loss = fn(params, x, y)
    assert np.isfinite(float(loss)), "MoE dry-run produced non-finite loss"
