"""Multi-chip dry run used by the driver (``__graft_entry__.dryrun_multichip``).

Builds an n-device mesh, shards the FULL training step (forward+backward+
optimizer update) with real dp×tp shardings, and executes one step on tiny
shapes.  Upgraded alongside the flagship model.
"""
from __future__ import annotations

import numpy as np


def run(n_devices: int) -> None:
    import jax

    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(jax.devices())} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)")

    from ..nn.conf.input_type import InputType
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork
    from .mesh import make_mesh
    from .wrapper import ParallelWrapper, megatron_dense_rule

    tp = 2 if n_devices % 2 == 0 else 1
    mesh = make_mesh(n_devices, tp=tp)

    conf = (NeuralNetConfiguration.builder()
            .seed(42).activation("relu").weight_init("xavier")
            .updater(Adam(learning_rate=1e-3))
            .list()
            .layer(DenseLayer(n_out=64))
            .layer(DenseLayer(n_out=64))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    model = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    dp = n_devices // tp
    batch = dp * 8  # divisible by the data axis (sharding requires it)
    x = rng.standard_normal((batch, 784), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]

    pw = ParallelWrapper(model, mesh, param_rule=megatron_dense_rule(model.params))
    pw.fit(x, y)
    assert np.isfinite(model.get_score()), "dry-run step produced non-finite loss"
