"""Shared 3D-parallel demo model: a pre-norm transformer block with ring
attention, GPipe-stacked stages, and a DP-reduced SGD train step.

Used by both the driver dry run (``dryrun.py``) and the pipeline test suite
so the validated model and the dry-run model cannot drift apart.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .pipeline import gpipe, stack_stage_params
from .sequence import ring_self_attention

__all__ = ["ring_transformer_block", "make_stage_params",
           "make_pipelined_train_step", "build_demo_inputs"]


def ring_transformer_block(params, x, *, n_heads: int, seq_axis: str = "seq"):
    """Pre-norm block: LN → ring-attention (causal) → residual → gelu MLP."""
    xn = (x - jnp.mean(x, -1, keepdims=True)) * jax.lax.rsqrt(
        jnp.var(x, -1, keepdims=True) + 1e-5)
    b, t, e = x.shape
    d = e // n_heads

    def heads(y):
        return y.reshape(b, t, n_heads, d).transpose(0, 2, 1, 3)

    q, k, v = (heads(xn @ params[w]) for w in ("Wq", "Wk", "Wv"))
    o = ring_self_attention(q, k, v, axis_name=seq_axis, causal=True)
    x = x + o.transpose(0, 2, 1, 3).reshape(b, t, e) @ params["Wo"]
    return x + jax.nn.gelu(x @ params["W1"]) @ params["W2"]


def make_stage_params(embed: int, seed: int, dtype=jnp.float32):
    r = np.random.default_rng(seed)

    def w(*s):
        return jnp.asarray(r.standard_normal(s) * 0.1, dtype)

    return {"Wq": w(embed, embed), "Wk": w(embed, embed),
            "Wv": w(embed, embed), "Wo": w(embed, embed),
            "W1": w(embed, 2 * embed), "W2": w(2 * embed, embed)}


def build_demo_inputs(*, n_stages: int, embed: int, n_heads: int, seq_len: int,
                      microbatch: int, n_micro: int, seed: int = 0,
                      dtype=jnp.float32):
    """Stacked stage params + [n_micro, mb, t, e] inputs/targets."""
    rng = np.random.default_rng(seed)
    stacked = stack_stage_params(
        [make_stage_params(embed, i, dtype) for i in range(n_stages)])
    xs = jnp.asarray(rng.standard_normal((n_micro, microbatch, seq_len, embed)),
                     dtype)
    ys = jnp.asarray(rng.standard_normal((n_micro, microbatch, seq_len, embed)),
                     dtype)
    return stacked, xs, ys


def make_pipelined_train_step(*, n_heads: int, lr: float = 0.1,
                              pipe_axis: str = "pipe",
                              reduce_axes=("data", "seq")):
    """shard_map body: GPipe forward, MSE loss, DP/SP gradient pmean, SGD."""

    def block(params, x):
        return ring_transformer_block(params, x, n_heads=n_heads,
                                      seq_axis="seq")

    def train_step(stacked, xs, ys):
        def loss_fn(stacked):
            out = gpipe(block, stacked, xs, axis_name=pipe_axis)
            return jnp.mean((out - ys) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(stacked)
        loss = jax.lax.pmean(loss, reduce_axes)
        g = jax.lax.pmean(g, reduce_axes)
        new = jax.tree.map(lambda p, gg: p - lr * gg, stacked, g)
        return loss, new

    return train_step
