"""Legacy single-layer distributed training path (reference
``dl4j-spark/.../spark/impl/layer/SparkDl4jLayer.java:48`` +
``IterativeReduceFlatMap.java`` — train ONE layer's parameters across
partitions, averaging per pass; superseded by the TrainingMaster flow but
kept for API completeness).

Here the "cluster" is a :class:`TrainingMaster` (threaded replicas standing
in for Spark executors, same as ``master.py``); the single layer is wrapped
in a one-layer ``MultiLayerNetwork`` so the normal jitted train step drives
it.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .master import ParameterAveragingTrainingMaster, TrainingMaster

__all__ = ["DistributedLayerTrainer"]


class DistributedLayerTrainer:
    """SparkDl4jLayer role: ``fit`` a single output layer distributed, then
    ``predict`` with it."""

    def __init__(self, layer_conf, input_size: int,
                 master: Optional[TrainingMaster] = None, seed: int = 0,
                 updater=None):
        from ..nn.conf.input_type import InputType
        from ..nn.conf.multi_layer import NeuralNetConfiguration
        from ..nn.multilayer import MultiLayerNetwork
        builder = NeuralNetConfiguration.builder().seed(seed)
        if updater is not None:
            builder = builder.updater(updater)
        conf = (builder.list()
                .layer(layer_conf)
                .set_input_type(InputType.feed_forward(input_size))
                .build())
        self.net = MultiLayerNetwork(conf).init()
        self.master = master or ParameterAveragingTrainingMaster(num_workers=2)

    def fit(self, iterator, epochs: int = 1) -> "DistributedLayerTrainer":
        """``fitDataSet(JavaRDD<DataSet>)`` role (SparkDl4jLayer.java:105)."""
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            self.master.fit(self.net, iterator)
        return self

    def predict(self, features) -> np.ndarray:
        """``predict(Matrix)`` role (SparkDl4jLayer.java:169)."""
        return np.asarray(self.net.output(np.asarray(features, np.float32)))
