"""Expert parallelism: mixture-of-experts FFN with all-to-all dispatch.

No reference equivalent (pre-transformer era) — this completes the
TPU-first parallelism taxonomy (dp/tp/pp/sp/ep) alongside ``pipeline.py``
and ``sequence.py``.  Design follows the GShard/Switch dense-dispatch
formulation: top-1 routing, fixed expert capacity (static shapes for XLA),
dispatch/combine as einsums on the MXU, and two tiled ``lax.all_to_all``
collectives over the ``expert`` mesh axis so each device hosts a shard of
experts while tokens stay sharded over data — the collective rides ICI.

Use under ``shard_map`` with mesh axes ("data", "expert"); see
``make_moe_train_step`` and ``tests/test_expert.py``.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["init_moe_params", "moe_ffn", "make_moe_train_step"]


def init_moe_params(key, n_experts: int, embed: int, hidden: int,
                    dtype=jnp.float32) -> Dict[str, jax.Array]:
    """Router + stacked expert FFN weights.  Under shard_map the expert
    dimension of w1/w2 is sharded over the 'expert' axis (each device
    holds n_experts / ep of them); the router is replicated."""
    kr, k1, k2 = jax.random.split(key, 3)
    s1 = 1.0 / np.sqrt(embed)
    s2 = 1.0 / np.sqrt(hidden)
    return {
        "router": jax.random.normal(kr, (embed, n_experts), dtype) * s1,
        "w1": jax.random.normal(k1, (n_experts, embed, hidden), dtype) * s1,
        "w2": jax.random.normal(k2, (n_experts, hidden, embed), dtype) * s2,
    }


def _dispatch_tensors(router_probs: jax.Array, capacity: int
                      ) -> Tuple[jax.Array, jax.Array]:
    """Top-1 dispatch/combine tensors [T, E, C] (Switch formulation):
    token t goes to its argmax expert at its position-in-expert slot,
    dropped when the expert is over capacity."""
    n_experts = router_probs.shape[-1]
    expert_idx = jnp.argmax(router_probs, axis=-1)            # [T]
    onehot = jax.nn.one_hot(expert_idx, n_experts,
                            dtype=router_probs.dtype)         # [T, E]
    pos = jnp.cumsum(onehot, axis=0) - 1.0                     # [T, E]
    keep = (pos < capacity).astype(router_probs.dtype) * onehot
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=router_probs.dtype)          # [T, E, C]
    dispatch = keep[..., None] * pos_oh                        # [T, E, C]
    gate = jnp.sum(router_probs * onehot, axis=-1)             # [T]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_ffn(params: Dict[str, jax.Array], x: jax.Array, capacity: int,
            expert_axis: Optional[str] = None,
            act=jax.nn.relu) -> Tuple[jax.Array, jax.Array]:
    """MoE FFN over local tokens x [T, D].

    Without ``expert_axis``: single-device path — w1/w2 hold ALL experts.
    With ``expert_axis`` (inside shard_map): w1/w2 hold this device's
    expert shard; two tiled all-to-alls move each token group to its
    expert's owner and back:

        [E, C, D] --a2a(split E, concat C)--> [E/ep, ep*C, D]   (to owners)
        [E/ep, ep*C, D] --a2a(split C, concat E)--> [E, C, D]   (back)

    Returns (output [T, D], Switch load-balancing aux loss scalar)."""
    probs = jax.nn.softmax(x @ params["router"], axis=-1)      # [T, E]
    n_experts = probs.shape[-1]
    dispatch, combine = _dispatch_tensors(probs, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)         # [E, C, D]
    if expert_axis is not None:
        expert_in = lax.all_to_all(expert_in, expert_axis, split_axis=0,
                                   concat_axis=1, tiled=True)
    h = act(jnp.einsum("ecd,edh->ech", expert_in, params["w1"])
            + params.get("b1", 0))
    out = jnp.einsum("ech,ehd->ecd", h, params["w2"]) + params.get("b2", 0)
    if expert_axis is not None:
        out = lax.all_to_all(out, expert_axis, split_axis=1,
                             concat_axis=0, tiled=True)
    y = jnp.einsum("tec,ecd->td", combine, out)
    # Switch aux loss: fraction-routed × mean router prob, per expert
    frac = jnp.mean(
        jax.nn.one_hot(jnp.argmax(probs, -1), n_experts), axis=0)
    aux = n_experts * jnp.sum(frac * jnp.mean(probs, axis=0))
    return y, aux


def make_moe_train_step(capacity: int, lr: float = 0.1,
                        aux_weight: float = 0.01):
    """SPMD MoE regression train step for shard_map over ("data",
    "expert"): tokens sharded over data, expert weights over expert,
    router replicated.  Gradients: w1/w2 pmean over data (their expert
    shard is unique per expert-group), router pmean over both axes."""

    def step(params, x, y):
        def loss_fn(p):
            out, aux = moe_ffn(p, x, capacity, expert_axis="expert")
            return jnp.mean((out - y) ** 2) + aux_weight * aux

        loss, grads = jax.value_and_grad(loss_fn)(params)
        loss = lax.pmean(lax.pmean(loss, "data"), "expert")
        grads = {
            "router": lax.pmean(lax.pmean(grads["router"], "data"),
                                "expert"),
            "w1": lax.pmean(grads["w1"], "data"),
            "w2": lax.pmean(grads["w2"], "data"),
        }
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    return step
