"""Cluster-style training masters (reference ``deeplearning4j-scaleout``:
``ParameterAveragingTrainingMaster.java:62`` — treeAggregate param averaging
with configurable depth — and ``SharedTrainingMaster.java:55`` — async
decentralized gradient sharing over Aeron, here over the
:class:`EncodedGradientsAccumulator`).

TPU-native framing: *synchronous* scale-out inside a slice is
``ParallelWrapper``/``pjit`` (XLA collectives over ICI) — no master needed.
These masters reproduce the reference's *cluster* semantics for the layers
XLA does not own: multi-host orchestration over DCN, elastic workers, and
bandwidth-starved links where quantized async sharing pays.  Workers here
are threads owning full model replicas (the reference's Spark executors);
the same loop body is what a multi-process DCN deployment runs per host
(see ``distributed.py`` for the jax.distributed bootstrap).
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .accumulation import EncodedGradientsAccumulator, EncodingHandler
from ..faulttolerance.faults import RetryPolicy
from ..observability.clock import monotonic_s
from ..observability.recorder import get_flight_recorder
from ..observability.registry import MetricsRegistry, default_registry
from ..observability.tracer import get_tracer

__all__ = ["TrainingMaster", "ParameterAveragingTrainingMaster",
           "SharedGradientsTrainingMaster", "TrainingMasterStats",
           "tree_average"]

log = logging.getLogger("deeplearning4j_tpu.parallel")


class TrainingMasterStats:
    """Phase wall-times per fit() call (reference
    ``ParameterAveragingTrainingMasterStats`` / ``SparkTrainingStats``:
    split/fit/aggregation/broadcast timings).  Times in seconds.

    A thin view over a metrics registry: each ``record`` lands in a
    ``training_master_phase_seconds{phase,worker}`` histogram (per-worker
    label for fan-out phases; master-side phases carry ``worker="-"``).
    By default the stats own a private always-on registry so phase
    timings survive even when the process-global registry is disabled;
    inject the default registry (or any other) to fold them into a
    ``/metrics`` exposition.

    Semantics note: fan-out phases ("fit") are recorded once per WORKER,
    so their totals are worker-seconds (CPU-time style — ~N_workers x the
    round wall time when workers run concurrently); master-side phases
    (split/broadcast/aggregation) are wall time.  The per-worker rows in
    ``stats_text`` make the distinction visible.
    """

    _HIST = "training_master_phase_seconds"
    # phase buckets: sub-ms splits to multi-second aggregation rounds
    _BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                10.0, 60.0)
    _MASTER = "-"   # worker label for phases the master itself runs

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry(enabled=True)
        self._hist = self.registry.histogram(
            self._HIST, "TrainingMaster phase wall time",
            ("phase", "worker"), buckets=self._BUCKETS)

    def record(self, phase: str, seconds: float,
               worker: Optional[int] = None) -> None:
        label = self._MASTER if worker is None else str(worker)
        self._hist.labels(phase, label).observe(seconds)

    def _by_phase(self):
        out: Dict[str, Dict[str, Any]] = {}
        for (phase, worker), child in self._hist.samples():
            out.setdefault(phase, {})[worker] = child
        return out

    def total(self, phase: str) -> float:
        return float(sum(c.sum for c in
                         self._by_phase().get(phase, {}).values()))

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        """Backward-compatible shape: per-phase count/total/mean
        aggregated over workers."""
        out = {}
        for phase, workers in self._by_phase().items():
            count = sum(c.count for c in workers.values())
            total = sum(c.sum for c in workers.values())
            if count:
                out[phase] = {"count": count, "total_s": float(total),
                              "mean_s": float(total / count)}
        return out

    def stats_text(self) -> str:
        """Deterministic table: rows sorted by (phase, worker), one row
        per (phase, worker) series plus the worker-aggregated line the
        pre-registry format printed."""
        by_phase = self._by_phase()
        lines = ["phase                worker  count   total_s   mean_s"]
        for phase, d in sorted(self.as_dict().items()):
            lines.append(f"{phase:<20} {'all':>6} {d['count']:>6} "
                         f"{d['total_s']:>9.3f} {d['mean_s']:>8.4f}")
            workers = by_phase[phase]
            if set(workers) != {self._MASTER}:
                for w in sorted(workers, key=lambda s: (len(s), s)):
                    c = workers[w]
                    if not c.count:
                        continue
                    mean = c.sum / c.count
                    lines.append(f"{phase:<20} {w:>6} {c.count:>6} "
                                 f"{c.sum:>9.3f} {mean:>8.4f}")
        return "\n".join(lines)


def tree_average(param_trees: Sequence[Any], depth: int = 2):
    """Average parameter pytrees pairwise to the given aggregation depth
    (reference ``treeAggregate`` ``aggregationDepth`` :74,150 — numerically
    a mean, shaped as a reduction tree so partial aggregates stay bounded)."""
    trees = list(param_trees)
    n = len(trees)
    if n == 1:
        return trees[0]

    def add(a, b):
        return jax.tree_util.tree_map(jnp.add, a, b)

    level = 0
    while len(trees) > 1 and level < max(depth, 1):
        nxt = [add(trees[i], trees[i + 1]) if i + 1 < len(trees) else trees[i]
               for i in range(0, len(trees), 2)]
        trees, level = nxt, level + 1
    total = trees[0]
    for t in trees[1:]:
        total = add(total, t)
    return jax.tree_util.tree_map(lambda s: s / n, total)


def _cast_like(a, ref):
    """Restore ``ref``'s dtype on an averaged leaf: integer leaves
    (optax step counts) round back to ints, floats pass through."""
    dt = getattr(ref, "dtype", None)
    if dt is not None and jnp.issubdtype(dt, jnp.integer):
        return jnp.round(a).astype(dt)
    return a


def _chunk_batches(iterator, n_workers: int) -> List[List[Any]]:
    """Round-robin batch assignment (the repartition step,
    ``ParameterAveragingTrainingMaster.java:97-98``)."""
    parts: List[List[Any]] = [[] for _ in range(n_workers)]
    for i, batch in enumerate(iterator):
        parts[i % n_workers].append(batch)
    return parts


class TrainingMaster:
    """fit(model, iterator) contract (reference ``TrainingMaster.java:28``),
    plus the distributed evaluation/scoring surface the reference exposes on
    the Spark facades (``SparkDl4jMultiLayer.evaluate`` map-partitions +
    ``IEvaluation.merge`` reduce; ``calculateScore`` :~ sum/average loss)."""

    num_workers: int = 2

    def fit(self, model, iterator) -> None:
        raise NotImplementedError

    def _get_replicas(self, model) -> List[Any]:
        """Replica pool: clone once per master+model, refresh params from
        the (possibly updated) master model on later calls (the reference
        re-broadcasts params per split, it does not rebuild workers).
        Clones share the process-global trace cache (nn/compile_cache):
        every replica executes the ONE compiled train step — replica K's
        time-to-first-step is dispatch, not an XLA compile — and each
        clone draws an independent RNG stream (decorrelated dropout)."""
        if (getattr(self, "_replicas", None) is None
                or self._replica_src is not model
                or len(self._replicas) != self.num_workers):
            self._replicas = [model] + [model.clone()
                                        for _ in range(self.num_workers - 1)]
            self._replica_src = model
        else:
            for r in self._replicas[1:]:
                # graftlint: disable=JX030  (once per fit() over num_workers replicas — replica refresh cadence, not step cadence)
                r.params = jax.tree_util.tree_map(jnp.array, model.params)  # graftlint: disable=JX030  (once-per-fit replica refresh)
                r.state = jax.tree_util.tree_map(jnp.array, model.state)  # graftlint: disable=JX030  (once-per-fit replica refresh)
                r.opt_state = jax.tree_util.tree_map(jnp.array,  # graftlint: disable=JX030  (once-per-fit replica refresh)
                                                     model.opt_state)
                # keep LR-schedule/epoch counters in lockstep too — the
                # master model may have been checkpoint-restored between fits
                r.iteration = model.iteration
                r.epoch = model.epoch
        return self._replicas

    def _fan_out(self, model, iterator, num_workers: Optional[int],
                 per_batch: Callable[[Any, Any, int], None]) -> int:
        """Shared map scaffolding for the evaluation/scoring surface: chunk
        batches over worker threads, run ``per_batch(model, batch, worker)``
        on each share, re-raise the first worker error.  Returns the worker
        count used.  The one model is shared across threads — output/score
        are read-only (only the train step donates buffers), so the
        reference's broadcast-a-copy step has no role here and cloning would
        just pay a param copy + re-jit per worker."""
        if hasattr(iterator, "reset"):
            iterator.reset()
        parts = [p for p in _chunk_batches(
            iterator, num_workers or self.num_workers) if p]
        if not parts:
            return 0
        errors: List[Exception] = []

        def work(w):
            try:
                for batch in parts[w]:
                    per_batch(model, batch, w)
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=work, args=(w,))
                   for w in range(len(parts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return len(parts)

    def evaluate(self, model, iterator, eval_factory=None,
                 num_workers: Optional[int] = None):
        """Distributed evaluation: batches fan out over worker threads, each
        accumulating a partial IEvaluation against the one shared read-only
        model; partials merge at the end.  ``eval_factory`` picks the evaluation type (Evaluation by
        default — pass e.g. ``RegressionEvaluation`` or
        ``lambda: ROC(threshold_steps=30)``)."""
        from ..evaluation.classification import Evaluation
        n_max = num_workers or self.num_workers
        evals = [(eval_factory or Evaluation)() for _ in range(n_max)]

        def per_batch(net, batch, w):
            x, y, _, lm = net._normalize_batch(batch)
            if isinstance(x, list):  # ComputationGraph batch
                out = net.output(*x)
                if isinstance(out, (list, tuple)) and len(out) > 1:
                    import warnings
                    warnings.warn(
                        "TrainingMaster.evaluate: multi-output graph — "
                        "only output[0]/labels[0] are evaluated; evaluate "
                        "other heads separately", stacklevel=2)
                out = out[0] if isinstance(out, (list, tuple)) else out
                y0 = y[0] if isinstance(y, (list, tuple)) else y
                lm0 = lm[0] if isinstance(lm, (list, tuple)) else lm
            else:
                out, y0, lm0 = net.output(x), y, lm
            evals[w].eval(np.asarray(y0), np.asarray(out),
                          mask=None if lm0 is None else np.asarray(lm0))

        used = self._fan_out(model, iterator, num_workers, per_batch)
        merged = evals[0]
        for ev in evals[1:used]:
            merged.merge(ev)
        return merged

    def score(self, model, iterator, average: bool = True,
              num_workers: Optional[int] = None) -> float:
        """Distributed loss over the dataset (reference
        ``SparkDl4jMultiLayer.calculateScore``: per-partition loss sums,
        reduced; ``average`` divides by the example count)."""
        n_max = num_workers or self.num_workers
        totals, counts = [0.0] * n_max, [0] * n_max

        def per_batch(net, batch, w):
            x, y, _, _ = net._normalize_batch(batch)
            if isinstance(x, list):
                s = net.score(inputs=x, labels=y)
                bs = int(np.asarray(x[0]).shape[0])
            else:
                s = net.score(x=x, y=y)
                bs = int(np.asarray(x).shape[0])
            totals[w] += s * bs
            counts[w] += bs

        self._fan_out(model, iterator, num_workers, per_batch)
        total, n = sum(totals), sum(counts)
        return total / max(n, 1) if average else total


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Synchronous data parallelism with periodic parameter averaging
    (reference ``ParameterAveragingTrainingMaster.java``): per split, every
    worker replica fits its partition locally, then params (and optionally
    updater state) are tree-averaged and re-broadcast.

    **Worker-failure recovery** (the Spark lineage-re-execution role,
    TensorFlow-paper posture: recover by re-execution, not per-op
    reliability): each worker's round runs against a round-start snapshot
    of its replica.  A failed round is retried up to ``max_retries`` times
    with seeded exponential backoff + jitter, re-executing the chunk from
    the snapshot (exactly-once in surviving state).  A worker exceeding
    ``straggler_timeout_s`` — or out of retries — is marked LOST: its
    round chunk is immediately re-chunked over the surviving workers and
    the rest of its shard rides their queues (*elastic degradation* — the
    fit completes on survivors instead of aborting), and it is excluded
    from every later round, aggregation, and broadcast.  A seeded
    :class:`~deeplearning4j_tpu.faulttolerance.FaultInjector` makes all of
    this deterministically testable.  Emits
    ``training_worker_retries_total`` / ``training_worker_lost_total``.
    """

    def __init__(self, num_workers: int, averaging_frequency: int = 5,
                 aggregation_depth: int = 2, average_updaters: bool = True,
                 tracer=None, max_retries: int = 2,
                 retry_backoff_s: float = 0.05,
                 straggler_timeout_s: Optional[float] = None,
                 fault_injector=None, retry_seed: int = 0,
                 elastic: bool = True):
        self.num_workers = num_workers
        self.averaging_frequency = max(1, averaging_frequency)
        self.aggregation_depth = aggregation_depth
        self.average_updaters = average_updaters
        self.stats = TrainingMasterStats()
        self.tracer = tracer   # None -> process-global (off by default)
        self.retry_policy = RetryPolicy(max_retries=max_retries,
                                        backoff_s=retry_backoff_s,
                                        seed=retry_seed)
        self.straggler_timeout_s = straggler_timeout_s
        self.fault_injector = fault_injector
        self.elastic = elastic
        self.lost_workers: set = set()
        self.retry_counts: Dict[int, int] = {}

    def fit(self, model, iterator) -> None:
        tracer = self.tracer if self.tracer is not None else get_tracer()
        with tracer.span("master.fit", mode="averaging",
                         workers=self.num_workers):
            self._fit_traced(model, iterator, tracer)

    # ------------------------------------------------- recovery plumbing
    @staticmethod
    def _snapshot_replica(replica):
        """Round-start snapshot: owned device copies (the jitted step
        donates the live buffers) + RNG/counters, so a retry re-executes
        the chunk from EXACTLY the state the failed attempt started at."""
        copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
        # the RNG key needs an owned copy too: the fused-RNG step donates
        # it, so a by-reference snapshot would hold a deleted buffer by
        # the time a retry restores it
        return (copy(replica.params), copy(replica.state),
                copy(replica.opt_state), jnp.array(replica._rng),
                replica.iteration, replica.epoch)

    @staticmethod
    def _restore_replica(replica, snap) -> None:
        copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
        p, s, o, rng, it, ep = snap
        replica.params = copy(p)     # keep the snapshot intact for the
        replica.state = copy(s)      # next attempt (donation again)
        replica.opt_state = copy(o)
        replica._rng = jnp.array(rng)
        replica.iteration = it
        replica.epoch = ep

    def _run_chunk(self, replica, chunk, w: int, rnd: int) -> None:
        """Fit one worker's round chunk, consulting the fault injector at
        batch boundaries.  fit_batch syncs the loss per step, so wall time
        recorded around this is honest compute+dispatch."""
        from ..faulttolerance.faults import InjectedWorkerFault

        inj = self.fault_injector
        for i, batch in enumerate(chunk):
            if inj is not None:
                inj.on_batch(w, rnd, i)
            replica.fit_batch(batch)
        if inj is not None and inj.should_drop(w, rnd):
            raise InjectedWorkerFault(w, rnd, "dropped result")

    def _count(self, name: str, doc: str) -> None:
        reg = default_registry()
        if reg.enabled:
            reg.counter(name, doc, ("mode",)).labels("threads").inc()

    def _retry_worker(self, replica, w, chunk, snap, rnd, tracer) -> bool:
        """Per-worker retry with exponential backoff + jitter, restoring
        the round-start snapshot before each attempt.  True on success."""
        last: Optional[BaseException] = None
        for attempt in range(1, self.retry_policy.max_retries + 1):
            self.retry_counts[w] = self.retry_counts.get(w, 0) + 1
            self._count("training_worker_retries_total",
                        "Worker round retries in the training masters")
            self.retry_policy.sleep(attempt, worker=w)
            self._restore_replica(replica, snap)
            try:
                with tracer.span("master.worker_retry", worker=w,
                                 round=rnd, attempt=attempt):
                    self._run_chunk(replica, chunk, w, rnd)
                return True
            except Exception as e:
                last = e
        if last is not None:
            log.warning("worker %d exhausted %d retries at round %d: %s",
                        w, self.retry_policy.max_retries, rnd, last)
        return False

    def _run_round(self, replicas, work, rnd, tracer, ctx):
        """Run one round's chunks on worker threads.  Returns
        ``{w: None | Exception | "straggler"}``; straggler threads are
        left running (their replicas are excluded from now on) and joined
        at the end of fit."""
        outcome: Dict[int, Any] = {}

        def runner(w, chunk):
            t_w = monotonic_s()
            try:
                with tracer.attach(ctx), \
                        tracer.span("master.worker_fit", worker=w,
                                    round=rnd):
                    self._run_chunk(replicas[w], chunk, w, rnd)
            except Exception as e:    # surfaced via the retry path
                outcome[w] = e
            else:
                outcome[w] = None
            finally:
                self.stats.record("fit", monotonic_s() - t_w, worker=w)

        threads = {w: threading.Thread(target=runner, args=(w, chunk))
                   for w, chunk in work.items()}
        for t in threads.values():
            t.start()
        deadline = None if self.straggler_timeout_s is None else \
            monotonic_s() + self.straggler_timeout_s
        for w, t in threads.items():
            t.join(None if deadline is None
                   else max(deadline - monotonic_s(), 0.0))
            if t.is_alive():
                outcome[w] = "straggler"
                self._lingering.append(t)
        return outcome

    def _fit_traced(self, model, iterator, tracer) -> None:
        t0 = monotonic_s()
        with tracer.span("master.split"):
            parts = _chunk_batches(iterator, self.num_workers)
        self.stats.record("split", monotonic_s() - t0)
        t0 = monotonic_s()
        with tracer.span("master.broadcast"):
            replicas = self._get_replicas(model)
        self.stats.record("broadcast", monotonic_s() - t0)
        queues = [deque(p) for p in parts]
        alive = list(range(self.num_workers))
        self.lost_workers = set()
        self.retry_counts = {}
        self._lingering: List[threading.Thread] = []
        freq = self.averaging_frequency
        ctx = tracer.current_context()   # propagated into worker threads
        try:
            self._fit_rounds(replicas, queues, alive, freq, tracer, ctx)
        finally:
            # join lingering straggler threads on EVERY exit path: a
            # zombie thread must never keep mutating a replica — least of
            # all replicas[0], which IS the caller's model — after fit()
            # returns or raises
            for t in self._lingering:
                t.join()
        # model IS replicas[0]; with worker 0 lost, install the surviving
        # state so fit() still ends with the trained params on the model
        if 0 in self.lost_workers and alive:
            src = replicas[min(alive)]
            copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
            model.params = copy(src.params)
            model.state = copy(src.state)
            model.opt_state = copy(src.opt_state)
            model.iteration = src.iteration
            model.epoch = src.epoch

    def _fit_rounds(self, replicas, queues, alive, freq, tracer,
                    ctx) -> None:
        """Round loop: chunk → run → retry/lose/re-chunk → aggregate,
        until every surviving queue drains.  ``alive`` is mutated in
        place so the caller sees the surviving set."""
        rnd = 0
        while True:
            work = {}
            for w in alive:
                chunk = [queues[w].popleft()
                         for _ in range(min(freq, len(queues[w])))]
                if chunk:
                    work[w] = chunk
            if not work:
                break
            snapshots = {w: self._snapshot_replica(replicas[w])
                         for w in work}
            outcome = self._run_round(replicas, work, rnd, tracer, ctx)
            ran = {w for w, res in outcome.items() if res is None}
            lost_now = []
            for w, res in outcome.items():
                if res is None:
                    continue
                if res == "straggler":
                    # its thread still runs — the replica can't be reused
                    # for a retry; treat as lost for the rest of the fit
                    log.warning("worker %d exceeded straggler timeout "
                                "%.3fs at round %d", w,
                                self.straggler_timeout_s, rnd)
                    lost_now.append(w)
                elif self._retry_worker(replicas[w], w, work[w],
                                        snapshots[w], rnd, tracer):
                    ran.add(w)
                else:
                    lost_now.append(w)
            for w in lost_now:
                if not self.elastic:
                    res = outcome[w]
                    raise res if isinstance(res, Exception) else \
                        RuntimeError(f"worker {w} lost at round {rnd} "
                                     "(straggler)")
                self.lost_workers.add(w)
                self._count("training_worker_lost_total",
                            "Workers permanently lost (retries/straggler "
                            "budget exhausted)")
                rec = get_flight_recorder()
                if rec is not None:
                    # the loss record carries the degradation context a
                    # post-mortem needs: which round, who survives
                    rec.record("cluster", "worker_lost", worker=w,
                               round=rnd, survivors=len(alive) - 1,
                               straggler=outcome[w] == "straggler")
                    rec.maybe_dump("worker_lost")
                alive.remove(w)
                if not alive:
                    res = outcome[w]
                    raise RuntimeError(
                        f"all {self.num_workers} workers lost by round "
                        f"{rnd}") from (res if isinstance(res, Exception)
                                        else None)
                # elastic degradation: the lost worker's ROUND chunk runs
                # on survivors now (the round's data is covered before its
                # average), and the rest of its shard rides their queues.
                # Each replayed batch gets the same snapshot+retry
                # protection as a normal round — a transient survivor
                # hiccup here must not abort the fit the recovery
                # machinery just saved
                with tracer.span("master.rechunk", round=rnd, worker=w,
                                 survivors=len(alive)):
                    survivors = sorted(alive)
                    for i, batch in enumerate(work[w]):
                        tw = survivors[i % len(survivors)]
                        snap = self._snapshot_replica(replicas[tw])
                        try:
                            self._run_chunk(replicas[tw], [batch], tw, -1)
                        except Exception as e:
                            if not self._retry_worker(replicas[tw], tw,
                                                      [batch], snap, -1,
                                                      tracer):
                                raise RuntimeError(
                                    f"survivor {tw} failed while "
                                    f"re-chunking lost worker {w}'s "
                                    f"round {rnd}") from e
                        ran.add(tw)
                    for i, batch in enumerate(queues[w]):
                        queues[survivors[i % len(survivors)]].append(batch)
                    queues[w].clear()
            participants = sorted(ran & set(alive))
            if len(participants) > 1:
                t_agg = monotonic_s()
                with tracer.span("master.aggregation", round=rnd,
                                 participants=len(participants)):
                    avg = tree_average(
                        [replicas[w].params for w in participants],
                        self.aggregation_depth)
                    if self.average_updaters:
                        # averaging turns integer leaves (optax step
                        # counts) into floats, which poisons the next
                        # round's jitted update — restore original dtypes
                        opt_avg = jax.tree_util.tree_map(  # graftlint: disable=JX030  (once per AVERAGING ROUND, not per step)
                            _cast_like,
                            tree_average(
                                [replicas[w].opt_state
                                 for w in participants],
                                self.aggregation_depth),
                            replicas[participants[0]].opt_state)
                    # broadcast to SURVIVORS only: a lost straggler's
                    # thread may still be writing its replica
                    for w in alive:
                        replicas[w].params = jax.tree_util.tree_map(  # graftlint: disable=JX030  (once per averaging round per survivor)
                            jnp.array, avg)
                        if self.average_updaters:
                            replicas[w].opt_state = jax.tree_util.tree_map(  # graftlint: disable=JX030  (once per averaging round per survivor)
                                jnp.array, opt_avg)
                    # async dispatch returns before the averaging runs; sync
                    # so the recorded time measures the reduction, not its
                    # dispatch
                    jax.block_until_ready(avg)  # graftlint: disable=JX029  (deliberate: once per AVERAGING ROUND, not per step — the timing sync that makes the recorded aggregation time honest)
                self.stats.record("aggregation", monotonic_s() - t_agg)
            rnd += 1


class SharedGradientsTrainingMaster(TrainingMaster):
    """Asynchronous decentralized update sharing (reference
    ``SharedTrainingMaster`` + ``SharedTrainingWrapper.run :127``): each
    worker publishes its threshold-encoded local param-update after every
    step and applies whatever peer updates have arrived — no barrier, no
    master copy; residuals carry the unsent mass."""

    def __init__(self, num_workers: int, threshold: float = 1e-3,
                 handler_factory: Optional[Callable[[], EncodingHandler]] = None,
                 tracer=None):
        self.num_workers = num_workers
        factory = handler_factory or (
            lambda: EncodingHandler(initial_threshold=threshold))
        self.accumulator = EncodedGradientsAccumulator(num_workers, factory)
        self.tracer = tracer

    def fit(self, model, iterator) -> None:
        from jax.flatten_util import ravel_pytree

        tracer = self.tracer if self.tracer is not None else get_tracer()
        parts = _chunk_batches(iterator, self.num_workers)
        replicas = self._get_replicas(model)
        acc = self.accumulator
        errors: List[Exception] = []
        ctx = tracer.current_context()

        def work(w):
            try:
                replica = replicas[w]
                with tracer.attach(ctx), \
                        tracer.span("master.worker_fit", worker=w,
                                    mode="shared"):
                    self._work_shared(replica, parts[w], acc, w)
            except Exception as e:  # surface worker crashes to the caller
                errors.append(e)

        threads = [threading.Thread(target=work, args=(w,))
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        # final convergence pass: drain late messages into worker 0 (= model)
        flat, unravel = ravel_pytree(model.params)
        model.params = unravel(acc.apply_updates(0, flat))

    @staticmethod
    def _work_shared(replica, batches, acc, w) -> None:
        from jax.flatten_util import ravel_pytree

        for batch in batches:
            flat_before, unravel = ravel_pytree(replica.params)
            flat_before = jnp.array(flat_before)  # pre-donation copy
            replica.fit_batch(batch)
            flat_after, _ = ravel_pytree(replica.params)
            acc.store_update(w, flat_after - flat_before)
            merged = acc.apply_updates(w, flat_after)
            replica.params = unravel(merged)
