"""ParallelWrapper — mesh-sharded training of a MultiLayerNetwork.

Reference semantics (``parallelism/ParallelWrapper.java:58``): N workers, one
model replica each, params synchronized by averaging or shared quantized
gradients.  TPU-native semantics: ONE jitted SPMD program over a device mesh;
gradients are reduced by XLA-inserted psum over ICI every step (mathematically
the reference's averagingFrequency=1 with exact sync — stronger guarantees at
higher speed, because ICI all-reduce is bandwidth-optimal).

Tensor parallelism (absent in the reference) comes free from the same
machinery: give parameter leaves a PartitionSpec over the 'model' axis and
GSPMD partitions the matmuls Megatron-style.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS, batch_spec, make_mesh


def _param_specs(params, rule: Optional[Callable[[str, str, Any], P]]):
    """Build a PartitionSpec pytree for params. rule(layer, name, leaf)->P."""
    if rule is None:
        return jax.tree_util.tree_map(lambda _: P(), params)
    out = {}
    for lname, lp in params.items():
        out[lname] = {pname: rule(lname, pname, leaf)
                      for pname, leaf in lp.items()}
    return out


def megatron_dense_rule(params) -> Callable[[str, str, Any], P]:
    """Alternate column/row parallel sharding for stacked dense layers:
    even layers split n_out over 'model', odd layers split n_in — activations
    stay sharded between the pair and XLA inserts one all-reduce per pair."""
    order = sorted(params.keys(), key=lambda s: int(s.split("_")[1]))
    idx = {n: i for i, n in enumerate(order)}

    def rule(lname, pname, leaf):
        if pname == "W" and getattr(leaf, "ndim", 0) == 2:
            col = idx.get(lname, 0) % 2 == 0
            return P(None, MODEL_AXIS) if col else P(MODEL_AXIS, None)
        if pname == "b" and idx.get(lname, 0) % 2 == 0 and getattr(leaf, "ndim", 0) == 1:
            return P(MODEL_AXIS)
        return P()

    return rule


class ParallelWrapper:
    """Train a model over a mesh. Drop-in for single-device ``model.fit``."""

    def __init__(self, model, mesh: Optional[Mesh] = None, *,
                 param_rule: Optional[Callable] = None):
        if model.params == {}:
            model.init()
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.param_rule = param_rule
        self._place()
        self._step = None

    # ------------------------------------------------------------------
    def _place(self):
        m, mesh = self.model, self.mesh
        pspecs = _param_specs(m.params, self.param_rule)
        to_sh = lambda spec: NamedSharding(mesh, spec)
        self.param_shardings = jax.tree_util.tree_map(
            to_sh, pspecs, is_leaf=lambda x: isinstance(x, P))
        m.params = jax.tree_util.tree_map(jax.device_put, m.params,
                                          self.param_shardings)
        repl = NamedSharding(mesh, P())
        m.state = jax.tree_util.tree_map(lambda a: jax.device_put(a, repl), m.state)
        # optimizer state mirrors the param sharding where shapes match
        def opt_put(leaf):
            return jax.device_put(leaf, repl)
        m.opt_state = jax.tree_util.tree_map(opt_put, m.opt_state)

    def _get_step(self):
        if self._step is None:
            self._step = self.model._get_jitted("train_step")
        return self._step

    # ------------------------------------------------------------------
    def fit(self, data=None, labels=None, **kw):
        """Shard each batch over the mesh then run the jitted SPMD step."""
        m, mesh = self.model, self.mesh
        put = lambda a: (None if a is None else jax.device_put(
            jnp.asarray(a), NamedSharding(mesh, batch_spec(np.ndim(a)))))
        if labels is not None:
            batches = [(data, labels, None, None)]
        else:
            batches = (m._normalize_batch(b) for b in data)
        step = self._get_step()
        with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") else _null():
            for x, y, mk, lmk in batches:
                m._rng, key = jax.random.split(m._rng)
                m.params, m.state, m.opt_state, loss = step(
                    m.params, m.state, m.opt_state, key,
                    put(x), put(y), put(mk), put(lmk))
                m._score = float(loss)
                m.iteration += 1
                for lst in m.listeners:
                    lst.iteration_done(m, m.iteration, m.epoch)
        return self

    def average_params(self):
        """No-op: SPMD keeps replicas exact (reference averageModelsParams
        exists because its replicas drift; ours cannot)."""
        return self.model.params


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False
