"""ParallelWrapper — mesh-sharded training of a MultiLayerNetwork.

Reference semantics (``parallelism/ParallelWrapper.java:58``): N workers, one
model replica each, params synchronized by averaging or shared quantized
gradients.  TPU-native semantics: ONE jitted SPMD program over a device mesh;
gradients are reduced by XLA-inserted psum over ICI every step (mathematically
the reference's averagingFrequency=1 with exact sync — stronger guarantees at
higher speed, because ICI all-reduce is bandwidth-optimal).

Tensor parallelism (absent in the reference) comes free from the same
machinery: give parameter leaves a PartitionSpec over the 'model' axis and
GSPMD partitions the matmuls Megatron-style.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import (DATA_AXIS, MODEL_AXIS, batch_spec, make_mesh,
                   place_sharded, shard_batch, zero3_spec)
from ..observability.clock import monotonic_s
from ..observability.registry import default_registry
from ..observability.tracer import get_tracer


def _param_specs(params, rule: Optional[Callable[[str, str, Any], P]]):
    """Build a PartitionSpec pytree for params. rule(layer, name, leaf)->P."""
    if rule is None:
        return jax.tree_util.tree_map(lambda _: P(), params)
    out = {}
    for lname, lp in params.items():
        out[lname] = {pname: rule(lname, pname, leaf)
                      for pname, leaf in lp.items()}
    return out


def place_opt_state(opt_state, param_treedef, place_param_tree: Callable,
                    place_other: Callable):
    """Walk an optax state pytree: subtrees shaped exactly like the params
    (mu/nu/trace...) are placed by ``place_param_tree``; every other leaf
    (step counts, scalars) by ``place_other``.  Container structure
    (NamedTuples, tuples, lists, dicts) is preserved.  Shared by the
    replicated wrapper and the ZeRO-3 sharded trainer."""
    def walk(o):
        if jax.tree_util.tree_structure(o) == param_treedef:
            return place_param_tree(o)
        if isinstance(o, tuple) and hasattr(o, "_fields"):  # NamedTuple
            return type(o)(*[walk(c) for c in o])
        if isinstance(o, tuple):
            return tuple(walk(c) for c in o)
        if isinstance(o, list):
            return [walk(c) for c in o]
        if isinstance(o, dict):
            return {k: walk(v) for k, v in o.items()}
        return place_other(o)

    return walk(opt_state)


def megatron_dense_rule(params) -> Callable[[str, str, Any], P]:
    """Alternate column/row parallel sharding for stacked dense layers:
    even layers split n_out over 'model', odd layers split n_in — activations
    stay sharded between the pair and XLA inserts one all-reduce per pair."""
    def _pos(name):
        tail = name.rsplit("_", 1)[-1]
        return int(tail) if tail.isdigit() else None

    order = sorted((n for n in params.keys() if _pos(n) is not None),
                   key=_pos)
    idx = {n: i for i, n in enumerate(order)}  # non-layer_N names replicate

    def rule(lname, pname, leaf):
        if pname == "W" and getattr(leaf, "ndim", 0) == 2:
            col = idx.get(lname, 0) % 2 == 0
            return P(None, MODEL_AXIS) if col else P(MODEL_AXIS, None)
        if pname == "b" and idx.get(lname, 0) % 2 == 0 and getattr(leaf, "ndim", 0) == 1:
            return P(MODEL_AXIS)
        return P()

    return rule


class ParallelWrapper:
    """Train a model over a mesh. Drop-in for single-device ``model.fit``."""

    def __init__(self, model, mesh: Optional[Mesh] = None, *,
                 param_rule: Optional[Callable] = None,
                 shard_optimizer_state: bool = False):
        if model.params == {}:
            model.init()
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.param_rule = param_rule
        # ZeRO-1 / "Automatic Cross-Replica Sharding of Weight Update in
        # Data-Parallel Training" (arXiv:2004.13336, PAPERS.md): shard the
        # optimizer state over the data axis; GSPMD then compiles the
        # update as reduce-scatter(grads) -> sharded optimizer math ->
        # all-gather(params), cutting optimizer memory by 1/dp with the
        # same numerics.
        if shard_optimizer_state and param_rule is not None:
            raise ValueError(
                "shard_optimizer_state=True is only supported with "
                "replicated params (param_rule=None): a TP param_rule "
                "already shards the optimizer state with the params")
        self.shard_optimizer_state = shard_optimizer_state
        self._place()
        self._step = None

    # ------------------------------------------------------------------
    def _place(self):
        m, mesh = self.model, self.mesh
        pspecs = _param_specs(m.params, self.param_rule)
        to_sh = lambda spec: NamedSharding(mesh, spec)
        self.param_shardings = jax.tree_util.tree_map(
            to_sh, pspecs, is_leaf=lambda x: isinstance(x, P))
        # place_sharded: direct device_put with the per-shard assembly
        # fallback for backends where a multi-process NamedSharding put
        # is unimplemented (the CPU rig limitation PR 7 recorded)
        m.params = jax.tree_util.tree_map(place_sharded, m.params,
                                          self.param_shardings)
        repl = NamedSharding(mesh, P())
        m.state = jax.tree_util.tree_map(
            lambda a: place_sharded(a, repl), m.state)
        # the RNG key rides the fused-RNG step (in and out), so it must
        # start mesh-replicated: the step returns the successor key with
        # this sharding, and a first-call mismatch would cost one extra
        # executable lowering
        m._rng = place_sharded(m._rng, repl)
        # optimizer state: subtrees shaped like params (optax mu/nu/trace...)
        # get the param sharding; everything else (counts) is replicated
        param_treedef = jax.tree_util.tree_structure(m.params)

        def zero1_sharding(leaf):
            """The shared ZeRO layout rule, threshold 0 (ZeRO-1 shards
            every divisible optimizer leaf; biases/scalars replicate
            because no axis divides)."""
            d = self.mesh.shape.get(DATA_AXIS, 1)
            return NamedSharding(
                mesh, zero3_spec(getattr(leaf, "shape", ()), d, 0))

        if self.shard_optimizer_state and self.param_rule is None:
            place_param_tree = lambda o: jax.tree_util.tree_map(
                lambda a: place_sharded(a, zero1_sharding(a)), o)
        else:
            place_param_tree = lambda o: jax.tree_util.tree_map(
                place_sharded, o, self.param_shardings)
        m.opt_state = place_opt_state(
            m.opt_state, param_treedef, place_param_tree,
            lambda o: place_sharded(o, repl))

    def remesh(self, mesh: Mesh) -> "ParallelWrapper":
        """Re-target the wrapper onto a different mesh and re-place all
        device state under its layout (the elastic shrink/grow path: the
        survivor mesh becomes the new topology).  The jitted train step
        is untouched — sharding lives in the step's ARGUMENTS, so the
        process-global trace serves the new mesh without retracing."""
        self.mesh = mesh
        self._place()
        return self

    # ---- model duck-typing (EarlyStoppingTrainer & friends) ----------
    @property
    def params(self):
        return self.model.params

    def init(self):
        self.model.init()
        self._place()
        return self

    def get_score(self) -> float:
        return self.model.get_score()

    def score(self, *a, **kw) -> float:
        return self.model.score(*a, **kw)

    def _normalize_batch(self, b):
        return self.model._normalize_batch(b)

    def clone(self):
        """Snapshot of the UNDERLYING model (savers keep plain models)."""
        return self.model.clone()

    def evaluate(self, *a, **kw):
        return self.model.evaluate(*a, **kw)

    def fit_batch(self, batch) -> float:
        """One sharded train step on one batch, no epoch bookkeeping
        (the EarlyStoppingTrainer inner-loop contract)."""
        m = self.model
        trimmed = self._trim(m._normalize_batch(batch))
        if trimmed is None:    # sub-shard batch: nothing to step on
            return m._score
        x, y, mk, lmk = trimmed
        if hasattr(m, "_validate_input_ids"):
            # embedding-first boundary validation (the traced gather
            # clamps out-of-range ids silently)
            m._validate_input_ids(x)
        put = self._put
        # fused-RNG step: splits the key inside the program (bit-identical
        # to the host split it replaces) and returns the successor
        m.params, m.state, m.opt_state, m._rng, loss, \
            m._last_grad_stats = \
            self._get_step()(m.params, m.state, m.opt_state, m._rng,
                             put(x), put(y), put(mk), put(lmk))
        m._score = float(loss)
        m.iteration += 1
        for lst in m.listeners:
            lst.iteration_done(m, m.iteration, m.epoch)
        return m._score

    def _data_axis_size(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in (DATA_AXIS,)
                            if a in self.mesh.shape]))

    def _trim(self, batch):
        """Drop the remainder rows of a partial batch so the leading dim
        shards evenly over the data axis (standard DP practice; the
        reference round-robins whole batches to workers instead)."""
        d = self._data_axis_size()
        x = batch[0][0] if isinstance(batch[0], (list, tuple)) else batch[0]
        n = int(x.shape[0])
        keep = (n // d) * d
        if keep == n:
            return batch
        if keep == 0:
            return None   # batch smaller than the data axis: skip it

        def cut(a):
            if a is None:
                return None
            if isinstance(a, (list, tuple)):
                return [None if e is None else e[:keep] for e in a]
            return a[:keep]

        return tuple(cut(p_) for p_ in batch)

    def _put(self, a):
        if a is None:
            return None
        if isinstance(a, (list, tuple)):
            return [self._put_one(e) for e in a]
        return self._put_one(a)

    def _put_one(self, a):
        """Shard one batch leaf; a leaf already placed on THIS mesh (a
        ``DevicePrefetchIterator(mesh=...)`` upstream) passes through with
        no second H2D copy or reshard.  Device arrays on a different mesh
        or uncommitted still go through ``device_put`` (it reshards)."""
        if a is None:
            return None
        if isinstance(a, jax.Array):
            sh = getattr(a, "sharding", None)
            if (isinstance(sh, NamedSharding) and sh.mesh == self.mesh
                    and sh.spec == batch_spec(a.ndim)):
                return a
            return shard_batch(self.mesh, a)
        return shard_batch(self.mesh, jnp.asarray(a))

    def _get_step(self):
        if self._step is None:
            self._step = self.model._get_jitted("train_step")
        return self._step

    # ------------------------------------------------------------------
    def fit(self, data=None, labels=None, *, epochs: int = 1,
            mask=None, label_mask=None):
        """Shard each batch over the mesh then run the jitted SPMD step.
        Same contract as ``MultiLayerNetwork.fit``: (x, y) arrays or an
        iterable/iterator of batches, optional masks, multiple epochs."""
        m = self.model
        put = self._put
        if labels is not None:
            batches_factory = lambda: [(data, labels, mask, label_mask)]
        elif hasattr(data, "reset") or hasattr(data, "__iter__"):
            src = data
            if not hasattr(src, "reset") and epochs > 1 and iter(src) is src:
                src = [m._normalize_batch(b) for b in src]

            def batches_factory():
                if hasattr(src, "reset"):
                    src.reset()
                for b in src:
                    yield m._normalize_batch(b)
        else:
            raise ValueError("fit() needs (x, y) or an iterator")
        step = self._get_step()
        # observability: counters only inside the loop (per-step TIMING
        # would need a host sync each step — deliberately absent; the
        # span below closes after the final score sync, so its duration
        # is honest end-to-end wall time)
        reg = default_registry()
        obs = reg.enabled
        if obs:
            steps_c = reg.counter("training_steps_total",
                                  "Optimizer steps taken")
            examples_c = reg.counter("training_examples_total",
                                     "Training examples consumed")
        # phase attribution with a SAMPLED fence (observability/profiler):
        # unsampled steps keep the zero-per-step-sync contract above —
        # only every sample_every-th step pays one block_until_ready
        from ..observability.profiler import step_profiler_for
        prof = step_profiler_for("train_step")
        # bounded async dispatch (ISSUE 18; see MultiLayerNetwork.fit):
        # the host runs up to DL4J_TPU_DISPATCH_DEPTH steps ahead of the
        # mesh — on a ZeRO-3 layout this is what lets the NEXT step's
        # host work overlap the in-flight step's all-gather + compute
        from ..nn.dispatch import DispatchWindow
        win = DispatchWindow(owner=m, profiler=prof)
        n_examples = 0
        t_fit = monotonic_s()
        with get_tracer().span("wrapper.fit", epochs=epochs,
                               devices=len(self.mesh.devices.flat)):
            for _ in range(epochs):
                for lst in m.listeners:
                    lst.on_epoch_start(m)
                for raw in batches_factory():
                    trimmed = self._trim(raw)
                    if trimmed is None:
                        continue
                    x, y, mk, lmk = trimmed
                    if hasattr(m, "_validate_input_ids"):
                        m._validate_input_ids(x)
                    if prof is not None:
                        prof.begin(monotonic_s())
                        _t = monotonic_s()
                    xd, yd, mkd, lmkd = put(x), put(y), put(mk), put(lmk)
                    if prof is not None:
                        prof.mark("h2d", monotonic_s() - _t)
                    # fused-RNG step: key split happens in the program;
                    # the successor key comes back as an output
                    (m.params, m.state, m.opt_state, m._rng, loss,
                     m._last_grad_stats) = step(
                        m.params, m.state, m.opt_state, m._rng,
                        xd, yd, mkd, lmkd)
                    # device scalar inside the batch loop (a float() here
                    # would host-sync every step); get_score() materializes
                    # on demand
                    m._score = loss
                    m.iteration += 1
                    if prof is not None:
                        prof.dispatched(loss, window=win)
                    if obs:
                        steps_c.inc()
                        xb = x[0] if isinstance(x, (list, tuple)) else x
                        bs = int(getattr(xb, "shape", (0,))[0])
                        examples_c.inc(bs)
                        n_examples += bs
                    if prof is None:
                        for lst in m.listeners:
                            lst.iteration_done(m, m.iteration, m.epoch)
                    else:
                        _t = monotonic_s()
                        for lst in m.listeners:
                            lst.iteration_done(m, m.iteration, m.epoch)
                        prof.mark("listener", monotonic_s() - _t)
                        prof.end(m.iteration)
                    # bounded-pipeline backpressure point
                    win.push(m._score, m.iteration)
                # epoch boundary drains the window (one-sync-per-epoch
                # listener cadence, same as the single-device fit)
                win.drain()
                for lst in m.listeners:
                    lst.on_epoch_end(m)
                m.epoch += 1
            # one final sync: "fit returned" still means "training finished",
            # and deferred device failures surface here instead of downstream
            m._score = float(m._score)
            if prof is not None:
                prof.materialized()
                prof.flush()
        if obs and n_examples:
            # whole-fit throughput, fetch-closed by the score sync above
            dt = max(monotonic_s() - t_fit, 1e-9)
            reg.gauge("training_examples_per_sec",
                      "Training examples/sec over the last fit() "
                      "(compile excluded where the path can tell)"
                      ).set(n_examples / dt)
        return self

    def average_params(self):
        """No-op: SPMD keeps replicas exact (reference averageModelsParams
        exists because its replicas drift; ours cannot)."""
        return self.model.params
