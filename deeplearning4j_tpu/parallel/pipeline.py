"""Pipeline parallelism (GPipe schedule) over a mesh 'pipe' axis.

The reference has NO model/pipeline parallelism (SURVEY.md §2.4: "Model
parallelism: Not implemented") — this is a first-class addition, built the
TPU way: every pipe-axis device runs the SAME program on its own stage's
parameter shard; activations hop stage-to-stage with ``lax.ppermute`` over
ICI.  ``jax.grad`` through the unrolled schedule transposes the ppermutes,
yielding the backward pipeline for free — no hand-written 1F1B machinery.

Contract: stages are structurally identical (same param shapes, same
activation shape), the transformer-stack case.  Stage params are stacked on a
leading axis of size n_stages and sharded over 'pipe'; inputs are split into
microbatches on a leading axis.

    ys = gpipe(stage_fn, stacked_params, xs, axis_name='pipe')

runs inside ``shard_map`` where ``stacked_params`` has specs
``P('pipe', ...)`` and ``xs`` ([n_micro, mb, ...]) is replicated on 'pipe'.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

# varying-manual-axes machinery (jax >= 0.6): shard_map values carry a vma
# type and replication changes go through pcast; absent both, every
# shard_map value is untyped-varying and the compat paths below apply
_HAS_VMA = hasattr(jax, "typeof") and hasattr(lax, "pcast")


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _broadcast_from_last(axis_name, x):
    """Replicate the last pipe stage's value to every device, counting its
    cotangent ONCE (owner-only) on the backward pass.  Plain ``psum`` is
    correct forward, but on jax versions without varying-manual-axes typing
    its shard_map transpose psums the (replicated, identical) downstream
    cotangents — inflating stage grads by the pipe-axis size when the loss
    is computed redundantly on every device, the normal replicated-loss
    pattern."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    return lax.psum(jnp.where(idx == n - 1, x, jnp.zeros_like(x)), axis_name)


def _broadcast_from_last_fwd(axis_name, x):
    return _broadcast_from_last(axis_name, x), None


def _broadcast_from_last_bwd(axis_name, _, ct):
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    return (jnp.where(idx == n - 1, ct, jnp.zeros_like(ct)),)


_broadcast_from_last.defvjp(_broadcast_from_last_fwd,
                            _broadcast_from_last_bwd)


def gpipe(stage_fn: Callable, stage_params, xs, *, axis_name: str = "pipe"):
    """Run microbatches [n_micro, mb, ...] through the stage pipeline.

    ``stage_params`` here is the LOCAL shard: [1, ...] leading stage axis
    (shard_map gives each device its own stage slice); ``stage_fn(params, x)``
    maps one microbatch through one stage.  Returns [n_micro, mb, ...] stage-N
    outputs, valid on every device (broadcast from the last stage).
    """
    n = lax.psum(1, axis_name)           # static pipe-axis size
    idx = lax.axis_index(axis_name)
    local = jax.tree.map(lambda p: p[0], stage_params)
    n_micro = xs.shape[0]
    if n_micro < n:
        raise ValueError(f"gpipe needs >= {n} microbatches to fill the "
                         f"pipeline, got {n_micro}")

    # The loop carry must be typed as device-varying over every mesh axis the
    # stage computation touches (e.g. 'seq' when the stage runs ring
    # attention), not just 'pipe' — collect them from the inputs.  Without
    # the vma machinery, vary() is the identity.
    if _HAS_VMA:
        vma = {axis_name} | set(jax.typeof(xs).vma)
        for leaf in jax.tree.leaves(local):
            vma |= set(jax.typeof(leaf).vma)

        def vary(a):
            missing = tuple(vma - set(jax.typeof(a).vma))
            return lax.pcast(a, missing, to="varying") if missing else a
    else:
        def vary(a):
            return a

    # Probe the stage output shape (stages are shape-uniform by contract).
    out_shape = jax.eval_shape(stage_fn, local, xs[0])
    buf = vary(jnp.zeros(out_shape.shape, out_shape.dtype))
    outs = vary(jnp.zeros((n_micro,) + tuple(out_shape.shape),
                          out_shape.dtype))

    fwd_perm = [(j, j + 1) for j in range(n - 1)]
    total = n_micro + n - 1

    def tick(t, carry):
        buf, outs = carry
        # Stage 0 consumes microbatch t (clamped; masked out when t >= n_micro),
        # other stages consume the activation that just arrived.
        x0 = vary(xs[jnp.minimum(t, n_micro - 1)])
        inp = jnp.where(idx == 0, x0.astype(buf.dtype), buf)
        y = vary(stage_fn(local, inp))
        # Last stage finished microbatch (t - idx) at this tick — record it.
        mb_idx = t - idx
        valid = jnp.logical_and(mb_idx >= 0, mb_idx < n_micro)
        write = jnp.logical_and(valid, idx == n - 1)
        slot = jnp.clip(mb_idx, 0, n_micro - 1)
        cur = lax.dynamic_index_in_dim(outs, slot, 0, keepdims=False)
        newval = jnp.where(write, y.astype(outs.dtype), cur)
        outs = lax.dynamic_update_index_in_dim(outs, newval, slot, 0)
        # Hand activations to the next stage.
        buf = lax.ppermute(y, axis_name, fwd_perm)
        return buf, outs

    _, outs = lax.fori_loop(0, total, tick, (buf, outs))
    # Broadcast stage-N results to every pipe device (callers typically take
    # the loss psum over 'data' afterwards; replicating keeps specs simple).
    if _HAS_VMA:
        outs = lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)),
                        axis_name)
    else:
        outs = _broadcast_from_last(axis_name, outs)
    return outs


def stack_stage_params(param_list):
    """Stack per-stage pytrees (identical structure) on a new leading axis —
    the layout ``gpipe`` shards over 'pipe'."""
    return jax.tree.map(lambda *ps: jnp.stack(ps, axis=0), *param_list)
