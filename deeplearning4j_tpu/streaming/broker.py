"""Message brokers: in-process topics + a TCP transport.

The Kafka stand-ins (reference wires ``CamelKafkaRouteBuilder`` to a real
Kafka cluster).  ``LocalMessageBroker`` is thread-safe named topics with
per-subscriber queues (fan-out, at-most-once like the reference's
auto-commit consumer).  ``TcpMessageBroker`` serves the same API across
processes over a length-prefixed socket protocol — the transport role
Kafka plays, sized for test rigs and single-host pipelines.
"""
from __future__ import annotations

import queue
import socket
import socketserver
import struct
import threading
from typing import Callable, Dict, List, Optional

from ..observability.registry import default_registry

__all__ = ["LocalMessageBroker", "TcpMessageBroker"]


class _Subscription:
    def __init__(self, maxsize: int, topic: str = "", broker=None):
        self.q: "queue.Queue[bytes]" = queue.Queue(maxsize)
        self.topic = topic
        self._broker = broker
        self._consumed = None      # (registry, counter child) cache

    def poll(self, timeout: Optional[float] = None) -> Optional[bytes]:
        try:
            payload = self.q.get(timeout=timeout)
        except queue.Empty:
            return None
        reg = default_registry()
        if reg.enabled:
            # child handle resolved once per registry, not per message
            cached = self._consumed
            if cached is None or cached[0] is not reg:
                child = reg.counter("broker_consumed_total",
                                    "Messages delivered to subscribers",
                                    ("topic",)).labels(self.topic)
                self._consumed = cached = (reg, child)
            cached[1].inc()
            if self._broker is not None:
                # depth = the topic's WORST backlog, so one drained
                # subscriber can't mask a backed-up sibling
                self._broker._observe_depth(self.topic)
        return payload


class LocalMessageBroker:
    """Named topics; publish fans out to every subscriber's queue.

    ``max_queue=0`` makes subscriber queues unbounded — the reliable-
    transport posture (no drop-oldest): exact-count protocols like the
    multiprocess masters' drain barrier require lossless delivery, and
    their memory is bounded by job size.  The default stays bounded with
    drop-oldest so streaming consumers can't stall producers."""

    def __init__(self, max_queue: int = 1024):
        self.max_queue = max_queue
        self._topics: Dict[str, List[_Subscription]] = {}
        self._lock = threading.Lock()
        # (registry, {topic: (published, dropped, depth) children}) —
        # per-message publishes must not pay registry name resolution
        self._metric_cache = None

    def _topic_metrics(self, reg, topic: str):
        cache = self._metric_cache
        if cache is None or cache[0] is not reg:
            self._metric_cache = cache = (reg, {})
        m = cache[1].get(topic)
        if m is None:
            m = (reg.counter("broker_published_total", "Messages published",
                             ("topic",)).labels(topic),
                 reg.counter("broker_dropped_total",
                             "Messages evicted by drop-oldest backpressure",
                             ("topic",)).labels(topic),
                 reg.gauge("broker_queue_depth",
                           "Deepest undelivered-message backlog across a "
                           "topic's subscriber queues",
                           ("topic",)).labels(topic))
            cache[1][topic] = m
        return m

    def publish(self, topic: str, payload: bytes) -> None:
        with self._lock:
            subs = list(self._topics.get(topic, ()))
        dropped = 0
        for s in subs:
            try:
                s.q.put_nowait(payload)
            except queue.Full:
                # drop-oldest keeps slow consumers from stalling producers
                dropped += 1
                try:
                    s.q.get_nowait()
                except queue.Empty:
                    pass
                try:
                    s.q.put_nowait(payload)
                except queue.Full:
                    pass
        reg = default_registry()
        if reg.enabled:
            published, dropped_c, depth = self._topic_metrics(reg, topic)
            published.inc()
            if dropped:
                dropped_c.inc(dropped)
            if subs:
                depth.set(max(s.q.qsize() for s in subs))

    def _observe_depth(self, topic: str) -> None:
        """Gauge the topic's deepest subscriber queue (publish and poll
        both route here, so the two writers agree on the semantics)."""
        reg = default_registry()
        with self._lock:
            subs = list(self._topics.get(topic, ()))
        if subs:
            self._topic_metrics(reg, topic)[2].set(
                max(s.q.qsize() for s in subs))

    def subscribe(self, topic: str, ack: bool = False) -> _Subscription:
        # in-process registration is synchronous; ``ack`` exists for API
        # parity with TcpMessageBroker (where it confirms hub registration)
        sub = _Subscription(self.max_queue, topic, broker=self)
        with self._lock:
            self._topics.setdefault(topic, []).append(sub)
        return sub

    def unsubscribe(self, topic: str, sub: _Subscription) -> None:
        with self._lock:
            subs = self._topics.get(topic, [])
            if sub in subs:
                subs.remove(sub)

    def close(self) -> None:
        with self._lock:
            self._topics.clear()


# --------------------------------------------------------------------- TCP
# frame: op(1: 0=pub 1=sub 2=sub+ack) topic_len(2) topic payload_len(4)
# payload.  op 2 answers with one empty frame on the subscription socket
# the moment the hub has registered the subscription — after the client
# reads it, any subsequently published message is guaranteed to fan out
# to this subscriber (no subscribe/publish cross-connection race).
def _send_frame(sock: socket.socket, op: int, topic: str,
                payload: bytes = b"") -> None:
    t = topic.encode()
    sock.sendall(struct.pack("<BH", op, len(t)) + t
                 + struct.pack("<I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpMessageBroker:
    """Broker server + client in one class.  ``serve()`` starts the hub;
    clients use ``publish``/``subscribe`` pointed at host:port.

    Client endpoints survive a hub restart: a stale/refused socket is
    rebuilt under ``reconnect_policy`` (bounded attempts, seeded
    exponential backoff — the ``RetryPolicy`` the training masters use),
    counted in ``broker_reconnects_total{op}``; only an exhausted budget
    raises, with the attempt count and last error spelled out."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_queue: int = 1024, reconnect_policy=None):
        from ..faulttolerance.faults import RetryPolicy
        self.host = host
        self.port = port
        self._local = LocalMessageBroker(max_queue)
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._pub_sock: Optional[socket.socket] = None
        self._pub_lock = threading.Lock()
        self.reconnect_policy = reconnect_policy if reconnect_policy \
            is not None else RetryPolicy(max_retries=4, backoff_s=0.05,
                                         max_backoff_s=2.0)
        # each reconnecting endpoint draws from its OWN policy stream
        # (worker key): the publisher is stream 0 (serialized under
        # _pub_lock), every subscription gets the next id — concurrent
        # reconnects (heartbeat publish vs a poll's resubscribe) never
        # race one numpy Generator
        self._stream_seq = 0
        self._stream_lock = threading.Lock()

    def _next_stream_id(self) -> int:
        with self._stream_lock:
            self._stream_seq += 1
            return self._stream_seq

    @staticmethod
    def _count_reconnect(op: str) -> None:
        reg = default_registry()
        if reg.enabled:
            reg.counter("broker_reconnects_total",
                        "Client reconnects after a stale/refused broker "
                        "socket", ("op",)).labels(op).inc()

    # -- server side ---------------------------------------------------------
    def serve(self) -> "TcpMessageBroker":
        broker = self._local
        # live handler sockets: shutdown() severs them so clients observe
        # the hub going away promptly (a crashed hub process resets its
        # connections; an in-process shutdown must look the same)
        conns = self._conns = set()
        conns_lock = self._conns_lock = threading.Lock()

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                subs = []
                with conns_lock:
                    conns.add(sock)
                try:
                    while True:
                        head = _recv_exact(sock, 3)
                        if head is None:
                            return
                        op, tlen = struct.unpack("<BH", head)
                        topic = _recv_exact(sock, tlen)
                        plen_b = _recv_exact(sock, 4)
                        if topic is None or plen_b is None:
                            return
                        payload = _recv_exact(
                            sock, struct.unpack("<I", plen_b)[0])
                        topic = topic.decode()
                        if op == 0:
                            broker.publish(topic, payload)
                        elif op in (1, 2):
                            sub = broker.subscribe(topic)
                            subs.append((topic, sub))
                            if op == 2:   # registration ack, before any pump
                                sock.sendall(struct.pack("<I", 0))
                            t = threading.Thread(
                                target=self._pump, args=(sock, sub),
                                daemon=True)
                            t.start()
                except (ConnectionError, OSError):
                    pass
                finally:
                    with conns_lock:
                        conns.discard(sock)
                    for topic, sub in subs:
                        broker.unsubscribe(topic, sub)

            @staticmethod
            def _pump(sock, sub):
                try:
                    while True:
                        payload = sub.poll(timeout=1.0)
                        if payload is None:
                            continue
                        sock.sendall(struct.pack("<I", len(payload)) + payload)
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True   # handlers must not block interpreter exit

        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            with self._conns_lock:
                pending, self._conns = set(self._conns), set()
            for sock in pending:
                try:     # sever live client connections (crash parity)
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                sock.close()
        with self._pub_lock:
            if self._pub_sock is not None:
                self._pub_sock.close()
                self._pub_sock = None
        self._local.close()

    # -- client side ---------------------------------------------------------
    def publish(self, topic: str, payload: bytes) -> None:
        """Publish over ONE persistent connection per client object: the
        hub's handler processes a connection's frames sequentially, so a
        sender's messages are delivered per-subscriber in publish order
        (the FIFO the masters' sequence-number dedup relies on) — and no
        per-message TCP setup.  A stale socket (hub restart) is rebuilt
        under the bounded ``reconnect_policy`` backoff; the budget
        exhausting raises with the full story."""
        policy = self.reconnect_policy
        with self._pub_lock:
            last_err: Optional[BaseException] = None
            for attempt in range(policy.max_retries + 1):
                if attempt:
                    self._count_reconnect("publish")
                    policy.sleep(attempt, worker=0)
                try:
                    if self._pub_sock is None:
                        self._pub_sock = socket.create_connection(
                            (self.host, self.port), timeout=5)
                    _send_frame(self._pub_sock, 0, topic, payload)
                    return
                except (ConnectionError, OSError) as e:
                    last_err = e
                    if self._pub_sock is not None:
                        try:
                            self._pub_sock.close()
                        finally:
                            self._pub_sock = None
            raise ConnectionError(
                f"broker publish to {self.host}:{self.port} topic "
                f"{topic!r} failed after {policy.max_retries} reconnect "
                f"attempts: {last_err}") from last_err

    class _TcpSubscription:
        def __init__(self, sock: socket.socket, broker=None, topic: str = "",
                     ack: bool = False):
            self._sock = sock
            self._buf = bytearray()   # partial frame survives poll timeouts
            self._broker = broker
            self._topic = topic
            self._ack = ack
            self._eof = False         # hub closed the stream (vs timeout)
            self._closed = False
            # dedicated backoff stream (see broker._next_stream_id)
            self._stream_id = broker._next_stream_id() \
                if broker is not None else 0

        def _fill(self, n: int, timeout: Optional[float]) -> bool:
            """Buffer until n bytes are available; False on timeout/EOF
            with the partial data RETAINED for the next poll (EOF is
            remembered in ``_eof`` so poll can resubscribe)."""
            import time as _time
            deadline = None if timeout is None else _time.time() + timeout
            while len(self._buf) < n:
                if deadline is not None:
                    remaining = deadline - _time.time()
                    if remaining <= 0:
                        return False
                    self._sock.settimeout(remaining)
                else:
                    self._sock.settimeout(None)
                try:
                    chunk = self._sock.recv(65536)
                except socket.timeout:
                    return False
                except (ConnectionError, OSError):
                    self._eof = True
                    return False
                if not chunk:
                    self._eof = True
                    return False
                self._buf.extend(chunk)
            return True

        def _resubscribe(self) -> None:
            """Rebuild the subscription socket after a hub restart under
            the broker's bounded backoff.  Undelivered frames from the
            dead hub are gone (the at-most-once contract); a partial
            frame in the buffer is dropped WITH the stream it belonged
            to.  Exhausting the budget raises a clear error."""
            policy = self._broker.reconnect_policy
            last_err: Optional[BaseException] = None
            for attempt in range(1, policy.max_retries + 1):
                TcpMessageBroker._count_reconnect("subscribe")
                policy.sleep(attempt, worker=self._stream_id)
                try:
                    fresh = self._broker.subscribe(self._topic,
                                                   ack=self._ack)
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = fresh._sock
                    self._buf = bytearray()
                    self._eof = False
                    return
                except (ConnectionError, OSError, RuntimeError) as e:
                    last_err = e
            raise ConnectionError(
                f"broker subscription to topic {self._topic!r} at "
                f"{self._broker.host}:{self._broker.port} lost and not "
                f"re-established after {policy.max_retries} reconnect "
                f"attempts: {last_err}") from last_err

        def poll(self, timeout: Optional[float] = None) -> Optional[bytes]:
            if self._eof and not self._closed and self._broker is not None:
                self._resubscribe()
            if not self._fill(4, timeout):
                return None
            size = struct.unpack("<I", bytes(self._buf[:4]))[0]
            if not self._fill(4 + size, timeout):
                return None
            payload = bytes(self._buf[4:4 + size])
            del self._buf[:4 + size]
            return payload

        def close(self):
            self._closed = True
            self._sock.close()

    def subscribe(self, topic: str, ack: bool = False) -> "_TcpSubscription":
        s = socket.create_connection((self.host, self.port), timeout=5)
        _send_frame(s, 2 if ack else 1, topic)
        sub = TcpMessageBroker._TcpSubscription(s, broker=self, topic=topic,
                                                ack=ack)
        if ack:
            first = sub.poll(timeout=10.0)
            if first != b"":
                raise RuntimeError(
                    f"no subscription ack from hub for {topic!r}")
        return sub
