"""Kafka wire-protocol (v0) client + dev broker.

Reference ``dl4j-streaming/.../streaming/kafka/NDArrayKafkaClient.java``
talks to a real Kafka cluster through the Kafka client library.  This
module implements the actual **Kafka binary protocol** (Produce v0 /
Fetch v0, message-set v0 with CRC32) over stdlib sockets, so the framework
can interoperate with a real broker where one exists — and ships
``MiniKafkaBroker``, an in-process single-node broker speaking the same
frames, for dev rigs and tests (the LocalMessageBroker/TcpMessageBroker in
``broker.py`` remain the non-Kafka transports).

Protocol framing (Kafka protocol guide, v0):
  request  = int32 size | int16 api_key | int16 api_version
             | int32 correlation_id | string client_id | body
  message  = int32 crc | int8 magic(0) | int8 attrs | bytes key | bytes value
  msum crc = CRC32 over magic..value
"""
from __future__ import annotations

import socket
import socketserver
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

__all__ = ["KafkaWireClient", "MiniKafkaBroker", "NDArrayKafkaClient"]

_API_PRODUCE = 0
_API_FETCH = 1


# ---------------------------------------------------------------- primitives
def _str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def take(self, fmt: str):
        vals = struct.unpack_from(">" + fmt, self.data, self.off)
        self.off += struct.calcsize(">" + fmt)
        return vals if len(vals) > 1 else vals[0]

    def string(self) -> str:
        n = self.take("h")
        if n < 0:            # nullable string: no payload bytes follow
            return ""
        s = self.data[self.off:self.off + n].decode()
        self.off += n
        return s

    def bytes_(self) -> Optional[bytes]:
        n = self.take("i")
        if n < 0:
            return None
        b = self.data[self.off:self.off + n]
        self.off += n
        return b


# ------------------------------------------------------------- message sets
def encode_message(value: bytes, key: Optional[bytes] = None) -> bytes:
    body = struct.pack(">bb", 0, 0) + _bytes(key) + _bytes(value)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return struct.pack(">I", crc) + body


def encode_message_set(values: List[bytes],
                       base_offset: int = 0) -> bytes:
    out = b""
    for i, v in enumerate(values):
        msg = encode_message(v)
        out += struct.pack(">qi", base_offset + i, len(msg)) + msg
    return out


def decode_message_set(data: bytes) -> List[Tuple[int, bytes]]:
    """[(offset, value)] — raises on CRC mismatch (torn/corrupt message)."""
    out: List[Tuple[int, bytes]] = []
    off = 0
    while off + 12 <= len(data):
        offset, size = struct.unpack_from(">qi", data, off)
        off += 12
        if off + size > len(data):
            break  # partial trailing message (Kafka semantics: ignore)
        msg = data[off:off + size]
        off += size
        crc = struct.unpack_from(">I", msg, 0)[0]
        if zlib.crc32(msg[4:]) & 0xFFFFFFFF != crc:
            raise ValueError(f"message at offset {offset}: CRC mismatch")
        r = _Reader(msg)
        r.take("I")          # crc
        _magic, attrs = r.take("bb")
        if attrs & 0x07:
            raise ValueError(
                f"message at offset {offset}: compressed message sets "
                f"(attrs={attrs:#x}) are not supported — produce uncompressed")
        r.bytes_()           # key
        value = r.bytes_()
        out.append((offset, value or b""))
    return out


# ------------------------------------------------------------------ client
class KafkaWireClient:
    """Minimal Kafka v0 client: produce/fetch against one broker (the
    bootstrap broker is assumed to lead the addressed partitions — the
    single-node dev case; a full metadata round is out of scope)."""

    def __init__(self, host: str, port: int, client_id: str = "dl4j-tpu",
                 timeout: float = 10.0):
        self.addr = (host, port)
        self.client_id = client_id
        self.timeout = timeout
        self._corr = 0
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, self.timeout)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def _roundtrip(self, api_key: int, body: bytes) -> _Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            req = (struct.pack(">hhi", api_key, 0, corr)
                   + _str(self.client_id) + body)
            try:
                sock = self._connect()
                sock.sendall(struct.pack(">i", len(req)) + req)
                raw = self._recv_frame(sock)
            except Exception:
                # a timeout / partial read leaves the stream desynced —
                # drop the socket so the next call reconnects cleanly
                self.close()
                raise
        r = _Reader(raw)
        got = r.take("i")
        if got != corr:
            self.close()
            raise IOError(f"correlation id mismatch: sent {corr} got {got}")
        return r

    def _recv_frame(self, sock: socket.socket) -> bytes:
        hdr = self._recv_n(sock, 4)
        (n,) = struct.unpack(">i", hdr)
        return self._recv_n(sock, n)

    @staticmethod
    def _recv_n(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("broker closed the connection")
            buf += chunk
        return buf

    def produce(self, topic: str, partition: int,
                values: List[bytes]) -> int:
        """Append messages; returns the base offset assigned."""
        mset = encode_message_set(values)
        body = (struct.pack(">hi", 1, int(self.timeout * 1000))  # acks=1
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1)
                + struct.pack(">i", partition)
                + struct.pack(">i", len(mset)) + mset)
        r = self._roundtrip(_API_PRODUCE, body)
        n_topics = r.take("i")
        assert n_topics == 1
        r.string()
        n_parts = r.take("i")
        assert n_parts == 1
        _part, err, base = r.take("i"), r.take("h"), r.take("q")
        if err:
            raise IOError(f"produce error code {err}")
        return base

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20) -> List[Tuple[int, bytes]]:
        """[(offset, value)] from ``offset`` onward (may be empty)."""
        body = (struct.pack(">iii", -1, 100, 0)
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iqi", partition, offset, max_bytes))
        r = self._roundtrip(_API_FETCH, body)
        n_topics = r.take("i")
        assert n_topics == 1
        r.string()
        n_parts = r.take("i")
        assert n_parts == 1
        _part, err, _hw = r.take("i"), r.take("h"), r.take("q")
        if err:
            raise IOError(f"fetch error code {err}")
        size = r.take("i")
        mset = r.data[r.off:r.off + size]
        return decode_message_set(mset)


# ------------------------------------------------------------------ broker
class MiniKafkaBroker:
    """Single-node in-process broker speaking Produce v0 / Fetch v0 — the
    dev/test stand-in for a real cluster (role of an embedded Kafka in the
    reference's test rigs).  Logs live in memory per (topic, partition)."""

    def __init__(self, port: int = 0):
        self._logs: Dict[Tuple[str, int], List[bytes]] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        raw = self._frame()
                        if raw is None:
                            return
                        try:
                            resp = outer._dispatch(raw)
                        except (ValueError, struct.error):
                            # malformed/corrupt request: close the
                            # connection cleanly instead of a traceback
                            return
                        self.request.sendall(
                            struct.pack(">i", len(resp)) + resp)
                except (ConnectionError, OSError):
                    return

            def _frame(self):
                try:
                    hdr = KafkaWireClient._recv_n(self.request, 4)
                except ConnectionError:
                    return None
                (n,) = struct.unpack(">i", hdr)
                return KafkaWireClient._recv_n(self.request, n)

        self._server = socketserver.ThreadingTCPServer(("127.0.0.1", port),
                                                       Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "MiniKafkaBroker":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- request dispatch -------------------------------------------------
    def _dispatch(self, raw: bytes) -> bytes:
        r = _Reader(raw)
        api_key, _ver, corr = r.take("h"), r.take("h"), r.take("i")
        r.string()  # client_id
        if api_key == _API_PRODUCE:
            return struct.pack(">i", corr) + self._produce(r)
        if api_key == _API_FETCH:
            return struct.pack(">i", corr) + self._fetch(r)
        return struct.pack(">i", corr)

    def _produce(self, r: _Reader) -> bytes:
        r.take("h")  # acks
        r.take("i")  # timeout
        out = b""
        n_topics = r.take("i")
        out += struct.pack(">i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            out += _str(topic)
            n_parts = r.take("i")
            out += struct.pack(">i", n_parts)
            for _ in range(n_parts):
                part = r.take("i")
                size = r.take("i")
                mset = r.data[r.off:r.off + size]
                r.off += size
                values = [v for _, v in decode_message_set(mset)]
                with self._lock:
                    log = self._logs.setdefault((topic, part), [])
                    base = len(log)
                    log.extend(values)
                out += struct.pack(">ihq", part, 0, base)
        return out

    def _fetch(self, r: _Reader) -> bytes:
        r.take("i")  # replica_id
        r.take("i")  # max_wait
        r.take("i")  # min_bytes
        out = b""
        n_topics = r.take("i")
        out += struct.pack(">i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            out += _str(topic)
            n_parts = r.take("i")
            out += struct.pack(">i", n_parts)
            for _ in range(n_parts):
                part, offset, max_bytes = r.take("i"), r.take("q"), r.take("i")
                with self._lock:
                    log = self._logs.get((topic, part), [])
                    high = len(log)
                    tail = log[offset:] if 0 <= offset <= high else None
                if tail is None:     # Kafka error 1: OFFSET_OUT_OF_RANGE
                    out += struct.pack(">ihq", part, 1, high)
                    out += struct.pack(">i", 0)
                    continue
                chunk: List[bytes] = []
                total = 0
                for v in tail:
                    total += len(v) + 38
                    if chunk and total > max_bytes:
                        break
                    chunk.append(v)
                mset = encode_message_set(chunk, base_offset=offset)
                out += struct.pack(">ihq", part, 0, high)
                out += struct.pack(">i", len(mset)) + mset
        return out


# ------------------------------------------------------- NDArray transport
class NDArrayKafkaClient:
    """Publish/consume NDArrays over the Kafka wire protocol (reference
    ``NDArrayKafkaClient.java``): arrays ride as codec-serialized message
    values; consumption is offset-tracked per client."""

    def __init__(self, host: str, port: int, topic: str,
                 partition: int = 0):
        self._client = KafkaWireClient(host, port)
        self.topic = topic
        self.partition = partition
        self._offset = 0

    def publish(self, arr) -> int:
        from .codec import serialize_array
        return self._client.produce(self.topic, self.partition,
                                    [serialize_array(arr)])

    def publish_all(self, arrays) -> int:
        from .codec import serialize_array
        return self._client.produce(self.topic, self.partition,
                                    [serialize_array(a) for a in arrays])

    def poll(self, max_items: int = 64):
        """Arrays appended since the last poll (advances this client's
        offset — the auto-commit consumer role)."""
        from .codec import deserialize_array
        msgs = self._client.fetch(self.topic, self.partition, self._offset)
        out = []
        for off, val in msgs[:max_items]:
            out.append(deserialize_array(val)[0])
            self._offset = off + 1
        return out

    def close(self) -> None:
        self._client.close()
