"""Kafka wire-protocol client + dev broker (message formats v0 AND v2).

Reference ``dl4j-streaming/.../streaming/kafka/NDArrayKafkaClient.java``
talks to a real Kafka cluster through the Kafka client library.  This
module implements the actual **Kafka binary protocol** over stdlib
sockets, so the framework can interoperate with a real broker — and ships
``MiniKafkaBroker``, an in-process single-node broker speaking the same
frames, for dev rigs and tests (the LocalMessageBroker/TcpMessageBroker in
``broker.py`` remain the non-Kafka transports).

Two on-wire generations are supported:

- **v0 message sets** (Produce v0 / Fetch v0, CRC32): the legacy format —
  removed from Apache Kafka 4.0, kept here for the mini-broker and old
  clusters.
- **v2 record batches** (Produce v3 / Fetch v4): varint+zigzag records,
  CRC32C (Castagnoli) over the batch, the format every broker since 0.11
  speaks and the only one after Kafka 4.0.  ``KafkaWireClient.negotiate()``
  runs ApiVersions (api_key 18) and picks the newest mutually supported
  produce/fetch pair automatically.

Protocol framing (Kafka protocol guide):
  request  = int32 size | int16 api_key | int16 api_version
             | int32 correlation_id | string client_id | body
  v0 message     = int32 crc | int8 magic(0) | int8 attrs | bytes key
                   | bytes value   (crc = CRC32 over magic..value)
  v2 recordbatch = int64 base_offset | int32 length | int32 leader_epoch
                   | int8 magic(2) | uint32 crc32c | int16 attrs
                   | int32 last_offset_delta | int64 base/max_timestamp
                   | int64 producer_id | int16 producer_epoch
                   | int32 base_sequence | int32 n_records | records
                   (crc32c covers attrs..records)
"""
from __future__ import annotations

import gzip
import socket
import socketserver
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

__all__ = ["KafkaWireClient", "MiniKafkaBroker", "NDArrayKafkaClient"]

_API_PRODUCE = 0
_API_FETCH = 1
_API_LIST_OFFSETS = 2
_API_METADATA = 3
_API_OFFSET_COMMIT = 8
_API_OFFSET_FETCH = 9
_API_FIND_COORDINATOR = 10
_API_VERSIONS = 18

# what the mini-broker advertises via ApiVersions (both generations)
_BROKER_API_VERSIONS = {_API_PRODUCE: (0, 3), _API_FETCH: (0, 4),
                        _API_LIST_OFFSETS: (0, 0), _API_METADATA: (0, 0),
                        _API_OFFSET_COMMIT: (0, 0), _API_OFFSET_FETCH: (0, 0),
                        _API_FIND_COORDINATOR: (0, 0), _API_VERSIONS: (0, 0)}


# ------------------------------------------------------------------- crc32c
def _make_crc32c_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _make_crc32c_table()


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    crc = ~crc & 0xFFFFFFFF
    table = _CRC32C_TABLE            # local ref: hot loop
    for b in data:
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return ~crc & 0xFFFFFFFF


try:                                 # C implementation when available —
    import google_crc32c as _gcrc    # the per-byte loop is ~1000x slower

    def crc32c(data: bytes, crc: int = 0) -> int:
        """CRC-32C (Castagnoli) — the v2 record-batch checksum."""
        return _gcrc.extend(crc, bytes(data))
except Exception:  # pragma: no cover
    crc32c = _crc32c_py


# ------------------------------------------------------------------ varints
def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _varint(n: int) -> bytes:
    """Zigzag varint (Kafka records use zigzag for all varint fields)."""
    u = _zigzag(n)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(data: bytes, off: int) -> Tuple[int, int]:
    shift, u = 0, 0
    while True:
        b = data[off]
        off += 1
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzigzag(u), off
        shift += 7


# ---------------------------------------------------------------- primitives
def _str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def take(self, fmt: str):
        vals = struct.unpack_from(">" + fmt, self.data, self.off)
        self.off += struct.calcsize(">" + fmt)
        return vals if len(vals) > 1 else vals[0]

    def string(self) -> str:
        n = self.take("h")
        if n < 0:            # nullable string: no payload bytes follow
            return ""
        s = self.data[self.off:self.off + n].decode()
        self.off += n
        return s

    def bytes_(self) -> Optional[bytes]:
        n = self.take("i")
        if n < 0:
            return None
        b = self.data[self.off:self.off + n]
        self.off += n
        return b


# ------------------------------------------------------------- message sets
def encode_message(value: bytes, key: Optional[bytes] = None) -> bytes:
    body = struct.pack(">bb", 0, 0) + _bytes(key) + _bytes(value)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return struct.pack(">I", crc) + body


def encode_message_set(values: List[bytes],
                       base_offset: int = 0) -> bytes:
    out = b""
    for i, v in enumerate(values):
        msg = encode_message(v)
        out += struct.pack(">qi", base_offset + i, len(msg)) + msg
    return out


def decode_message_set(data: bytes, _depth: int = 0) -> List[Tuple[int, bytes]]:
    """[(offset, value)] — raises on CRC mismatch (torn/corrupt message).
    gzip wrapper envelopes (legacy v0 compression) unwrap ONE level — real
    producers never nest them, and unbounded recursion on crafted input
    would escape as RecursionError."""
    out: List[Tuple[int, bytes]] = []
    off = 0
    while off + 12 <= len(data):
        offset, size = struct.unpack_from(">qi", data, off)
        off += 12
        if off + size > len(data):
            break  # partial trailing message (Kafka semantics: ignore)
        msg = data[off:off + size]
        off += size
        crc = struct.unpack_from(">I", msg, 0)[0]
        if zlib.crc32(msg[4:]) & 0xFFFFFFFF != crc:
            raise ValueError(f"message at offset {offset}: CRC mismatch")
        r = _Reader(msg)
        r.take("I")          # crc
        _magic, attrs = r.take("bb")
        codec = attrs & 0x07
        if codec not in (_CODEC_NONE, _CODEC_GZIP):
            raise ValueError(
                f"message at offset {offset}: "
                f"{_CODEC_NAMES.get(codec, codec)}-compressed message sets "
                "are not supported (this environment has gzip only)")
        r.bytes_()           # key
        value = r.bytes_()
        if codec == _CODEC_GZIP:
            if _depth:
                raise ValueError(f"message at offset {offset}: nested "
                                 "compression envelopes are not valid")
            inner = _gunzip_or_raise(value or b"",
                                     f"message at offset {offset}")
            out.extend(decode_message_set(inner, _depth=1))
        else:
            out.append((offset, value or b""))
    return out


_MAX_GUNZIP = 1 << 26   # 64 MiB expansion cap — gzip-bomb guard


def _gunzip_or_raise(payload: bytes, what: str) -> bytes:
    """Bounded gzip decompression with torn/corrupt streams normalized to
    the decoder's ValueError contract (EOFError/zlib.error otherwise
    escape the broker's malformed-request guard).  The expansion cap stops
    a small crafted bomb from materializing gigabytes before record
    parsing ever runs."""
    try:
        d = zlib.decompressobj(wbits=31)          # gzip wrapper
        out = d.decompress(payload, _MAX_GUNZIP)
        if d.unconsumed_tail:
            raise ValueError(f"{what}: gzip payload expands past "
                             f"{_MAX_GUNZIP} bytes")
        if not d.eof:
            raise ValueError(f"{what}: corrupt gzip payload "
                             "(truncated stream)")
        return out
    except (EOFError, OSError, zlib.error) as e:
        raise ValueError(f"{what}: corrupt gzip payload ({e})")


# ------------------------------------------------------- v2 record batches
def _encode_record(offset_delta: int, value: bytes,
                   key: Optional[bytes] = None) -> bytes:
    body = (b"\x00"                       # record attributes
            + _varint(0)                  # timestamp delta
            + _varint(offset_delta)
            + (_varint(-1) if key is None
               else _varint(len(key)) + key)
            + _varint(len(value)) + value
            + _varint(0))                 # headers count
    return _varint(len(body)) + body


_CODEC_NONE, _CODEC_GZIP = 0, 1
_CODEC_NAMES = {0: "none", 1: "gzip", 2: "snappy", 3: "lz4", 4: "zstd"}


def encode_record_batch(values: List[bytes], base_offset: int = 0,
                        compression: Optional[str] = None) -> bytes:
    """One v2 RecordBatch holding ``values`` (no producer id).

    ``compression="gzip"`` compresses the records section and sets the
    codec bits in attributes (KIP-98 batch format: the batch header stays
    uncompressed, CRC32C covers attributes..compressed-records)."""
    if compression not in (None, "none", "gzip"):
        raise ValueError(f"unsupported compression {compression!r} "
                         "(stdlib provides gzip; snappy/lz4/zstd are not "
                         "in this environment)")
    records = b"".join(_encode_record(i, v) for i, v in enumerate(values))
    attrs = _CODEC_NONE
    if compression == "gzip":
        records = gzip.compress(records)
        attrs = _CODEC_GZIP
    after_crc = (struct.pack(">hiqqqhii", attrs, len(values) - 1, 0, 0,
                             -1, -1, -1, len(values))
                 + records)
    crc = crc32c(after_crc)
    batch_tail = struct.pack(">ibI", 0, 2, crc) + after_crc
    #                        leader_epoch, magic, crc32c
    return struct.pack(">qi", base_offset, len(batch_tail)) + batch_tail


def decode_record_batches(data: bytes) -> List[Tuple[int, bytes]]:
    """[(offset, value)] from a sequence of v2 RecordBatches — raises on
    CRC32C mismatch; partial trailing batches ignored (Kafka semantics)."""
    out: List[Tuple[int, bytes]] = []
    off = 0
    while off + 12 <= len(data):
        base_offset, length = struct.unpack_from(">qi", data, off)
        if off + 12 + length > len(data):
            break                          # partial trailing batch
        _epoch, magic, crc = struct.unpack_from(">ibI", data, off + 12)
        if magic != 2:
            raise ValueError(f"record batch at {base_offset}: magic {magic}"
                             " (expected 2) — use decode_message_set for v0")
        body_off = off + 12 + 9            # past leader_epoch+magic+crc
        body = data[body_off:off + 12 + length]
        if crc32c(body) != crc:
            raise ValueError(
                f"record batch at {base_offset}: CRC32C mismatch")
        (attrs, _last_delta, _bts, _mts, _pid, _pepoch, _bseq,
         n_records) = struct.unpack_from(">hiqqqhii", body, 0)
        codec = attrs & 0x07
        p = struct.calcsize(">hiqqqhii")
        if codec == _CODEC_GZIP:
            recs = _gunzip_or_raise(
                body[p:], f"record batch at {base_offset}")
            p = 0
        elif codec == _CODEC_NONE:
            recs = body
        else:
            raise ValueError(
                f"record batch at {base_offset}: "
                f"{_CODEC_NAMES.get(codec, codec)}-compressed batches are "
                "not supported (this environment has gzip only)")
        for _ in range(n_records):
            rec_len, p = _read_varint(recs, p)
            end = p + rec_len
            p += 1                         # record attributes
            _ts, p = _read_varint(recs, p)
            odelta, p = _read_varint(recs, p)
            klen, p = _read_varint(recs, p)
            if klen >= 0:
                p += klen
            vlen, p = _read_varint(recs, p)
            value = recs[p:p + vlen] if vlen >= 0 else b""
            out.append((base_offset + odelta, value))
            p = end                        # skip headers
        off += 12 + length
    return out


# ------------------------------------------------------------------ client
class KafkaWireClient:
    """Minimal Kafka client: produce/fetch/metadata against one broker.
    Requests go to the bootstrap broker; ``metadata()`` reports the real
    partition leaders so callers can verify the single-node assumption
    (cross-broker routing itself stays out of scope)."""

    def __init__(self, host: str, port: int, client_id: str = "dl4j-tpu",
                 timeout: float = 10.0):
        self.addr = (host, port)
        self.client_id = client_id
        self.timeout = timeout
        self._corr = 0
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        # wire generation: (0, 0) = legacy message sets; negotiate() raises
        # these to (3, 4) = v2 record batches when the broker allows
        self.produce_version = 0
        self.fetch_version = 0

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, self.timeout)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def _roundtrip(self, api_key: int, body: bytes,
                   api_version: int = 0) -> _Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            req = (struct.pack(">hhi", api_key, api_version, corr)
                   + _str(self.client_id) + body)
            try:
                sock = self._connect()
                sock.sendall(struct.pack(">i", len(req)) + req)
                raw = self._recv_frame(sock)
            except Exception:
                # a timeout / partial read leaves the stream desynced —
                # drop the socket so the next call reconnects cleanly
                self.close()
                raise
        r = _Reader(raw)
        got = r.take("i")
        if got != corr:
            self.close()
            raise IOError(f"correlation id mismatch: sent {corr} got {got}")
        return r

    def _recv_frame(self, sock: socket.socket) -> bytes:
        hdr = self._recv_n(sock, 4)
        (n,) = struct.unpack(">i", hdr)
        return self._recv_n(sock, n)

    @staticmethod
    def _recv_n(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("broker closed the connection")
            buf += chunk
        return buf

    def api_versions(self) -> Dict[int, Tuple[int, int]]:
        """ApiVersions (api_key 18): {api_key: (min, max)} the broker
        supports — the capability handshake every modern client starts with."""
        r = self._roundtrip(_API_VERSIONS, b"")
        err = r.take("h")
        if err:
            raise IOError(f"api_versions error code {err}")
        out: Dict[int, Tuple[int, int]] = {}
        for _ in range(r.take("i")):
            key, lo, hi = r.take("h"), r.take("h"), r.take("h")
            out[key] = (lo, hi)
        return out

    def metadata(self, *topics: str):
        """Metadata v0 (api_key 3): the cluster's brokers and, per topic,
        the leader node of every partition.  No ``topics`` = all topics.
        Returns ``{"brokers": [(node_id, host, port)], "topics": {name:
        {"error": code, "partitions": {partition: leader_node_id}}}}`` —
        the round that lets a client CHECK the bootstrap-is-leader
        assumption instead of assuming it."""
        body = struct.pack(">i", len(topics))
        for t in topics:
            body += _str(t)
        r = self._roundtrip(_API_METADATA, body)
        brokers = []
        for _ in range(r.take("i")):
            node = r.take("i")
            host = r.string()
            brokers.append((node, host, r.take("i")))
        out = {"brokers": brokers, "topics": {}}
        for _ in range(r.take("i")):
            terr = r.take("h")
            name = r.string()
            parts: Dict[int, int] = {}
            for _ in range(r.take("i")):
                _perr, pid, leader = r.take("h"), r.take("i"), r.take("i")
                for _ in range(r.take("i")):
                    r.take("i")               # replicas
                for _ in range(r.take("i")):
                    r.take("i")               # isr
                parts[pid] = leader
            out["topics"][name] = {"error": terr, "partitions": parts}
        return out

    def negotiate(self) -> "KafkaWireClient":
        """Pick the newest mutually supported produce/fetch generation:
        v2 record batches (Produce 3 / Fetch 4) when the broker allows,
        legacy message sets otherwise."""
        versions = self.api_versions()
        if versions.get(_API_PRODUCE, (0, 0))[1] >= 3:
            self.produce_version = 3
        if versions.get(_API_FETCH, (0, 0))[1] >= 4:
            self.fetch_version = 4
        return self

    def produce(self, topic: str, partition: int, values: List[bytes],
                compression: Optional[str] = None) -> int:
        """Append messages; returns the base offset assigned.  Encodes a v2
        RecordBatch after ``negotiate()`` (produce_version 3), a v0 message
        set otherwise.  ``compression="gzip"`` compresses the v2 records
        section (legacy message sets stay uncompressed — use the modern
        path for compressed payloads)."""
        v3 = self.produce_version >= 3
        if compression not in (None, "none") and not v3:
            raise ValueError("compression requires the v2 record-batch "
                             "path — call negotiate() first")
        mset = encode_record_batch(values, compression=compression) if v3 \
            else encode_message_set(values)
        body = (struct.pack(">h", -1) if v3 else b"")  # transactional_id
        body += (struct.pack(">hi", 1, int(self.timeout * 1000))  # acks=1
                 + struct.pack(">i", 1) + _str(topic)
                 + struct.pack(">i", 1)
                 + struct.pack(">i", partition)
                 + struct.pack(">i", len(mset)) + mset)
        r = self._roundtrip(_API_PRODUCE, body,
                            api_version=self.produce_version)
        n_topics = r.take("i")
        assert n_topics == 1
        r.string()
        n_parts = r.take("i")
        assert n_parts == 1
        _part, err, base = r.take("i"), r.take("h"), r.take("q")
        if err:
            raise IOError(f"produce error code {err}")
        return base

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20) -> List[Tuple[int, bytes]]:
        """[(offset, value)] from ``offset`` onward (may be empty).
        Decodes v2 record batches after ``negotiate()`` (fetch_version 4),
        v0 message sets otherwise."""
        v4 = self.fetch_version >= 4
        body = struct.pack(">iii", -1, 100, 0)
        if v4:
            body += struct.pack(">ib", max_bytes, 0)  # max_bytes, read_uncmt
        body += (struct.pack(">i", 1) + _str(topic)
                 + struct.pack(">i", 1)
                 + struct.pack(">iqi", partition, offset, max_bytes))
        r = self._roundtrip(_API_FETCH, body, api_version=self.fetch_version)
        if v4:
            r.take("i")                    # throttle_time_ms
        n_topics = r.take("i")
        assert n_topics == 1
        r.string()
        n_parts = r.take("i")
        assert n_parts == 1
        _part, err, _hw = r.take("i"), r.take("h"), r.take("q")
        if v4:
            r.take("q")                    # last_stable_offset
            n_aborted = r.take("i")
            for _ in range(max(n_aborted, 0)):
                r.take("qq")               # producer_id, first_offset
        if err:
            raise IOError(f"fetch error code {err}")
        size = r.take("i")
        mset = r.data[r.off:r.off + size]
        # dispatch on the stored magic byte, not the request version: real
        # brokers return whatever format the log holds (old segments stay
        # magic 0/1 even under Fetch v4)
        records = (decode_record_batches(mset)
                   if len(mset) > 16 and mset[16] == 2
                   else decode_message_set(mset))
        # real brokers return whole batches (indivisible on disk); drop the
        # records below the requested offset so consumers never see repeats
        return [(o, v) for o, v in records if o >= offset]

    # -- consumer-group offset management ---------------------------------
    # The reference consumes as a managed group (groupId in the Camel route
    # URI, DL4jServeRouteBuilder.java:55) so a restarted consumer resumes at
    # its committed offset.  These four rounds are that capability on the
    # wire: FindCoordinator locates the group's offset store, OffsetCommit/
    # OffsetFetch persist and recover positions, ListOffsets resolves the
    # log's earliest/latest watermarks for consumers with no commit yet.

    def find_coordinator(self, group_id: str) -> Tuple[int, str, int]:
        """FindCoordinator v0 (api_key 10): ``(node_id, host, port)`` of the
        broker coordinating ``group_id``'s offsets.  Single-node rigs always
        get the bootstrap broker back, but going through the round keeps the
        client correct against real clusters."""
        r = self._roundtrip(_API_FIND_COORDINATOR, _str(group_id))
        err = r.take("h")
        if err:
            raise IOError(f"find_coordinator error code {err}")
        node = r.take("i")
        host = r.string()
        return node, host, r.take("i")

    def offset_commit(self, group_id: str, topic: str, partition: int,
                      offset: int, metadata: str = "") -> None:
        """OffsetCommit v0 (api_key 8): durably record ``group_id``'s next
        read position for (topic, partition)."""
        body = (_str(group_id)
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iq", partition, offset) + _str(metadata))
        r = self._roundtrip(_API_OFFSET_COMMIT, body)
        n_topics = r.take("i")
        assert n_topics == 1
        r.string()
        n_parts = r.take("i")
        assert n_parts == 1
        _part, err = r.take("i"), r.take("h")
        if err:
            raise IOError(f"offset_commit error code {err}")

    def offset_fetch(self, group_id: str, topic: str,
                     partition: int) -> int:
        """OffsetFetch v0 (api_key 9): the committed offset for
        ``group_id`` on (topic, partition), or -1 when the group has never
        committed there (Kafka's no-offset sentinel)."""
        body = (_str(group_id)
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1) + struct.pack(">i", partition))
        r = self._roundtrip(_API_OFFSET_FETCH, body)
        n_topics = r.take("i")
        assert n_topics == 1
        r.string()
        n_parts = r.take("i")
        assert n_parts == 1
        _part, off = r.take("i"), r.take("q")
        r.string()                       # metadata
        err = r.take("h")
        if err:
            raise IOError(f"offset_fetch error code {err}")
        return off

    def list_offsets(self, topic: str, partition: int,
                     timestamp: int = -1) -> int:
        """ListOffsets v0 (api_key 2): the log's latest offset (timestamp
        -1, the high watermark = next offset to be assigned) or earliest
        (timestamp -2).  The round a group-less or never-committed consumer
        uses to choose its starting position."""
        body = (struct.pack(">i", -1)    # replica_id
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iqi", partition, timestamp, 1))
        r = self._roundtrip(_API_LIST_OFFSETS, body)
        n_topics = r.take("i")
        assert n_topics == 1
        r.string()
        n_parts = r.take("i")
        assert n_parts == 1
        _part, err = r.take("i"), r.take("h")
        if err:
            raise IOError(f"list_offsets error code {err}")
        n_offsets = r.take("i")
        offsets = [r.take("q") for _ in range(n_offsets)]
        if not offsets:
            raise IOError("list_offsets returned no offsets")
        return offsets[0]


# ------------------------------------------------------------------ broker
class MiniKafkaBroker:
    """Single-node in-process broker speaking Produce v0 / Fetch v0 — the
    dev/test stand-in for a real cluster (role of an embedded Kafka in the
    reference's test rigs).  Logs live in memory per (topic, partition)."""

    def __init__(self, port: int = 0):
        self._logs: Dict[Tuple[str, int], List[bytes]] = {}
        # consumer-group offset store: (group, topic, partition) ->
        # (offset, metadata) — the __consumer_offsets topic's role
        self._offsets: Dict[Tuple[str, str, int], Tuple[int, str]] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        raw = self._frame()
                        if raw is None:
                            return
                        try:
                            resp = outer._dispatch(raw)
                        except (ValueError, struct.error, IndexError):
                            # malformed/corrupt request: close the
                            # connection cleanly instead of a traceback
                            return
                        self.request.sendall(
                            struct.pack(">i", len(resp)) + resp)
                except (ConnectionError, OSError):
                    return

            def _frame(self):
                try:
                    hdr = KafkaWireClient._recv_n(self.request, 4)
                except ConnectionError:
                    return None
                (n,) = struct.unpack(">i", hdr)
                return KafkaWireClient._recv_n(self.request, n)

        self._server = socketserver.ThreadingTCPServer(("127.0.0.1", port),
                                                       Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "MiniKafkaBroker":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- request dispatch -------------------------------------------------
    def _dispatch(self, raw: bytes) -> bytes:
        r = _Reader(raw)
        api_key, ver, corr = r.take("h"), r.take("h"), r.take("i")
        r.string()  # client_id
        if api_key == _API_PRODUCE:
            return struct.pack(">i", corr) + self._produce(r, ver)
        if api_key == _API_FETCH:
            return struct.pack(">i", corr) + self._fetch(r, ver)
        if api_key == _API_METADATA:
            return struct.pack(">i", corr) + self._metadata(r, ver)
        if api_key == _API_LIST_OFFSETS:
            return struct.pack(">i", corr) + self._list_offsets(r, ver)
        if api_key == _API_OFFSET_COMMIT:
            return struct.pack(">i", corr) + self._offset_commit(r, ver)
        if api_key == _API_OFFSET_FETCH:
            return struct.pack(">i", corr) + self._offset_fetch(r, ver)
        if api_key == _API_FIND_COORDINATOR:
            return struct.pack(">i", corr) + self._find_coordinator(r, ver)
        if api_key == _API_VERSIONS:
            return struct.pack(">i", corr) + self._api_versions()
        return struct.pack(">i", corr)

    def _find_coordinator(self, r: _Reader, ver: int) -> bytes:
        """FindCoordinator v0: a single-node broker coordinates every
        group itself."""
        if ver != 0:
            raise ValueError(f"find_coordinator v{ver} not supported")
        r.string()                                   # group_id
        host, port = self._server.server_address
        return (struct.pack(">h", 0) + struct.pack(">i", 0)
                + _str(host) + struct.pack(">i", port))

    def _offset_commit(self, r: _Reader, ver: int) -> bytes:
        if ver != 0:
            raise ValueError(f"offset_commit v{ver} not supported")
        group = r.string()
        out = b""
        n_topics = r.take("i")
        out += struct.pack(">i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            out += _str(topic)
            n_parts = r.take("i")
            out += struct.pack(">i", n_parts)
            for _ in range(n_parts):
                part, offset = r.take("i"), r.take("q")
                meta = r.string()
                with self._lock:
                    self._offsets[(group, topic, part)] = (offset, meta)
                out += struct.pack(">ih", part, 0)
        return out

    def _offset_fetch(self, r: _Reader, ver: int) -> bytes:
        if ver != 0:
            raise ValueError(f"offset_fetch v{ver} not supported")
        group = r.string()
        out = b""
        n_topics = r.take("i")
        out += struct.pack(">i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            out += _str(topic)
            n_parts = r.take("i")
            out += struct.pack(">i", n_parts)
            for _ in range(n_parts):
                part = r.take("i")
                with self._lock:
                    offset, meta = self._offsets.get(
                        (group, topic, part), (-1, ""))
                # no committed offset = offset -1, error 0 (Kafka contract)
                out += struct.pack(">iq", part, offset) + _str(meta)
                out += struct.pack(">h", 0)
        return out

    def _list_offsets(self, r: _Reader, ver: int) -> bytes:
        if ver != 0:
            raise ValueError(f"list_offsets v{ver} not supported")
        r.take("i")                                  # replica_id
        out = b""
        n_topics = r.take("i")
        out += struct.pack(">i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            out += _str(topic)
            n_parts = r.take("i")
            out += struct.pack(">i", n_parts)
            for _ in range(n_parts):
                part, ts = r.take("i"), r.take("q")
                r.take("i")                          # max_num_offsets
                with self._lock:
                    known = (topic, part) in self._logs
                    high = len(self._logs.get((topic, part), ()))
                if not known:
                    # error 3: UNKNOWN_TOPIC_OR_PARTITION, empty offsets
                    out += struct.pack(">ihi", part, 3, 0)
                    continue
                offset = 0 if ts == -2 else high     # -2 earliest, -1 latest
                out += struct.pack(">ihi", part, 0, 1)
                out += struct.pack(">q", offset)
        return out

    def _metadata(self, r: _Reader, ver: int) -> bytes:
        """Metadata v0: this single node is broker 0 and leads every
        partition it has a log for; unknown requested topics answer
        error 3 (UNKNOWN_TOPIC_OR_PARTITION) rather than auto-creating.
        v1+ layouts differ (controller_id, racks) — close cleanly instead
        of serving a v0 body a v1 parser would silently desync on."""
        if ver != 0:
            raise ValueError(f"metadata v{ver} not supported")
        wanted = [r.string() for _ in range(r.take("i"))]
        host, port = self._server.server_address
        out = struct.pack(">i", 1)                      # one broker
        out += struct.pack(">i", 0) + _str(host) + struct.pack(">i", port)
        with self._lock:
            known: Dict[str, List[int]] = {}
            for (topic, part) in self._logs:
                known.setdefault(topic, []).append(part)
        names = wanted or sorted(known)
        out += struct.pack(">i", len(names))
        for name in names:
            parts = sorted(known.get(name, ()))
            err = 0 if parts else 3     # UNKNOWN_TOPIC_OR_PARTITION
            out += struct.pack(">h", err) + _str(name)
            out += struct.pack(">i", len(parts))
            for pid in parts:
                out += struct.pack(">hii", 0, pid, 0)   # leader: node 0
                out += struct.pack(">ii", 1, 0)         # replicas [0]
                out += struct.pack(">ii", 1, 0)         # isr [0]
        return out

    @staticmethod
    def _api_versions() -> bytes:
        out = struct.pack(">hi", 0, len(_BROKER_API_VERSIONS))
        for key, (lo, hi) in sorted(_BROKER_API_VERSIONS.items()):
            out += struct.pack(">hhh", key, lo, hi)
        return out

    def _produce(self, r: _Reader, ver: int) -> bytes:
        if ver >= 3:
            r.string()   # transactional_id (nullable string)
        r.take("h")  # acks
        r.take("i")  # timeout
        out = b""
        n_topics = r.take("i")
        out += struct.pack(">i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            out += _str(topic)
            n_parts = r.take("i")
            out += struct.pack(">i", n_parts)
            for _ in range(n_parts):
                part = r.take("i")
                size = r.take("i")
                mset = r.data[r.off:r.off + size]
                r.off += size
                # sniff the generation from the magic byte (offset 16 in a
                # v2 batch; offset 16 in a v0 entry is inside the message) —
                # Kafka brokers key on magic the same way
                magic = mset[16] if len(mset) > 16 else 0
                values = ([v for _, v in decode_record_batches(mset)]
                          if magic == 2
                          else [v for _, v in decode_message_set(mset)])
                with self._lock:
                    log = self._logs.setdefault((topic, part), [])
                    base = len(log)
                    log.extend(values)
                out += struct.pack(">ihq", part, 0, base)
                if ver >= 2:
                    out += struct.pack(">q", -1)   # log_append_time
        if ver >= 1:
            out += struct.pack(">i", 0)            # throttle_time_ms
        return out

    def _fetch(self, r: _Reader, ver: int) -> bytes:
        r.take("i")  # replica_id
        r.take("i")  # max_wait
        r.take("i")  # min_bytes
        if ver >= 3:
            r.take("i")  # top-level max_bytes
        if ver >= 4:
            r.take("b")  # isolation_level
        out = struct.pack(">i", 0) if ver >= 1 else b""   # throttle_time
        n_topics = r.take("i")
        out += struct.pack(">i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            out += _str(topic)
            n_parts = r.take("i")
            out += struct.pack(">i", n_parts)
            for _ in range(n_parts):
                part, offset, max_bytes = r.take("i"), r.take("q"), r.take("i")
                with self._lock:
                    log = self._logs.get((topic, part), [])
                    high = len(log)
                    tail = log[offset:] if 0 <= offset <= high else None
                if tail is None:     # Kafka error 1: OFFSET_OUT_OF_RANGE
                    out += struct.pack(">ihq", part, 1, high)
                    if ver >= 4:
                        out += struct.pack(">qi", high, 0)
                    out += struct.pack(">i", 0)
                    continue
                chunk: List[bytes] = []
                total = 0
                for v in tail:
                    total += len(v) + 70
                    if chunk and total > max_bytes:
                        break
                    chunk.append(v)
                mset = (encode_record_batch(chunk, base_offset=offset)
                        if ver >= 4 and chunk
                        else encode_message_set(chunk, base_offset=offset)
                        if chunk else b"")
                out += struct.pack(">ihq", part, 0, high)
                if ver >= 4:
                    out += struct.pack(">qi", high, 0)  # lso, aborted_txns
                out += struct.pack(">i", len(mset)) + mset
        return out


# ------------------------------------------------------- NDArray transport
class NDArrayKafkaClient:
    """Publish/consume NDArrays over the Kafka wire protocol (reference
    ``NDArrayKafkaClient.java``): arrays ride as codec-serialized message
    values; consumption is offset-tracked per client.

    With ``group_id`` the client consumes as a managed group member (the
    reference's ``kafka:...&groupId=...`` route,
    ``DL4jServeRouteBuilder.java:55``): the first poll resumes from the
    group's committed offset (or the log's earliest when the group has
    never committed), and every poll commits the new position after its
    records are returned — so a restarted consumer continues exactly
    where the previous incarnation's last completed poll left off."""

    def __init__(self, host: str, port: int, topic: str,
                 partition: int = 0, negotiate: bool = True,
                 group_id: Optional[str] = None):
        self._client = KafkaWireClient(host, port)
        self.topic = topic
        self.partition = partition
        self.group_id = group_id
        self._offset: Optional[int] = None if group_id else 0
        # lazy: no I/O in the constructor (broker may not be up yet);
        # first use runs ApiVersions and falls back to the v0 generation
        # for brokers that don't speak it (pre-0.10 closes the connection)
        self._want_negotiate = negotiate

    def _ensure_negotiated(self) -> None:
        if not self._want_negotiate:
            return
        self._want_negotiate = False
        try:
            self._client.negotiate()
        except Exception:
            self._client.close()     # resync; stay on the v0 generation

    def publish(self, arr) -> int:
        from .codec import serialize_array
        self._ensure_negotiated()
        return self._client.produce(self.topic, self.partition,
                                    [serialize_array(arr)])

    def publish_all(self, arrays) -> int:
        from .codec import serialize_array
        self._ensure_negotiated()
        return self._client.produce(self.topic, self.partition,
                                    [serialize_array(a) for a in arrays])

    def _resolve_start(self) -> int:
        """Group members resume at the committed offset; a group with no
        commit yet starts at the log's earliest (auto.offset.reset=earliest,
        the reference route's implicit default for training data — losing
        the head of the stream would silently skew the model)."""
        committed = self._client.offset_fetch(self.group_id, self.topic,
                                              self.partition)
        if committed >= 0:
            return committed
        try:
            return self._client.list_offsets(self.topic, self.partition,
                                             timestamp=-2)
        except IOError:
            return 0                     # topic not created yet

    def poll(self, max_items: int = 64):
        """Arrays appended since the last poll.  Group members commit the
        advanced position to the coordinator after the batch is decoded
        (per-poll auto-commit: a consumer killed between polls restarts
        with no loss and no duplication); group-less clients track the
        offset in memory only."""
        from .codec import deserialize_array
        self._ensure_negotiated()
        if self._offset is None:
            self._offset = self._resolve_start()
        msgs = self._client.fetch(self.topic, self.partition, self._offset)
        out = []
        for off, val in msgs[:max_items]:
            out.append(deserialize_array(val)[0])
            self._offset = off + 1
        if self.group_id is not None and out:
            self.commit()
        return out

    def commit(self) -> None:
        """Commit the current position for this client's group."""
        if self.group_id is None:
            raise ValueError("commit() requires a group_id")
        if self._offset is not None:
            self._client.offset_commit(self.group_id, self.topic,
                                       self.partition, self._offset)

    def close(self) -> None:
        self._client.close()
