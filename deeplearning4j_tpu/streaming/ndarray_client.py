"""NDArray publisher/consumer over a broker.

Reference ``dl4j-streaming/.../kafka/{NDArrayPublisher,NDArrayConsumer,
NDArrayKafkaClient}.java`` — typed array pub/sub riding the codec frames.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .codec import deserialize_array, serialize_array

__all__ = ["NDArrayPublisher", "NDArrayConsumer"]


class NDArrayPublisher:
    def __init__(self, broker, topic: str):
        self.broker = broker
        self.topic = topic

    def publish(self, arr) -> None:
        self.broker.publish(self.topic, serialize_array(arr))

    def publish_all(self, arrays) -> None:
        for a in arrays:
            self.publish(a)


class NDArrayConsumer:
    def __init__(self, broker, topic: str):
        self.broker = broker
        self.topic = topic
        self._sub = broker.subscribe(topic)

    def get_array(self, timeout: Optional[float] = 5.0
                  ) -> Optional[np.ndarray]:
        payload = self._sub.poll(timeout=timeout)
        if payload is None:
            return None
        arr, _ = deserialize_array(payload)
        return arr

    def get_arrays(self, n: int, timeout: Optional[float] = 5.0
                   ) -> List[np.ndarray]:
        out = []
        for _ in range(n):
            a = self.get_array(timeout=timeout)
            if a is None:
                break
            out.append(a)
        return out

    def close(self) -> None:
        if hasattr(self._sub, "close"):
            self._sub.close()
        elif hasattr(self.broker, "unsubscribe"):
            self.broker.unsubscribe(self.topic, self._sub)
