"""Streaming: pub/sub of serialized arrays/DataSets + prediction routes.

TPU-native re-design of reference ``dl4j-streaming`` (SURVEY.md §2.4):
``NDArrayKafkaClient``/``NDArrayPublisher``/``NDArrayConsumer`` and the
Camel routes (``CamelKafkaRouteBuilder``, ``DL4jServeRouteBuilder``).  Kafka
+ Camel are replaced by a broker abstraction with an in-process
implementation and a TCP transport — same publish/subscribe/route API, no
external infrastructure.
"""
from .broker import LocalMessageBroker, TcpMessageBroker
from .codec import (deserialize_array, deserialize_dataset, serialize_array,
                    serialize_dataset)
from .ndarray_client import NDArrayConsumer, NDArrayPublisher
from .routes import ServeRoute

__all__ = ["LocalMessageBroker", "TcpMessageBroker", "NDArrayPublisher",
           "NDArrayConsumer", "ServeRoute", "serialize_array",
           "deserialize_array", "serialize_dataset", "deserialize_dataset"]
