"""Prediction routes: features topic → model → predictions topic.

Reference ``dl4j-streaming/.../routes/DL4jServeRouteBuilder.java`` (Camel
route consuming Kafka records, running the net, re-publishing results) —
here a background worker thread with clean shutdown; batching happens
upstream (ParallelInference) when throughput matters.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

import numpy as np

from .codec import deserialize_array, serialize_array

__all__ = ["ServeRoute"]


class ServeRoute:
    """Consume arrays from ``in_topic``, apply ``model.output`` (or a bare
    callable), publish results to ``out_topic``."""

    def __init__(self, broker, model, in_topic: str, out_topic: str,
                 transform: Optional[Callable] = None):
        self.broker = broker
        self.in_topic = in_topic
        self.out_topic = out_topic
        self._predict = model if callable(model) else model.output
        self.transform = transform
        self._sub = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.processed = 0

    def start(self) -> "ServeRoute":
        self._sub = self.broker.subscribe(self.in_topic)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            payload = self._sub.poll(timeout=0.2)
            if payload is None:
                continue
            arr, _ = deserialize_array(payload)
            if self.transform is not None:
                arr = self.transform(arr)
            pred = np.asarray(self._predict(arr))
            self.broker.publish(self.out_topic, serialize_array(pred))
            self.processed += 1

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._sub is not None and hasattr(self._sub, "close"):
            self._sub.close()
