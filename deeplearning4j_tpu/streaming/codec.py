"""Wire codec for arrays and DataSets.

Reference serializes NDArrays base64-inside-Kafka-JSON
(``dl4j-streaming/.../kafka/NDArrayKafkaClient.java`` via RecordConverter);
here: a compact self-describing binary frame (magic, dtype, rank, dims,
raw little-endian data) — zero-copy on decode via ``np.frombuffer``.
"""
from __future__ import annotations

import struct
from typing import Optional, Tuple

import numpy as np

_MAGIC = b"DTA1"
_DTYPES = ["float32", "float64", "int32", "int64", "uint8", "bool"]


def serialize_array(arr) -> bytes:
    a = np.ascontiguousarray(np.asarray(arr))
    if a.dtype.name not in _DTYPES:
        raise ValueError(f"unsupported wire dtype {a.dtype}")
    head = _MAGIC + struct.pack(
        "<BB", _DTYPES.index(a.dtype.name), a.ndim)
    head += struct.pack(f"<{a.ndim}q", *a.shape)
    return head + a.tobytes()


def deserialize_array(data: bytes, offset: int = 0
                      ) -> Tuple[np.ndarray, int]:
    """Returns (array, next_offset) so frames can be concatenated."""
    if data[offset:offset + 4] != _MAGIC:
        raise ValueError("bad array frame magic")
    dt_idx, ndim = struct.unpack_from("<BB", data, offset + 4)
    dims = struct.unpack_from(f"<{ndim}q", data, offset + 6)
    dtype = np.dtype(_DTYPES[dt_idx])
    start = offset + 6 + 8 * ndim
    nbytes = int(np.prod(dims)) * dtype.itemsize if ndim else dtype.itemsize
    arr = np.frombuffer(data, dtype, count=int(np.prod(dims)) if ndim else 1,
                        offset=start).reshape(dims)
    return arr, start + nbytes


def serialize_dataset(features, labels=None, features_mask=None,
                      labels_mask=None) -> bytes:
    """DataSet frame: presence bitmap + up to four array frames (the
    reference's DataSet-over-Kafka role)."""
    parts = [features, labels, features_mask, labels_mask]
    bitmap = sum(1 << i for i, p in enumerate(parts) if p is not None)
    out = b"DSB1" + struct.pack("<B", bitmap)
    for p in parts:
        if p is not None:
            out += serialize_array(p)
    return out


def deserialize_dataset(data: bytes):
    """Returns (features, labels, features_mask, labels_mask)."""
    if data[:4] != b"DSB1":
        raise ValueError("bad dataset frame magic")
    bitmap = data[4]
    off = 5
    parts = []
    for i in range(4):
        if bitmap & (1 << i):
            arr, off = deserialize_array(data, off)
            parts.append(arr)
        else:
            parts.append(None)
    return tuple(parts)
